"""Continuous-batching serving engine: ONE jitted steady-state decode
step over a fixed slot array (ISSUE 4 tentpole).

The reference had no inference story beyond a per-sentence Python
``translate`` loop (``examples/seq2seq/seq2seq.py`` (dagger); SURVEY.md:
"no scheduler layer, no serving layer"), and this repo's own
:func:`chainermn_tpu.models.transformer.generate` still serves one
prompt batch at a time — the chip idles between requests and every
ragged batch re-pads into a fresh scan. This engine applies PR 3's
discipline (hide cost behind a FIXED compiled program, account
honestly) to serving:

- **Slot array.** ``num_slots`` requests decode in one fused program.
  Join/leave mutate HOST-side metadata only (positions, free list,
  block tables); the compiled step never changes — the suite pins
  exactly one compilation across occupancy churn.
- **Prefill/decode split.** Prompts run through a separate bucketed
  prefill program (``datasets/bucketing.py`` ladder), writing the whole
  prompt's KV in one pass; compile count is bounded by
  ``len(prefill_buckets)``, not prompt-length spread.
- **Paged KV cache.** ``decode_impl='paged'`` stores KV in a shared
  block pool with per-slot tables (:mod:`chainermn_tpu.ops.paged_kv`,
  :mod:`chainermn_tpu.serving.kv_blocks`): HBM scales with resident
  tokens, and the cache is DONATED through the decode jit so occupancy
  changes never reallocate. ``'dense'`` keeps the classic
  ``[slots, max_len]`` ring; ``'auto'`` resolves through the tuning
  registry (decisions ``decode_impl`` / ``kv_block_size``, seeded
  offline from bench's ``serving`` rows).
- **Tensor-parallel decode.** Pass a ``mesh`` with a ``'model'`` axis:
  weights are head/width-sharded through
  :mod:`chainermn_tpu.parallel.tensor`'s adjoint pairs — exactly one
  psum per column→row pair (2 per layer), zero collectives in the
  paged-cache bookkeeping (both pinned structurally in the suite).

Token-stream guarantee: engine output for a request equals the
:func:`generate` stream for the same prompt, regardless of what other
requests share the slot array (per-row attention never mixes rows; the
equivalence test drives staggered joins/leaves). At temperature 0 that
is greedy determinism; at temperature > 0 it holds because sampling
keys are COUNTER-BASED (:func:`~chainermn_tpu.models.transformer.
stream_sample_keys`): token ``i`` of a request with seed ``s`` draws
with ``fold_in(fold_in(base_key, s), i)`` — no consumed split chain, so
the draw is invariant to which program (monolithic, chunked,
seq-parallel, speculative) or which replica emitted it
(docs/serving.md "Sampling").
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import numpy as np

from chainermn_tpu.datasets.bucketing import DEFAULT_BUCKETS, bucket_length
from chainermn_tpu.serving.kv_blocks import (
    BlockAllocator,
    PrefixCache,
    default_num_blocks,
    init_serving_cache,
)

#: tuning-registry candidates for the serving decisions.
DECODE_IMPLS = ("dense", "paged")
KV_BLOCK_SIZES = ("16", "32", "64", "128")
#: slot-decode attention impl (ISSUE 19): 'xla' = scatter → dense-view
#: gather → einsum attend; 'fused' = the flash-decoding Pallas kernel
#: (:mod:`chainermn_tpu.ops.paged_decode`) — one HBM pass over the live
#: blocks, table-indexed in-kernel gather, no dense view. Table default
#: 'xla': the kernel must EARN adoption through bench's
#: ``serving_decode_kernel`` rows (spread-gated); a Pallas without
#: scalar-prefetch grid specs forces 'xla' with ``forced:jax-compat``.
DECODE_ATTEND_IMPLS = ("xla", "fused")
#: speculation lengths the ``spec_tokens`` decision chooses among
#: (ISSUE 5): 0 = plain one-token decode; K > 0 = draft-and-verify with
#: K drafted tokens per slot per tick.
SPEC_TOKENS = ("0", "2", "4", "8")
#: cross-request prefix sharing over the paged pool (ISSUE 7): the
#: radix-trie block cache + copy-on-write; paged-only (dense rows are
#: slot-private by layout).
PREFIX_CACHE = ("off", "on")
#: minimum matched FULL blocks before a trie hit is adopted — below it
#: the join prefills from scratch (a one-block hit saves little prefill
#: but still pays table/refcount churn and pins blocks in the cache).
MIN_SHARED_BLOCKS = ("1", "2", "4")
#: chunked-prefill widths the ``prefill_chunk`` decision chooses among
#: (ISSUE 11): 0 = monolithic bucketed prefill (``prefill_join``); C > 0
#: = admitted prompts write C tokens of KV per tick INSIDE the mixed
#: step while the remaining active slots decode — the long-prompt
#: TPOT-freeze fix, priced by the bench's bursty goodput-under-SLO rows.
PREFILL_CHUNKS = ("0", "16", "32", "64", "128")
#: sequence-parallel long-prompt prefill over the replica's ``model``
#: partition (ISSUE 13): 'off' = the TP (or single-device) monolithic
#: prefill; 'on' = a cache-miss prompt's forward is SHARDED over the
#: mesh's 'model' axis — each shard runs its token slice through the
#: ring/Ulysses attention (decision ``seq_attn_impl``, shared with the
#: ParallelPlan's seq axis), the sown per-layer K/V is resharded
#: heads<->sequence by one all_to_all into exactly the TP cache layout,
#: and the assembled block chain is handed to the existing paged/dense
#: decode path. Streams stay bit-identical to sequential ``generate``.
PREFILL_SEQ_PARALLEL = ("off", "on")
#: multi-tenant adapter application (ISSUE 14): 'gather' = the one
#: compiled program gathers each slot's A/B rows from the bank's
#: stacks and adds the rank-r delta in-forward (mixed-tenant traffic;
#: tenant churn is host metadata only); 'merged' = the tenant's delta
#: is folded into the base weights at construction (zero per-step
#: delta cost — single-tenant-dominant traffic; other tenants refused
#: loudly). Table default 'gather': merging must EARN adoption through
#: the bench's ``serving_tenants`` rows. ONE definition, in
#: adapters.py — the ctor's validation and the tuning candidates must
#: never disagree.
from chainermn_tpu.serving.adapters import ADAPTER_IMPLS  # noqa: E402


def resolve_adapter_impl(d_model: int, num_heads: int, max_len: int) -> str:
    """Resolve ``adapter_impl`` ('gather' | 'merged') via the registry
    (decision ``adapter_impl``, same key as the other serving
    decisions; bench's ``serving_tenants`` phase measures both arms
    under Zipf-skewed multi-tenant traffic and seeds it)."""
    from chainermn_tpu import tuning

    return tuning.choice(
        "adapter_impl", ADAPTER_IMPLS,
        serving_decision_key(d_model, num_heads, max_len),
    )


def _gather_adapter_rows(stacks, rows):
    """Per-slot adapter gather (ISSUE 14): index every layer's stacked
    ``[capacity, ...]`` A/B pair by the ``[B]`` tenant-row vector —
    the ONE in-program step that turns host tenant metadata into the
    forward's per-row deltas. Runs inside the jitted programs; a row
    of 0 gathers the null adapter (exact zeros)."""
    return [
        {tgt: (A[rows], B[rows]) for tgt, (A, B) in layer.items()}
        for layer in stacks
    ]


def serving_decision_key(d_model: int, num_heads: int, max_len: int,
                         device_kind: Optional[str] = None) -> str:
    """The ONE key both serving decisions resolve under —
    device_kind x model-shape bucket x max-seq bucket. bench's
    ``serving`` phase records the same dims (``serving_model_shape``)
    so offline seeding rebuilds this key exactly."""
    from chainermn_tpu import tuning

    return tuning.decision_key(
        device_kind, shape=(d_model, num_heads, max_len), dtype="decode"
    )


def resolve_decode_impl(d_model: int, num_heads: int, max_len: int) -> str:
    """Resolve ``decode_impl`` ('dense' | 'paged') via the registry."""
    from chainermn_tpu import tuning

    return tuning.choice(
        "decode_impl", DECODE_IMPLS,
        serving_decision_key(d_model, num_heads, max_len),
    )


def resolve_kv_block_size(d_model: int, num_heads: int, max_len: int) -> int:
    """Resolve the paged-pool block size via the registry."""
    from chainermn_tpu import tuning

    return int(tuning.choice(
        "kv_block_size", KV_BLOCK_SIZES,
        serving_decision_key(d_model, num_heads, max_len),
    ))


def resolve_decode_attend_impl(d_model: int, num_heads: int,
                               max_len: int) -> str:
    """Resolve ``decode_attend_impl`` ('xla' | 'fused') via the registry
    (same key as the other serving decisions; bench's
    ``serving_decode_kernel`` phase measures both attends per shape and
    seeds it — table default 'xla', the kernel earns adoption)."""
    from chainermn_tpu import tuning

    return tuning.choice(
        "decode_attend_impl", DECODE_ATTEND_IMPLS,
        serving_decision_key(d_model, num_heads, max_len),
    )


def resolve_spec_tokens(d_model: int, num_heads: int, max_len: int) -> int:
    """Resolve the speculation length K via the registry (decision
    ``spec_tokens``, same key as the other serving decisions — bench's
    ``serving`` phase measures spec-vs-plain per shape and seeds it)."""
    from chainermn_tpu import tuning

    return int(tuning.choice(
        "spec_tokens", SPEC_TOKENS,
        serving_decision_key(d_model, num_heads, max_len),
    ))


def resolve_prefix_cache(d_model: int, num_heads: int, max_len: int) -> str:
    """Resolve ``prefix_cache`` ('off' | 'on') via the registry."""
    from chainermn_tpu import tuning

    return tuning.choice(
        "prefix_cache", PREFIX_CACHE,
        serving_decision_key(d_model, num_heads, max_len),
    )


def resolve_min_shared_blocks(d_model: int, num_heads: int,
                              max_len: int) -> int:
    """Resolve the trie-hit adoption threshold via the registry."""
    from chainermn_tpu import tuning

    return int(tuning.choice(
        "min_shared_blocks", MIN_SHARED_BLOCKS,
        serving_decision_key(d_model, num_heads, max_len),
    ))


def resolve_prefill_chunk(d_model: int, num_heads: int,
                          max_len: int) -> int:
    """Resolve the chunked-prefill width via the registry (decision
    ``prefill_chunk``, same key as the other serving decisions — table
    default 0: chunking must EARN adoption through the bench's bursty
    goodput-under-SLO rows, the spec_tokens precedent)."""
    from chainermn_tpu import tuning

    return int(tuning.choice(
        "prefill_chunk", PREFILL_CHUNKS,
        serving_decision_key(d_model, num_heads, max_len),
    ))


def resolve_prefill_seq_parallel(d_model: int, num_heads: int,
                                 max_len: int) -> str:
    """Resolve ``prefill_seq_parallel`` ('off' | 'on') via the registry
    (decision ``prefill_seq_parallel``, same key as the other serving
    decisions; table default 'off' — the wide prefill must EARN adoption
    through bench's ``seq_parallel`` long-prompt TTFT rows)."""
    from chainermn_tpu import tuning

    return tuning.choice(
        "prefill_seq_parallel", PREFILL_SEQ_PARALLEL,
        serving_decision_key(d_model, num_heads, max_len),
    )


def shard_lm_params(model, variables, n: int):
    """Stack a :class:`~chainermn_tpu.models.transformer.TransformerLM`
    param tree into ``[n, ...]`` per-shard leaves for tensor-parallel
    decode over a ``'model'`` axis.

    Sharding map (Megatron column/row placement, matching the
    ``tp_axis`` psum hooks in the block): ``qkv`` kernels head-sharded
    (:func:`~chainermn_tpu.parallel.tensor.shard_qkv_columns`), ``proj``
    and ``ff_down`` kernels row-sharded, ``ff_up`` column-sharded;
    ``ff_down`` bias divided by ``n`` so the row-parallel psum
    reassembles it exactly (bit-exact for power-of-two ``n``);
    MoE expert leaves (``moe_w_up``/``moe_b_up``/``moe_w_down``/
    ``moe_b_down``, ISSUE 20) sliced along their leading ``n_experts``
    dim (shard ``i`` owns experts ``[i*E/n, (i+1)*E/n)`` — the
    residency unit the cluster router filters on) with ``moe_router``
    replicated (every shard routes its owned token rows against the
    full expert table); everything else (embeddings, norms) replicated
    by tiling. Feed through ``shard_map`` with ``P('model')`` on every
    leaf's leading axis.
    """
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.parallel.tensor import (
        shard_qkv_columns,
        stack_tp_params,
    )

    n_heads = model.num_heads
    kv_heads = model.num_kv_heads or model.num_heads
    head_dim = model.d_model // model.num_heads

    def shard_leaf(path, leaf):
        names = [str(getattr(p, "key", p)) for p in path]
        if "qkv" in names and names[-1] == "kernel":
            return shard_qkv_columns(leaf, n_heads, kv_heads, head_dim, n)
        if "proj" in names and names[-1] == "kernel":
            return stack_tp_params(leaf, n, 0)
        if "ff_up" in names:  # kernel [D, dff] dim 1; bias [dff] dim 0
            return stack_tp_params(leaf, n, leaf.ndim - 1)
        if "ff_down" in names and names[-1] == "kernel":
            return stack_tp_params(leaf, n, 0)
        if "ff_down" in names and names[-1] == "bias":
            return jnp.stack([leaf / n] * n)
        if any(nm.startswith("moe_") and nm != "moe_router"
               for nm in names):
            if leaf.shape[0] % n:
                raise ValueError(
                    f"n_experts={leaf.shape[0]} must divide the "
                    f"model-axis size {n} (leaf {'/'.join(names)})"
                )
            return stack_tp_params(leaf, n, 0)  # expert-dim slice
        return jnp.stack([leaf] * n)

    return jax.tree_util.tree_map_with_path(shard_leaf, variables)


def unshard_lm_params(model, stacked):
    """Inverse of :func:`shard_lm_params`: reassemble the FULL param
    tree from the ``[n, ...]``-stacked shard form. Pure ``jnp`` — the
    sequence-parallel prefill program calls it INSIDE ``shard_map``
    after an in-program all-gather of the resident TP stacks, so the
    full weights exist only transiently per prefill (no 2x-params
    replica lives in HBM). ``ff_down``'s bias was stored divided by
    ``n``, so its reassembly is the shard SUM (bit-exact for
    power-of-two ``n``, same note as the shard direction). Roundtrip
    ``unshard(shard(p)) == p`` is pinned in tests/test_serving.py."""
    import jax
    import jax.numpy as jnp

    n_heads = model.num_heads
    kv_heads = model.num_kv_heads or model.num_heads
    head_dim = model.d_model // model.num_heads

    def cols(t):
        # [n, d, c] stacked column shards -> [d, n*c] in shard order
        return t.transpose(1, 0, 2).reshape(t.shape[1], -1)

    def un(path, leaf):
        names = [str(getattr(p, "key", p)) for p in path]
        n = leaf.shape[0]
        if "qkv" in names and names[-1] == "kernel":
            hl = n_heads // n * head_dim
            kl = kv_heads // n * head_dim
            q = leaf[:, :, :hl]
            k = leaf[:, :, hl:hl + kl]
            v = leaf[:, :, hl + kl:]
            return jnp.concatenate([cols(q), cols(k), cols(v)], axis=-1)
        if "proj" in names and names[-1] == "kernel":
            return leaf.reshape(-1, leaf.shape[-1])
        if "ff_up" in names:
            if names[-1] == "kernel":
                return cols(leaf)
            return leaf.reshape(-1)  # bias: [n, dff/n] -> [dff]
        if "ff_down" in names and names[-1] == "kernel":
            return leaf.reshape(-1, leaf.shape[-1])
        if "ff_down" in names and names[-1] == "bias":
            return leaf.sum(axis=0)  # stored as bias / n per shard
        if any(nm.startswith("moe_") and nm != "moe_router"
               for nm in names):
            # [n, E/n, ...] expert slices -> [E, ...] in shard order
            return leaf.reshape(-1, *leaf.shape[2:])
        return leaf[0]  # replicated tiles

    return jax.tree_util.tree_map_with_path(un, stacked)


class ServingEngine:
    """Fixed-slot continuous-batching decode over a ``TransformerLM``.

    Args:
      model: the trained model (``causal=True``, ``return_hidden=False``).
      params: its ``{'params': ...}`` variables.
      num_slots: concurrent requests in the compiled step.
      max_len: serving horizon (prompt + generated) per request;
        defaults to ``model.max_len``. Dense caches and paged tables are
        sized to it.
      decode_impl: ``'dense'`` | ``'paged'`` | ``'auto'`` (tuning
        registry, decision ``decode_impl``).
      kv_block_size: paged block size in tokens, or ``'auto'``
        (decision ``kv_block_size``).
      num_blocks: paged-pool capacity in blocks (incl. scratch block 0);
        default is the no-oversubscription worst case
        (:func:`~chainermn_tpu.serving.kv_blocks.default_num_blocks`) —
        pass less to oversubscribe (admission defers on exhaustion).
      temperature/top_k/top_p: sampling configuration shared with
        :func:`generate` (same ``_tempered_filtered`` path; temperature
        0 = greedy). Sampling keys are COUNTER-BASED
        (:func:`~chainermn_tpu.models.transformer.stream_sample_keys`):
        the token at absolute position ``i`` of a request with seed
        ``s`` draws with ``fold_in(fold_in(base_key, s), i)`` — a pure
        function of (base key, request seed, position), so sampled
        streams keep the same bit-identical-stream guarantee as greedy
        ones across chunked/seq-parallel prefill, speculative decode,
        preemption/resume and cross-replica migration.
      base_seed: integer seed for the sampling base key
        (``PRNGKey(base_seed)``, default 0) — the EXPLICIT spelling of
        the engine-level randomness source; two engines with the same
        ``base_seed`` and per-request seeds produce identical sampled
        streams.
      rng: optional explicit PRNG base key; overrides ``base_seed``
        (passing both is rejected). Use when the base key comes from an
        existing key-management scheme rather than an integer seed.
      pad_id: prompt right-padding token for the bucketed prefill.
      mesh: optional ``Mesh`` with a ``'model'`` axis → tensor-parallel
        decode (weights sharded via :func:`shard_lm_params`).
      spec_tokens: speculative draft length K per tick (ISSUE 5):
        ``0`` = plain one-token decode; ``K > 0`` = each tick drafts up
        to K tokens per slot and ONE jitted verify forward scores
        ``[slots, K+1]`` positions, committing the longest greedy-
        matching prefix plus the model's own next token (1..K+1 tokens
        per tick, bit-identical to the plain stream). ``'auto'``
        resolves through the registry (decision ``spec_tokens``).
        Under ``temperature > 0`` the verify grid samples every
        position with its counter key and acceptance is the standard
        rejection-sampling rule specialised to the deterministic
        drafters (:func:`~chainermn_tpu.serving.speculate.
        rejection_accept_length`) — the committed stream is
        distribution-exact AND bit-identical to sequential sampling at
        a fixed seed.
      drafter: proposal source for ``spec_tokens > 0`` — any object with
        ``propose(history, k)`` (:mod:`chainermn_tpu.serving.speculate`;
        default :class:`~chainermn_tpu.serving.speculate.NgramDrafter`).
      prefix_cache: cross-request prefix sharing (ISSUE 7): ``'on'``
        keeps a block-granular radix trie over completed prefills so a
        joining request adopts the already-filled blocks of its longest
        matching full-block prefix and prefills only the unshared tail
        (the TTFT lever under duplicate-prefix load). ``'auto'``
        resolves through the registry (decision ``prefix_cache``);
        paged-only — under ``decode_impl='dense'`` it is forced off.
        Host metadata + one block-copy program only: the decode/verify
        programs are untouched, and shared streams are bit-identical to
        unshared ones (pinned in tests/test_prefix_cache.py).
      min_shared_blocks: minimum matched FULL blocks before a trie hit
        is adopted (decision ``min_shared_blocks`` under ``'auto'``).
      prefill_chunk: chunked-prefill width in tokens per tick (ISSUE
        11): ``0`` = monolithic bucketed prefill (``prefill_join`` runs
        the whole prompt in one forward, freezing every active slot's
        decode for its duration — the long-prompt p99 killer); ``C >
        0`` = admission reserves the slot without a forward
        (``chunked_join``) and each :meth:`mixed_step` tick writes up
        to C prompt tokens of KV at their true positions for the
        filling slots WHILE the remaining active slots decode (or, with
        ``spec_tokens > 0``, draft-and-verify) — ONE jitted program of
        fixed width ``max(C, spec_tokens + 1)`` whose jit cache stays
        at 1 across every chunk/decode occupancy mix. Chunked streams
        are bit-identical to monolithic ones at ANY temperature (every
        emitted token is the model's own argmax — or counter-keyed
        sample — at its true position). ``'auto'`` resolves through the
        registry (decision
        ``prefill_chunk``, table default 0 — chunking must earn
        adoption via the bursty bench rows).
      prefill_seq_parallel: sequence-parallel long-prompt prefill over
        the mesh's ``model`` partition (ISSUE 13): ``'on'`` shards a
        cache-MISS prompt's forward over the TP devices — each shard
        runs its token slice with ring/Ulysses attention (decision
        ``seq_attn_impl``; Ulysses force-falls back to ring when heads
        are indivisible), the sown per-layer K/V is resharded by one
        ``all_to_all`` per layer into exactly the TP cache layout and
        scattered at true positions, and the last true position's
        logits are psum-selected for the first token — the assembled
        block chain then feeds the existing paged/dense decode path.
        Streams stay bit-identical to sequential ``generate``; composes
        with the prefix cache (a trie HIT takes the monolithic tail
        prefill — its context lives in adopted blocks the sharded
        forward cannot see; the MISS, which is where long-prompt TTFT
        lives, goes wide). The psum-selected last-position logits feed
        the same counter-keyed sample as the monolithic path, so
        sampled streams stay bit-identical too. Requires a ``mesh``, no
        ``window``, and ``prefill_chunk == 0`` (chunked admission takes
        precedence) — explicit ``'on'`` violating these is rejected; an
        ``'auto'`` resolution is forced off with provenance. ``'auto'``
        resolves via the registry (table default ``off`` — the wide
        prefill must earn adoption through bench's ``seq_parallel``
        long-prompt TTFT rows).
      adapter_bank: multi-tenant low-rank delta store (ISSUE 14,
        :class:`~chainermn_tpu.serving.adapters.AdapterBank`): each
        slot carries a host-side tenant row, every serving program
        gathers that slot's A/B rows from the bank's stacks and adds
        the rank-r delta inside the forward — tenant join/leave/
        registration churn mutates host metadata only (the jit caches
        stay pinned at 1), and under TP the stacks are sharded along
        the existing column/row split so the compiled step keeps
        exactly the pre-adapter 2 all-reduces/layer. A tenant's stream
        is bit-identical to sequential ``generate`` with that tenant's
        adapter (``bank.adapter_arrays``); a zero-adapter tenant is
        bitwise the base model. Blocks ``prefill_seq_parallel`` (no
        delta path in the sharded prompt forward yet — forced off with
        provenance).
      adapter_impl: ``'gather'`` | ``'merged'`` | ``'auto'`` (registry
        decision ``adapter_impl``, table ``gather``) — requires
        ``adapter_bank``. ``'merged'`` folds ``merged_tenant``'s delta
        into the base weights at construction
        (``bank.merge_adapter_params``) and serves ONLY that tenant
        (others refused loudly): zero per-step delta cost for
        single-tenant-dominant traffic, bit-identical to ``generate``
        over the offline-merged weights.
      merged_tenant: the tenant ``adapter_impl='merged'`` folds
        (required for explicit ``'merged'``; an ``'auto'`` resolution
        of ``merged`` without it falls back to ``gather`` with
        provenance).
    """

    def __init__(self, model, params, *, num_slots: int,
                 max_len: Optional[int] = None,
                 decode_impl: str = "auto",
                 decode_attend_impl: str = "auto",
                 kv_block_size="auto",
                 num_blocks: Optional[int] = None,
                 prefill_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 base_seed: int = 0,
                 rng=None, pad_id: int = 0, mesh=None,
                 spec_tokens="auto", drafter=None,
                 prefix_cache="auto", min_shared_blocks="auto",
                 prefill_chunk="auto",
                 prefill_seq_parallel="auto",
                 adapter_bank=None, adapter_impl="auto",
                 merged_tenant=None) -> None:
        import jax

        from chainermn_tpu.models.transformer import TransformerLM

        if not isinstance(model, TransformerLM):
            raise TypeError(f"ServingEngine serves TransformerLM, got "
                            f"{type(model).__name__}")
        if model.return_hidden or not model.causal:
            raise ValueError("serving needs a causal LM with logits "
                             "(return_hidden=False, causal=True)")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        max_len = int(max_len or model.max_len)
        if max_len > model.max_len:
            raise ValueError(
                f"max_len={max_len} exceeds the model context "
                f"{model.max_len}"
            )
        if rng is not None and base_seed:
            raise ValueError(
                "pass base_seed= (an integer) OR rng= (an explicit base "
                "key), not both — they name the same randomness source"
            )
        if (top_k is not None or top_p is not None) and temperature <= 0.0:
            raise ValueError("top_k/top_p filtering is for sampling — set "
                             "temperature > 0")
        if top_p is not None and not (0.0 < top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k is not None and not (1 <= top_k <= model.vocab_size):
            raise ValueError(
                f"top_k must be in [1, vocab_size={model.vocab_size}], "
                f"got {top_k}"
            )

        self.num_slots = int(num_slots)
        self.max_len = max_len
        self.pad_id = int(pad_id)
        self.temperature = float(temperature)
        self.top_k, self.top_p = top_k, top_p
        # Counter-based sampling state: ONE base key (explicit — no
        # silent PRNGKey(0) fallback hidden behind temperature > 0) and
        # a per-slot request-seed row. Token i of the request in slot s
        # draws with fold_in(fold_in(_base_key, _seeds[s]), i); there is
        # no consumed split chain, so no key threads through steps.
        self.base_seed = int(base_seed)
        self._base_key = (rng if rng is not None
                          else jax.random.PRNGKey(self.base_seed))
        self._seeds = np.zeros((self.num_slots,), dtype=np.int32)
        self._seeds_ver = 0  # bumped on every _seeds mutation
        self._seeds_dev = None  # cached device copy (H2D discipline)
        self._seeds_dev_ver = -1
        self._buckets = tuple(
            b for b in sorted(set(prefill_buckets)) if b <= max_len
        ) or (max_len,)
        if self._buckets[-1] < max_len:
            # the ladder must be able to carry a full-horizon prompt
            self._buckets = self._buckets + (max_len,)
        self.decisions: list[dict] = []

        # ---- decode_impl / kv_block_size resolution (with provenance)
        from chainermn_tpu import tuning

        key = serving_decision_key(model.d_model, model.num_heads, max_len)
        if decode_impl == "auto":
            decode_impl = resolve_decode_impl(
                model.d_model, model.num_heads, max_len
            )
            self._adopt_decision("decode_impl", key)
        elif decode_impl in DECODE_IMPLS:
            self.decisions.append({"name": "decode_impl", "key": key,
                                   "winner": decode_impl,
                                   "source": "explicit"})
        else:
            raise ValueError(
                f"decode_impl must be one of {DECODE_IMPLS + ('auto',)}, "
                f"got {decode_impl!r}"
            )
        self.decode_impl = decode_impl

        if decode_impl == "paged":
            if kv_block_size == "auto":
                kv_block_size = resolve_kv_block_size(
                    model.d_model, model.num_heads, max_len
                )
                self._adopt_decision("kv_block_size", key)
            else:
                kv_block_size = int(kv_block_size)
                self.decisions.append({"name": "kv_block_size", "key": key,
                                       "winner": str(kv_block_size),
                                       "source": "explicit"})
            num_blocks = num_blocks or default_num_blocks(
                num_slots, kv_block_size, max_len
            )
            self._alloc: Optional[BlockAllocator] = BlockAllocator(
                num_blocks, kv_block_size, num_slots, max_len
            )
        else:
            kv_block_size = int(kv_block_size) if kv_block_size != "auto" \
                else 64
            self._alloc = None

        # ---- decode attend impl (ISSUE 19): the fused paged-decode
        # Pallas kernel vs the XLA scatter → gather → attend. ONE field
        # on the decode model clone, so the decode / verify / mixed /
        # prefill-tail programs all switch together (their jit caches
        # stay pinned — the impl is a static model field, not a traced
        # arg). Validate BEFORE the capability gate: a typo must raise
        # identically whichever jax is present.
        if (decode_attend_impl != "auto"
                and decode_attend_impl not in DECODE_ATTEND_IMPLS):
            raise ValueError(
                f"decode_attend_impl must be one of "
                f"{DECODE_ATTEND_IMPLS + ('auto',)}, got "
                f"{decode_attend_impl!r}"
            )
        from chainermn_tpu._jax_compat import pallas_paged_decode_supported
        if decode_attend_impl == "auto":
            decode_attend_impl = resolve_decode_attend_impl(
                model.d_model, model.num_heads, max_len
            )
            self._adopt_decision("decode_attend_impl", key)
            if (decode_attend_impl == "fused"
                    and not pallas_paged_decode_supported()):
                # The cache says the kernel wins this shape, but this
                # image's Pallas lacks scalar-prefetch grid specs —
                # serve the XLA attend with honest provenance.
                decode_attend_impl = "xla"
                self.decisions.append({
                    "name": "decode_attend_impl", "key": key,
                    "winner": "xla", "source": "forced:jax-compat",
                })
        else:
            if (decode_attend_impl == "fused"
                    and not pallas_paged_decode_supported()):
                raise ValueError(
                    "decode_attend_impl='fused' needs a Pallas with "
                    "scalar-prefetch grid specs "
                    "(pltpu.PrefetchScalarGridSpec) — this jax lacks "
                    "them (an 'auto' resolution would fall back with "
                    "forced:jax-compat)"
                )
            self.decisions.append({"name": "decode_attend_impl",
                                   "key": key,
                                   "winner": decode_attend_impl,
                                   "source": "explicit"})
        self.decode_attend_impl = decode_attend_impl

        # ---- MoE dispatch impl (ISSUE 20): the ownership-split decode
        # path builds its expert queues by sort-scatter or dense one-hot
        # einsum — registry decision, resolved ONCE here so the decode /
        # verify / mixed / prefill programs all trace the same impl (a
        # static model field, exactly like decode_attend_impl; jit
        # caches stay pinned).
        self.n_experts = int(model.n_experts)
        self.moe_dispatch_impl: Optional[str] = None
        if self.n_experts > 0:
            from chainermn_tpu.parallel.moe import resolve_dispatch_impl

            tp = (int(mesh.shape["model"])
                  if mesh is not None and "model" in mesh.axis_names
                  else 1)
            own_rows = -(-num_slots // tp)
            moe_key = tuning.decision_key(
                shape=(own_rows, self.n_experts, model.d_model),
                dtype=model.compute_dtype,
            )
            self.moe_dispatch_impl = resolve_dispatch_impl(
                own_rows, self.n_experts, model.d_model,
                model.compute_dtype, model.moe_dispatch_impl,
            )
            if model.moe_dispatch_impl == "auto":
                self._adopt_decision("moe_dispatch", moe_key)
            else:
                self.decisions.append({
                    "name": "moe_dispatch", "key": moe_key,
                    "winner": self.moe_dispatch_impl,
                    "source": "explicit",
                })

        # ---- prefix sharing (ISSUE 7): trie + COW over the paged pool.
        # Dense rows are slot-private by layout — nothing to share, so
        # the decision is forced off there without consulting the
        # registry (an 'on' cache entry for a dense shape would be a
        # lie about what ran). Validate BEFORE the dense force: a typo
        # must raise identically whichever decode impl it rides with.
        if prefix_cache != "auto" and prefix_cache not in PREFIX_CACHE:
            raise ValueError(
                f"prefix_cache must be one of {PREFIX_CACHE + ('auto',)}, "
                f"got {prefix_cache!r}"
            )
        if self._alloc is None:
            prefix_cache = "off"
            self.decisions.append({"name": "prefix_cache", "key": key,
                                   "winner": "off",
                                   "source": "forced:dense"})
        elif prefix_cache == "auto":
            prefix_cache = resolve_prefix_cache(
                model.d_model, model.num_heads, max_len
            )
            self._adopt_decision("prefix_cache", key)
        else:
            self.decisions.append({"name": "prefix_cache", "key": key,
                                   "winner": prefix_cache,
                                   "source": "explicit"})
        self.prefix_cache_enabled = prefix_cache == "on"
        if self.prefix_cache_enabled:
            if min_shared_blocks == "auto":
                min_shared_blocks = resolve_min_shared_blocks(
                    model.d_model, model.num_heads, max_len
                )
                self._adopt_decision("min_shared_blocks", key)
            else:
                min_shared_blocks = int(min_shared_blocks)
                self.decisions.append({"name": "min_shared_blocks",
                                       "key": key,
                                       "winner": str(min_shared_blocks),
                                       "source": "explicit"})
            if min_shared_blocks < 1:
                raise ValueError(
                    f"min_shared_blocks must be >= 1, got "
                    f"{min_shared_blocks}"
                )
            self._prefix: Optional[PrefixCache] = PrefixCache(self._alloc)
            self._min_shared_blocks = int(min_shared_blocks)
        else:
            self._prefix = None
            self._min_shared_blocks = 0
        #: lifetime prefix-cache accounting (the scheduler's hit-rate
        #: gauge and dryrun/bench lines read it).
        self.prefix_stats = {
            "lookups": 0, "hits": 0, "hit_tokens": 0, "prompt_tokens": 0,
            "prefill_tokens": 0, "cow_blocks": 0,
        }
        #: per-join event payload for the scheduler's ``prefix_cache``
        #: trace event — set by every paged+cache-on prefill_join, None
        #: otherwise.
        self.last_prefix_info: Optional[dict] = None

        # ---- speculation length (ISSUE 5): K drafted tokens per tick,
        # verified in one forward. Resolved like the other serving
        # decisions. At temperature 0 acceptance compares drafts
        # against the model's argmax; at temperature > 0 the verify
        # grid is counter-key SAMPLED and the same comparison is the
        # rejection-sampling acceptance rule (speculate.
        # rejection_accept_length) — both modes serve.
        if spec_tokens == "auto":
            spec_tokens = resolve_spec_tokens(
                model.d_model, model.num_heads, max_len
            )
            self._adopt_decision("spec_tokens", key)
        else:
            spec_tokens = int(spec_tokens)
            self.decisions.append({"name": "spec_tokens", "key": key,
                                   "winner": str(spec_tokens),
                                   "source": "explicit"})
        if spec_tokens < 0 or spec_tokens >= max_len:
            raise ValueError(
                f"spec_tokens must be in [0, max_len={max_len}), got "
                f"{spec_tokens}"
            )
        self.spec_tokens = spec_tokens
        if drafter is not None and not callable(
            getattr(drafter, "propose", None)
        ):
            raise TypeError(
                "drafter must have a propose(history, k) method "
                "(see chainermn_tpu.serving.speculate)"
            )
        if drafter is None and spec_tokens > 0:
            from chainermn_tpu.serving.speculate import NgramDrafter

            drafter = NgramDrafter()
        self._drafter = drafter

        # ---- chunked prefill (ISSUE 11): C prompt tokens of KV written
        # per tick inside the mixed step, interleaved with decode.
        if prefill_chunk == "auto":
            prefill_chunk = resolve_prefill_chunk(
                model.d_model, model.num_heads, max_len
            )
            self._adopt_decision("prefill_chunk", key)
        else:
            prefill_chunk = int(prefill_chunk)
            self.decisions.append({"name": "prefill_chunk", "key": key,
                                   "winner": str(prefill_chunk),
                                   "source": "explicit"})
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}"
            )
        self.prefill_chunk = int(prefill_chunk)
        #: width of the mixed step's token grid — the chunk columns and
        #: the verify span share ONE program, so chunk and draft rows
        #: coexist in the same tick at the wider of the two.
        self._mixed_T = (max(self.prefill_chunk, self.spec_tokens + 1)
                         if self.prefill_chunk > 0 else 0)
        #: slots admitted by chunked_join whose prompt KV is still being
        #: written (insertion order = admission order, the fill-row FIFO
        #: mixed_step advances). NOT active: decode masks exclude them.
        self._pending_fill: dict[int, dict] = {}

        # ---- multi-tenant adapters (ISSUE 14): resolve the impl and,
        # under 'merged', fold the tenant's delta into the base weights
        # BEFORE the clone/shard below — the rest of the ctor then
        # builds an ordinary engine over the folded tree.
        if adapter_impl != "auto" and adapter_impl not in ADAPTER_IMPLS:
            raise ValueError(
                f"adapter_impl must be one of "
                f"{ADAPTER_IMPLS + ('auto',)}, got {adapter_impl!r}"
            )
        self.adapter_bank = adapter_bank
        self.merged_tenant = merged_tenant
        if adapter_bank is None:
            if adapter_impl != "auto":
                raise ValueError(
                    f"adapter_impl={adapter_impl!r} needs an "
                    "adapter_bank"
                )
            if merged_tenant is not None:
                raise ValueError("merged_tenant needs an adapter_bank")
            self.adapter_impl: Optional[str] = None
        else:
            if adapter_bank.num_layers != model.num_layers:
                raise ValueError(
                    f"adapter_bank stacks {adapter_bank.num_layers} "
                    f"layers, model has {model.num_layers}"
                )
            if adapter_impl == "auto":
                adapter_impl = resolve_adapter_impl(
                    model.d_model, model.num_heads, max_len
                )
                self._adopt_decision("adapter_impl", key)
                if adapter_impl == "merged" and merged_tenant is None:
                    # The cache says merging wins this shape, but this
                    # engine was built without a tenant to fold — serve
                    # the gather path with honest provenance rather
                    # than guess whose weights to merge.
                    adapter_impl = "gather"
                    self.decisions.append({
                        "name": "adapter_impl", "key": key,
                        "winner": "gather",
                        "source": "forced:no-merged-tenant",
                    })
            else:
                if adapter_impl == "merged" and merged_tenant is None:
                    raise ValueError(
                        "adapter_impl='merged' needs merged_tenant= — "
                        "the fold must know whose delta to bake in"
                    )
                if adapter_impl == "gather" and merged_tenant is not None:
                    # Loud like every other invalid combination: an
                    # explicit gather engine never folds, so a
                    # merged_tenant here is a typoed/confused intent
                    # the caller must resolve, not a silent no-op.
                    raise ValueError(
                        "merged_tenant= is only meaningful with "
                        "adapter_impl='merged' (or 'auto'); an "
                        "explicit 'gather' engine serves every "
                        "registered tenant and folds nothing"
                    )
                self.decisions.append({"name": "adapter_impl",
                                       "key": key,
                                       "winner": adapter_impl,
                                       "source": "explicit"})
            self.adapter_impl = adapter_impl
            if adapter_impl == "merged":
                params = adapter_bank.merge_adapter_params(
                    params, merged_tenant)
        #: whether the compiled programs carry the per-slot gather+delta
        #: (the 'gather' impl); merged/bank-less engines run the plain
        #: programs.
        self._use_adapters = (adapter_bank is not None
                              and self.adapter_impl == "gather")
        if self._use_adapters:
            # Trie invalidation on weight churn (review finding): a
            # tenant's cached KV is only valid under the stacks that
            # produced it — drop the namespace whenever the bank's
            # content for that tenant changes (register overwrite,
            # zero-adapter downgrade, evict), whichever engine or
            # caller mutated the bank.
            adapter_bank.add_listener(self._on_adapter_change)
        #: per-slot tenant identity (host metadata: the prefix-trie
        #: namespace, the bank pin, the export payload field).
        self._tenant_ids: list[Optional[str]] = [None] * num_slots
        #: per-slot bank row the programs gather (0 = null adapter).
        self._tenant_rows = np.zeros(num_slots, np.int32)
        self._tenant_rows_ver = 0
        self._tenant_rows_dev = None
        self._tenant_rows_dev_ver = -1
        self._adapter_dev = None
        self._adapter_ver = -1

        # ---- decode-path model (and its TP shard form)
        self._mesh = mesh
        clone_kw: dict[str, Any] = dict(
            kv_layout=decode_impl,
            kv_block_size=int(kv_block_size),
            kv_num_blocks=(self._alloc.num_blocks if self._alloc else 0),
            decode_cache_len=max_len,
            decode_attend_impl=decode_attend_impl,
        )
        if mesh is None:
            self._decode_model = model.clone(**clone_kw)
            self._vars = {"params": params["params"]}
        else:
            if "model" not in mesh.axis_names:
                raise ValueError(
                    f"serving mesh needs a 'model' axis, got "
                    f"{mesh.axis_names}"
                )
            n = int(mesh.shape["model"])
            kvh = model.num_kv_heads or model.num_heads
            moe = self.n_experts > 0
            if model.num_heads % n or kvh % n or (
                    not moe and model.d_ff % n):
                raise ValueError(
                    f"heads={model.num_heads}/kv={kvh}/d_ff={model.d_ff} "
                    f"must divide the model-axis size {n}"
                )
            if moe and self.n_experts % n:
                raise ValueError(
                    f"n_experts={self.n_experts} must divide the "
                    f"model-axis size {n} — expert shards live on the "
                    f"TP mesh"
                )
            self._tp_n = n
            # MoE keeps the FULL d_ff (experts shard by expert index,
            # not by hidden width) and n_experts stays GLOBAL — the
            # sharder slices the stacked expert leaves, the block reads
            # the local count off the leaf at trace time.
            self._decode_model = model.clone(
                num_heads=model.num_heads // n,
                num_kv_heads=kvh // n,
                d_ff=model.d_ff if moe else model.d_ff // n,
                head_dim=model.d_model // model.num_heads,
                tp_axis="model",
                expert_axis="model" if moe else None,
                moe_dispatch_impl=(self.moe_dispatch_impl or "auto"),
                moe_experts_local=(self.n_experts // n if moe else None),
                **clone_kw,
            )
            self._vars = shard_lm_params(
                model, {"params": params["params"]}, n
            )

        # ---- cache + host slot metadata. Shape evaluation runs outside
        # shard_map where no mesh axis is bound, so strip the psum hooks
        # (tp_axis) — cache shapes depend only on the (local) head/width
        # fields, which the clone keeps.
        cache = init_serving_cache(
            self._decode_model.clone(tp_axis=None, expert_axis=None),
            self._local_vars_for_init(), num_slots,
        )
        if mesh is not None:
            import jax.numpy as jnp
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            # Placed with the mesh sharding the step programs RETURN
            # (out_specs P('model')): the first program to touch the
            # cache must see the canonical sharding, or its jit entry
            # compiles against the default placement and the second
            # call recompiles — monolithic engines never noticed
            # (prefill always ran first and canonicalised it), but a
            # chunked engine's FIRST forward is the mixed step itself.
            sh = NamedSharding(mesh, P("model"))
            cache = jax.tree.map(
                lambda c: jax.device_put(
                    jnp.broadcast_to(c[None], (self._tp_n,) + c.shape),
                    sh,
                ),
                cache,
            )
        self._cache = cache
        self._positions = np.zeros(num_slots, np.int64)
        self._last_tok = np.zeros(num_slots, np.int64)
        self._active = np.zeros(num_slots, bool)
        self._free = list(range(num_slots - 1, -1, -1))
        #: per-slot committed token history (prompt + generated incl.
        #: the pending last token) — what the drafter proposes from.
        self._history: list[list[int]] = [[] for _ in range(num_slots)]
        #: the resolution key every serving decision rode (the cluster
        #: router resolves its disaggregation decision under the same
        #: key — one key per model shape, ISSUE 8).
        self.decision_key = key
        self._tables_dev = None  # device copy of the block tables...
        self._tables_ver = -1    # ...valid while allocator.version holds
        # Cross-replica KV handoff programs (ISSUE 8): built lazily on
        # the first export/import — most engines never transfer.
        self._kv_extract_jit = None
        self._kv_inject_jit = None

        # ---- sequence-parallel prefill (ISSUE 13): shard a cache-miss
        # prompt's forward over the mesh's 'model' partition.
        if (prefill_seq_parallel != "auto"
                and prefill_seq_parallel not in PREFILL_SEQ_PARALLEL):
            raise ValueError(
                f"prefill_seq_parallel must be one of "
                f"{PREFILL_SEQ_PARALLEL + ('auto',)}, got "
                f"{prefill_seq_parallel!r}"
            )
        explicit_sp = prefill_seq_parallel != "auto"
        if prefill_seq_parallel == "auto":
            prefill_seq_parallel = resolve_prefill_seq_parallel(
                model.d_model, model.num_heads, max_len
            )
            self._adopt_decision("prefill_seq_parallel", key)
        else:
            self.decisions.append({"name": "prefill_seq_parallel",
                                   "key": key,
                                   "winner": prefill_seq_parallel,
                                   "source": "explicit"})
        if prefill_seq_parallel == "on":
            blocked = None
            if mesh is None:
                blocked = ("forced:no-mesh",
                           "needs a mesh with a 'model' axis to shard "
                           "the prompt over")
            elif model.window is not None:
                blocked = ("forced:window",
                           "the sharded forward's ring/Ulysses "
                           "attention does not honour a sliding window")
            elif self.prefill_chunk > 0:
                blocked = ("forced:chunked",
                           "chunked admission (prefill_chunk > 0) "
                           "already bounds long-prompt interference and "
                           "takes precedence")
            elif adapter_bank is not None:
                blocked = ("forced:adapters",
                           "the sequence-parallel prompt forward has "
                           "no adapter-delta path — multi-tenant "
                           "engines take the monolithic prefill")
            if blocked is not None:
                if explicit_sp:
                    raise ValueError(
                        f"prefill_seq_parallel='on' {blocked[1]} — "
                        f"({blocked[0]})"
                    )
                prefill_seq_parallel = "off"
                self.decisions.append({"name": "prefill_seq_parallel",
                                       "key": key, "winner": "off",
                                       "source": blocked[0]})
        self.prefill_seq_parallel = prefill_seq_parallel == "on"
        #: whether the LAST prefill_join ran the sequence-parallel
        #: program (the scheduler's prefill-event field).
        self.last_prefill_seq_parallel = False
        self._base_model = model
        self._seq_base_model = None
        self._seq_attn_impl = None
        self._seq_prefill_jits: dict[int, Any] = {}
        if self.prefill_seq_parallel:
            from chainermn_tpu import tuning
            from chainermn_tpu.parallel.plan_specs import SEQ_ATTN_IMPLS
            from chainermn_tpu.parallel.ring_attention import (
                seq_ring_attention_local,
            )
            from chainermn_tpu.parallel.ulysses import (
                ulysses_attention_local,
            )

            n = self._tp_n
            kvh = model.num_kv_heads or model.num_heads
            skey = tuning.decision_key(
                shape=(n, model.num_heads, max_len), dtype="seqattn"
            )
            impl = tuning.choice("seq_attn_impl", SEQ_ATTN_IMPLS, skey)
            self._adopt_decision("seq_attn_impl", skey)
            if impl == "ulysses" and (model.num_heads % n or kvh % n):
                impl = "ring"
                self.decisions.append({
                    "name": "seq_attn_impl", "key": skey,
                    "winner": "ring",
                    "source": "forced:heads-indivisible",
                })
            self._seq_attn_impl = impl
            interp = mesh.devices.flat[0].platform != "tpu"
            if impl == "ring":
                def _seq_attn(q, k, v, *, causal, scale, **kw):
                    return seq_ring_attention_local(
                        q, k, v, "model", causal=causal, scale=scale,
                        interpret=interp,
                    )
            else:
                def _seq_attn(q, k, v, *, causal, scale, **kw):
                    return ulysses_attention_local(
                        q, k, v, "model", causal=causal, scale=scale,
                        impl="flash", interpret=interp,
                    )
            self._seq_base_model = model.clone(
                attention_fn=_seq_attn, sow_kv=True
            )

        self._decode_step_jit = self._build_decode_step()
        self._verify_step_jit = (
            self._build_verify_step() if self.spec_tokens > 0 else None
        )
        self._mixed_step_jit = (
            self._build_mixed_step() if self.prefill_chunk > 0 else None
        )
        self._cow_copy_jit = (
            self._build_cow_copy() if self._prefix is not None else None
        )
        self._prefill_jits: dict[int, Any] = {}

    # ------------------------------------------------------------------
    # construction helpers

    def _adopt_decision(self, name: str, key: str) -> None:
        """Copy the registry's resolution record (winner + provenance)
        into ``self.decisions`` — what dryrun/bench print per engine."""
        from chainermn_tpu import tuning

        recs = [d for d in tuning.decisions_taken()
                if d["name"] == name and d["key"] == key]
        if recs:
            self.decisions.append(dict(recs[-1]))

    def _local_vars_for_init(self):
        """Per-shard variables for cache shape evaluation (TP stacks
        carry a leading mesh axis the local model must not see)."""
        if self._mesh is None:
            return self._vars
        import jax

        return jax.tree.map(lambda a: a[0], self._vars)

    def _dummy_tables(self):
        """Dense decode still passes a (tiny, ignored) tables arg so the
        step signature — and therefore the compiled program — is one
        shape for both impls."""
        if self._alloc is not None:
            return self._alloc.tables
        return np.zeros((self.num_slots, 1), np.int32)

    def _tables_device(self):
        """The block tables as a CACHED device array, re-uploaded only
        when the allocator actually mutated a row — the steady-state
        decode loop must not pay an H2D transfer right after its D2H
        token sync every step (the tunnelled-TPU degradation trap)."""
        import jax.numpy as jnp

        version = self._alloc.version if self._alloc is not None else 0
        if self._tables_dev is None or self._tables_ver != version:
            self._tables_dev = jnp.asarray(self._dummy_tables())
            self._tables_ver = version
        return self._tables_dev

    def _adapter_device(self):
        """The bank's stacks as CACHED device arrays (TP-sharded under a
        mesh), re-uploaded only when a registration actually changed a
        row (``bank.version`` — the block-table discipline: the decode
        loop must not pay an H2D per tick for tenant data that did not
        change)."""
        import jax

        bank = self.adapter_bank
        if self._adapter_dev is None or self._adapter_ver != bank.version:
            import jax.numpy as jnp

            stacks = bank.stacks()
            if self._mesh is None:
                dev = [
                    {t: (jnp.asarray(A), jnp.asarray(B))
                     for t, (A, B) in layer.items()}
                    for layer in stacks
                ]
            else:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                from chainermn_tpu.serving.adapters import (
                    shard_adapter_stacks,
                )

                sh = NamedSharding(self._mesh, P("model"))
                dev = jax.tree.map(
                    lambda a: jax.device_put(a, sh),
                    shard_adapter_stacks(
                        self._base_model, stacks, self._tp_n),
                )
            self._adapter_dev = dev
            self._adapter_ver = bank.version
        return self._adapter_dev

    def _tenant_rows_device(self):
        """The per-slot tenant-row vector as a cached device array —
        re-uploaded only when a join/leave changed a row (same H2D
        discipline as the block tables)."""
        import jax.numpy as jnp

        if (self._tenant_rows_dev is None
                or self._tenant_rows_dev_ver != self._tenant_rows_ver):
            self._tenant_rows_dev = jnp.asarray(self._tenant_rows)
            self._tenant_rows_dev_ver = self._tenant_rows_ver
        return self._tenant_rows_dev

    def _step_args(self, *mid, tail=(), tenant_rows=None):
        """ONE argument-splice rule for every jitted program call
        (prefill/decode/verify/mixed): ``(cache, vars, *mid, *tail)``,
        with the adapter stacks inserted after ``vars`` and the
        per-slot tenant rows between ``mid`` and ``tail`` when the
        bank is active (review finding: four hand-expanded if/else
        copies of the argument list were one reorder away from
        silently misfeeding a compiled program). ``tenant_rows``
        defaults to the cached whole-array upload; prefill passes its
        single-slot slice."""
        if not self._use_adapters:
            return (self._cache, self._vars, *mid, *tail)
        rows = (self._tenant_rows_device() if tenant_rows is None
                else tenant_rows)
        return (self._cache, self._vars, self._adapter_device(),
                *mid, rows, *tail)

    def _tp_jit(self, inner, n_plain_args: int, n_model_args: int = 0):
        """The ONE jit(+shard_map) wrapper all the serving programs
        (decode / verify / mixed / prefill) share: donate the cache,
        and under TP unstack the ``[n, ...]`` cache/param stacks around
        the local program so the psum hooks see per-shard leaves.

        ``inner(cache, variables, *model_args, *rest) -> (cache, out)``;
        ``n_model_args`` counts extra model-axis-sharded pytrees right
        after ``variables`` (ISSUE 14: the adapter stacks ride here so
        each shard gathers its own column/row slice), ``n_plain_args``
        counts the trailing ``rest`` (replicated under TP)."""
        import jax

        if self._mesh is None:
            return jax.jit(inner, donate_argnums=(0,))

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def local(cache_st, vars_st, *rest):
            cache = jax.tree.map(lambda a: a[0], cache_st)
            variables = jax.tree.map(lambda a: a[0], vars_st)
            sharded = [jax.tree.map(lambda a: a[0], t)
                       for t in rest[:n_model_args]]
            cache2, out = inner(cache, variables, *sharded,
                                *rest[n_model_args:])
            return jax.tree.map(lambda a: a[None], cache2), out

        return jax.jit(
            shard_map(
                local, mesh=self._mesh,
                in_specs=(P("model"), P("model"))
                + (P("model"),) * n_model_args
                + (P(),) * n_plain_args,
                out_specs=(P("model"), P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def _pool_exhausted_error(self) -> RuntimeError:
        # blocks_in_use counts slot-referenced blocks only; cached
        # (trie-held, refcount 0) blocks make the arithmetic add up —
        # without them "20/32 in use" on a full pool reads like a lie.
        cached = self._alloc.blocks_cached()
        return RuntimeError(
            "paged KV pool exhausted mid-stream: "
            f"{self._alloc.blocks_in_use}/"
            f"{self._alloc.num_blocks - 1} blocks in use"
            + (f" (+{cached} trie-cached)" if cached else "")
            + " — size num_blocks for the resident-token worst case "
            "or admit fewer concurrent requests"
        )

    def _seeds_device(self):
        """The per-slot request-seed vector as a cached device array —
        re-uploaded only when an admission/release changed a seed (same
        H2D discipline as the block tables and tenant rows: the decode
        loop must not pay an H2D right after its D2H token sync)."""
        import jax.numpy as jnp

        if self._seeds_dev is None or self._seeds_dev_ver != self._seeds_ver:
            self._seeds_dev = jnp.asarray(self._seeds)
            self._seeds_dev_ver = self._seeds_ver
        return self._seeds_dev

    def _set_slot_seed(self, slot: int, seed) -> None:
        """Commit a slot's request seed (admission / KV import / release
        hygiene), bumping the H2D version only on an actual change."""
        seed = np.int32(0 if seed is None else int(seed))
        if self._seeds[slot] != seed:
            self._seeds[slot] = seed
            self._seeds_ver += 1

    def _sample(self, logits, seeds, counters):
        """Shared sampling tail of every serving program: greedy argmax
        at temperature 0 (``seeds``/``counters`` are then dead arguments
        XLA drops — the compiled grids stay bitwise the pre-sampling
        programs); otherwise ONE counter-keyed categorical per row — row
        ``i`` draws with ``fold_in(fold_in(base_key, seeds[i]),
        counters[i])`` (:func:`~chainermn_tpu.models.transformer.
        stream_sample_keys`), so the token depends only on (request
        seed, absolute position, logits) — never on which program or
        tick asked, which is the whole bit-identical-stream argument."""
        import jax
        import jax.numpy as jnp

        from chainermn_tpu.models.transformer import (
            _tempered_filtered,
            stream_sample_keys,
        )

        if self.temperature > 0.0:
            keys = stream_sample_keys(self._base_key, seeds, counters)
            return jax.vmap(jax.random.categorical)(
                keys,
                _tempered_filtered(logits, self.temperature, self.top_k,
                                   self.top_p),
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _build_decode_step(self):
        model = self._decode_model

        if self._use_adapters:
            def inner(cache, variables, ad, tokens, positions, tables,
                      rows, seeds):
                logits, mutated = model.apply(
                    {**variables, "cache": cache}, tokens[:, None],
                    train=False, decode=True, decode_positions=positions,
                    block_tables=tables, mutable=["cache"],
                    adapters=_gather_adapter_rows(ad, rows),
                )
                # Slot s holds `positions[s]` tokens; this step samples
                # the token at that absolute position + 1 → counter.
                return mutated["cache"], self._sample(
                    logits[:, 0], seeds, positions + 1)

            return self._tp_jit(inner, 5, n_model_args=1)

        def inner(cache, variables, tokens, positions, tables, seeds):
            logits, mutated = model.apply(
                {**variables, "cache": cache}, tokens[:, None],
                train=False, decode=True, decode_positions=positions,
                block_tables=tables, mutable=["cache"],
            )
            return mutated["cache"], self._sample(
                logits[:, 0], seeds, positions + 1)

        return self._tp_jit(inner, 4)

    def _build_verify_step(self):
        """The speculative verify program: ONE forward scores
        ``[slots, K+1]`` positions — the pending last token plus K
        drafts per row, written/attended at per-row position spans
        (``_slot_decode_attend`` with ``T = K+1``) — and returns the
        model's OWN token at every position: greedy argmax at
        temperature 0, the counter-keyed sample otherwise (cell
        ``(s, j)`` uses counter ``positions[s] + j + 1``, the absolute
        index of the token that cell emits — exactly the key sequential
        decode would use there, which is what makes sampled acceptance
        the rejection-sampling rule, see :func:`~chainermn_tpu.serving.
        speculate.rejection_accept_length`). Acceptance, rollback,
        and padding are HOST decisions (:meth:`verify_step`): the
        compiled program is one fixed shape across request churn and
        any acceptance outcome, and under TP it carries exactly the
        same 2 all-reduces per layer as the one-token step (the
        amortization the suite pins by HLO count)."""
        import jax.numpy as jnp

        model = self._decode_model

        def grid_sample(logits, positions, seeds):
            if self.temperature <= 0.0:  # bitwise the pre-sampling grid
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            S, T = logits.shape[:2]
            counters = positions[:, None] + jnp.arange(
                1, T + 1, dtype=positions.dtype)[None, :]
            return self._sample(
                logits.reshape(S * T, -1),
                jnp.repeat(seeds, T), counters.reshape(S * T),
            ).reshape(S, T)

        if self._use_adapters:
            def inner(cache, variables, ad, tokens, positions, tables,
                      rows, seeds):
                logits, mutated = model.apply(
                    {**variables, "cache": cache}, tokens,
                    train=False, decode=True, decode_positions=positions,
                    block_tables=tables, mutable=["cache"],
                    adapters=_gather_adapter_rows(ad, rows),
                )
                return mutated["cache"], grid_sample(
                    logits, positions, seeds)  # [slots, K+1]

            return self._tp_jit(inner, 5, n_model_args=1)

        def inner(cache, variables, tokens, positions, tables, seeds):
            logits, mutated = model.apply(
                {**variables, "cache": cache}, tokens,  # [slots, K+1]
                train=False, decode=True, decode_positions=positions,
                block_tables=tables, mutable=["cache"],
            )
            return mutated["cache"], grid_sample(
                logits, positions, seeds)  # [slots, K+1]

        return self._tp_jit(inner, 4)

    def _build_mixed_step(self):
        """The chunked-prefill MIXED step (ISSUE 11 tentpole): ONE
        forward over a fixed ``[slots, T]`` grid, ``T = max(chunk,
        K+1)``, through the same per-row position spans as the verify
        step (``_slot_decode_attend``) — fill rows write up to
        ``chunk`` REAL prompt tokens at their true positions, decode
        rows carry ``[last_tok, drafts..., pad]``, inactive/stalled
        rows carry pads whose writes land in scratch or in blocks the
        next real write re-covers before any causal mask admits them
        (the speculative-rollback staleness argument, reused). Which
        rows chunk vs decode is HOST metadata, so the jit cache stays
        at one entry across every chunk/decode occupancy mix — and
        under TP the program carries exactly the same 2 all-reduces
        per layer as the one-token step (pinned by HLO count).
        Sampling runs per grid position with the cell's COUNTER key
        (cell ``(s, j)`` emits the token at absolute index
        ``positions[s] + j + 1`` and uses exactly that counter — the
        final chunk's boundary cell lands on counter ``P_len``, the
        same key the monolithic prefill uses): at temperature 0 that
        is the verify step's greedy-argmax grid, which is what
        acceptance and the chunk boundary token both read."""
        import jax.numpy as jnp

        model = self._decode_model

        def grid_sample(logits, positions, seeds):
            if self.temperature <= 0.0:  # bitwise the pre-sampling grid
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            S, T = logits.shape[:2]
            counters = positions[:, None] + jnp.arange(
                1, T + 1, dtype=positions.dtype)[None, :]
            return self._sample(
                logits.reshape(S * T, -1),
                jnp.repeat(seeds, T), counters.reshape(S * T),
            ).reshape(S, T)

        if self._use_adapters:
            def inner(cache, variables, ad, tokens, positions, tables,
                      rows, seeds):
                logits, mutated = model.apply(
                    {**variables, "cache": cache}, tokens,  # [slots, T]
                    train=False, decode=True, decode_positions=positions,
                    block_tables=tables, mutable=["cache"],
                    adapters=_gather_adapter_rows(ad, rows),
                )
                return mutated["cache"], grid_sample(
                    logits, positions, seeds)  # [slots, T]

            return self._tp_jit(inner, 5, n_model_args=1)

        def inner(cache, variables, tokens, positions, tables, seeds):
            logits, mutated = model.apply(
                {**variables, "cache": cache}, tokens,  # [slots, T]
                train=False, decode=True, decode_positions=positions,
                block_tables=tables, mutable=["cache"],
            )
            return mutated["cache"], grid_sample(
                logits, positions, seeds)  # [slots, T]

        return self._tp_jit(inner, 4)

    def _build_cow_copy(self):
        """The copy-on-write block copy: ONE jitted program copying one
        physical block (src -> dst) in every layer's K and V pool
        (:func:`chainermn_tpu.ops.paged_kv.copy_block`). Routed through
        the same ``_tp_jit`` wrapper as the serving programs so the
        cache stays donated and, under TP, each shard copies its own
        slice — zero collectives, one compile for any block pair (the
        jit-cache pin extends over COW churn)."""
        import jax

        from chainermn_tpu.ops.paged_kv import copy_block

        def inner(cache, variables, src, dst):
            del variables
            cache2 = jax.tree.map(
                lambda pool: copy_block(pool, src, dst), cache
            )
            return cache2, src

        return self._tp_jit(inner, 2)

    def _cow_protect(self, slot: int, start: int, n_positions: int,
                     strict: bool = True) -> Optional[int]:
        """Copy-on-write guard for a device write span ``[start, start +
        n_positions)`` of ``slot``: any covered block that another slot
        references — or the prefix trie caches — is copied to a fresh
        block and the WRITER's table repointed before the write program
        runs (host rewrite for this slot only; readers and the trie's
        pristine copy untouched). Partial tail blocks are never shared,
        so in practice this fires on the boundary block of a full-prefix
        hit and is a no-op everywhere else. Returns blocks copied; on
        genuine pool exhaustion raises when ``strict`` (the decode/
        verify paths, where the slot already holds tokens) and returns
        None when not (the join path defers the admission instead —
        the copy needs ONE block beyond what ``ensure`` reserved)."""
        if self._prefix is None or n_positions <= 0:
            return 0
        import jax.numpy as jnp

        alloc = self._alloc
        bs = alloc.block_size
        # Read the live table row, no defensive copy: this guard runs
        # per active slot per decode/verify tick and is a no-op outside
        # the join boundary (partial tails are never shared).
        owned = alloc._owned[slot]
        first = start // bs
        last = min(-(-(start + n_positions) // bs), len(owned))
        copied = 0
        for j in range(first, last):
            blk = owned[j]
            if not alloc.shared_for_write(blk):
                continue
            fresh = alloc.alloc_block()
            if fresh is None:
                if strict:
                    raise self._pool_exhausted_error()
                return None
            self._cache, _ = self._cow_copy_jit(
                self._cache, self._vars,
                jnp.int32(blk), jnp.int32(fresh),
            )
            alloc.cow_replace(slot, j, fresh)
            copied += 1
        if copied:
            self.prefix_stats["cow_blocks"] += copied
        return copied

    def _prefill_fn(self, bucket: int):
        """The (cached) prefill program for one bucket length. ``start``
        is a traced per-call scalar — position of the bucket's FIRST
        token — so the same compiled program serves a from-scratch
        prefill (start 0) and a prefix-cache tail prefill that begins
        at the first unshared position (ISSUE 7): compile count stays
        bounded by the bucket ladder either way."""
        if bucket in self._prefill_jits:
            return self._prefill_jits[bucket]
        import jax.numpy as jnp

        model = self._decode_model

        if self._use_adapters:
            def inner(cache, variables, ad, tokens, true_len, start,
                      slot, table_row, rows, seed):
                logits, mutated = model.apply(
                    {**variables, "cache": cache}, tokens,
                    train=False, decode=True,
                    decode_positions=start,
                    block_tables=table_row, decode_slots=slot,
                    mutable=["cache"],
                    adapters=_gather_adapter_rows(ad, rows),
                )
                last = jnp.take(logits[0], true_len - 1, axis=0)  # [V]
                # The first generated token sits at absolute position
                # start + true_len → its sampling counter (start is 0
                # for a from-scratch prefill, the resume depth for a
                # trie-tail or re-prefill — which is exactly why a
                # resumed stream redraws the SAME token here).
                return mutated["cache"], self._sample(
                    last[None], seed, start + true_len)[0]

            fn = self._tp_jit(inner, 7, n_model_args=1)
        else:
            def inner(cache, variables, tokens, true_len, start, slot,
                      table_row, seed):
                logits, mutated = model.apply(
                    {**variables, "cache": cache}, tokens,
                    train=False, decode=True,
                    decode_positions=start,
                    block_tables=table_row, decode_slots=slot,
                    mutable=["cache"],
                )
                last = jnp.take(logits[0], true_len - 1, axis=0)  # [V]
                return mutated["cache"], self._sample(
                    last[None], seed, start + true_len)[0]

            fn = self._tp_jit(inner, 6)
        self._prefill_jits[bucket] = fn
        return fn

    def _seq_prefill_fn(self, t_pad: int):
        """The (cached) sequence-parallel prefill program for one padded
        length ``t_pad`` (a bucket rounded up to the shard count — the
        compile count stays bounded by the bucket ladder).

        ONE ``shard_map`` over the mesh's ``model`` axis: tokens arrive
        sequence-sharded ``[1, t_pad/n]`` per shard; the resident TP
        param stacks are all-gathered and reassembled IN-PROGRAM
        (:func:`unshard_lm_params` — full weights exist only transiently,
        no 2x-params replica in HBM); each shard runs its slice through
        the base model with global rope/learned positions and
        ``sow_kv=True``; per layer, one ``all_to_all`` reshards the sown
        K/V heads<->sequence into exactly the TP cache layout (all
        positions x local kv heads) and scatters it at true positions
        (``paged_update`` redirects pad overhang to scratch; dense
        scatters drop out-of-bounds rows — the monolithic path's own
        staleness contract); the last TRUE position's logits are
        psum-selected across shards and fed to the same sampling tail
        as the monolithic prefill — greedy argmax at temperature 0, the
        counter-keyed sample (counter ``true_len``, every shard derives
        the identical replicated key) otherwise — for the first token.
        The cache is donated, so the chain hands off to decode without
        a copy."""
        if t_pad in self._seq_prefill_jits:
            return self._seq_prefill_jits[t_pad]
        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from chainermn_tpu.ops.paged_kv import paged_update

        base = self._seq_base_model
        base_model = self._base_model
        paged = self._alloc is not None

        def local(cache_st, vars_st, tokens, true_len, slot, table_row,
                  seed):
            cache = jax.tree.map(lambda a: a[0], cache_st)
            stacked = jax.tree.map(
                lambda a: jax.lax.all_gather(
                    a[0], "model", axis=0, tiled=False
                ),
                vars_st,
            )
            full = unshard_lm_params(base_model, stacked)
            Tl = tokens.shape[1]
            my = jax.lax.axis_index("model")
            pos = my * Tl + jnp.arange(Tl, dtype=jnp.int32)
            logits, mut = base.apply(
                full, tokens, positions=pos, train=False,
                mutable=["kv_out"],
            )
            # first generated token = the monolithic prefill's sampling
            # tail over the psum-assembled last-TRUE-position logits:
            # argmax at temperature 0, else the counter-keyed sample at
            # counter true_len (seed/true_len/psum row are replicated,
            # so every shard derives the identical key and token).
            j = true_len - 1
            row = jnp.where(
                (j // Tl) == my,
                logits[0, j % Tl].astype(jnp.float32), 0.0,
            )
            full_row = jax.lax.psum(row, "model")
            tok = self._sample(
                full_row[None], seed,
                jnp.reshape(true_len, (1,)).astype(jnp.int32),
            )[0].astype(jnp.int32)
            new_cache = dict(cache)
            for blk, kv in mut["kv_out"].items():
                entry = dict(cache[blk])
                for src, dst in (("k", "key"), ("v", "value")):
                    sh = jax.lax.all_to_all(
                        kv[src][0], "model", split_axis=2,
                        concat_axis=1, tiled=True,
                    )  # [1, t_pad, kvh/n, dh] — the TP cache layout
                    if paged:
                        pool = entry[f"pool_{dst}"]
                        entry[f"pool_{dst}"] = paged_update(
                            pool, table_row,
                            jnp.zeros((1,), jnp.int32),
                            sh.astype(pool.dtype),
                        )
                    else:
                        cols = jnp.arange(t_pad, dtype=jnp.int32)
                        entry[f"cached_{dst}"] = (
                            entry[f"cached_{dst}"]
                            .at[slot[:, None], cols[None, :]]
                            .set(sh.astype(entry[f"cached_{dst}"].dtype))
                        )
                new_cache[blk] = entry
            return jax.tree.map(lambda a: a[None], new_cache), tok

        fn = jax.jit(
            shard_map(
                local, mesh=self._mesh,
                in_specs=(P("model"), P("model"), P(None, "model"),
                          P(), P(), P(), P()),
                out_specs=(P("model"), P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )
        self._seq_prefill_jits[t_pad] = fn
        return fn

    # ------------------------------------------------------------------
    # serving surface

    @property
    def n_active(self) -> int:
        return int(self._active.sum())

    @property
    def free_slot_count(self) -> int:
        return len(self._free)

    @property
    def n_filling(self) -> int:
        """Slots admitted by ``chunked_join`` still writing prompt KV."""
        return len(self._pending_fill)

    def occupancy(self) -> float:
        return self.n_active / self.num_slots

    def pool_utilization(self) -> Optional[float]:
        return self._alloc.utilization() if self._alloc else None

    def _publish_pool_gauges(self) -> None:
        """Direct KV-pool gauges (ISSUE 6): the block allocator is
        state with no trace events — refresh free/leased on every
        mutation point (join / leave / per-step growth). One global
        read when the metrics plane is off."""
        from chainermn_tpu.observability import metrics

        reg = metrics.active_registry()
        if reg is None:
            return
        if self._alloc is None:
            self._publish_adapter_gauges(reg)
            return
        reg.gauge("kv_blocks_free",
                  "allocatable KV pool blocks currently free").set(
            self._alloc.free_blocks)
        reg.gauge("kv_blocks_leased",
                  "KV pool blocks owned by slots").set(
            self._alloc.blocks_in_use)
        if self._prefix is not None:
            reg.gauge("kv_blocks_shared",
                      "KV pool blocks referenced by more than one "
                      "slot's table (prefix sharing)").set(
                self._alloc.blocks_shared())
            reg.gauge("kv_blocks_cached",
                      "trie-cached KV blocks no slot references (an "
                      "upper bound on reclaimable — a live descendant "
                      "pins its cached ancestors)").set(
                self._alloc.blocks_cached())
        self._publish_adapter_gauges(reg)

    def _publish_adapter_gauges(self, reg) -> None:
        """Adapter-bank gauges (ISSUE 14): residency + per-tenant slot
        occupancy, tenant-labeled (the live-SLO surface;
        ``tools/metrics_dump.py --label tenant=<id>`` filters on
        exactly this label). No-op without a bank."""
        if self.adapter_bank is None:
            return
        reg.gauge("adapter_bank_residents",
                  "tenants with a registered adapter row").set(
            len(self.adapter_bank.residents()))
        reg.gauge("adapter_bank_free_rows",
                  "unclaimed adapter rows in the bank").set(
            self.adapter_bank.free_rows)
        counts: dict = {}
        for t in self._tenant_ids:
            if t is not None:
                counts[t] = counts.get(t, 0) + 1
        for t in self.adapter_bank.residents():
            reg.gauge("serving_tenant_active_slots",
                      "slots currently serving a tenant").set(
                counts.get(t, 0), tenant=str(t))

    def prefix_trie_blocks(self) -> Optional[int]:
        """Blocks held by the prefix trie (None when sharing is off) —
        the scheduler's trie-size gauge."""
        return self._prefix.n_nodes if self._prefix is not None else None

    def prefix_evictions(self) -> int:
        """Lifetime trie evictions (0 when sharing is off)."""
        return self._prefix.evictions if self._prefix is not None else 0

    def decode_compile_count(self) -> Optional[int]:
        """Compilations of the steady-state step (the no-recompile pin:
        must stay 1 across any join/leave churn)."""
        size = getattr(self._decode_step_jit, "_cache_size", None)
        return int(size()) if size else None

    def verify_compile_count(self) -> Optional[int]:
        """Compilations of the speculative verify step (same pin as the
        plain step: must stay 1 across churn AND acceptance variation).
        None when speculation is off or the runtime hides the cache."""
        if self._verify_step_jit is None:
            return None
        size = getattr(self._verify_step_jit, "_cache_size", None)
        return int(size()) if size else None

    def mixed_compile_count(self) -> Optional[int]:
        """Compilations of the chunked-prefill mixed step (the ISSUE 11
        pin: must stay 1 across every chunk/decode occupancy mix).
        None when chunking is off or the runtime hides the cache."""
        if self._mixed_step_jit is None:
            return None
        size = getattr(self._mixed_step_jit, "_cache_size", None)
        return int(size()) if size else None

    def prefill_compile_count(self) -> Optional[int]:
        sizes = [getattr(f, "_cache_size", None)
                 for f in self._prefill_jits.values()]
        if any(s is None for s in sizes):
            return None
        return int(sum(s() for s in sizes))

    def prefill_join(self, prompt, tenant_id: Optional[str] = None,
                     seed: Optional[int] = None):
        """Admit one request: claim a slot, run bucketed prefill, return
        ``(slot, first_token, bucket)`` — or None when no slot (or,
        paged, not enough pool blocks) is available right now (the
        scheduler retries later; host state is untouched on refusal).

        ``tenant_id`` (ISSUE 14) selects the slot's adapter row (the
        bank must hold the tenant — unknown tenants raise loudly rather
        than silently serve the base model) and namespaces the
        prefix-trie consultation: one tenant's cached blocks can never
        adopt into another's stream.

        ``seed`` is the request's sampling-stream seed (counter-based
        keys: token ``i`` draws with ``fold_in(fold_in(base_key, seed),
        i)``); ``None`` means stream 0. The scheduler derives one per
        request (``crc32(request_id)``) and re-passes the SAME value on
        resume/migration, which is what keeps a moved sampled stream
        ONE stream. Ignored at temperature 0.

        With the prefix cache on (ISSUE 7) the join first consults the
        trie: the longest matching FULL-block chain is adopted into the
        slot's table (refcounts, no copy) and the prefill runs only the
        unshared tail at its true start position — bucketed by the TAIL
        length, so a full-hit request's prefill shrinks to one token.
        The bucket of the RUN prefill is returned (the scheduler's
        event field measures exactly the work done). A full-block-exact
        hit re-feeds the last prompt token (logits need a forward), and
        the write at that boundary position triggers the copy-on-write
        path (:meth:`_cow_protect`) — the one place a shared block is
        ever written toward.
        """
        import jax.numpy as jnp

        res = self._admit_common(prompt, tenant_id, seed)
        if res is None:
            return None
        slot, prompt, P_len, tail_start, tail_len, _matched, _cow = res
        bucket = bucket_length(tail_len, self._buckets)
        self.last_prefill_seq_parallel = False

        # Sequence-parallel path (ISSUE 13): a cache-MISS prompt
        # (tail_start == 0 — on a trie hit the tail's context lives in
        # adopted blocks the sharded forward cannot see, so the
        # monolithic tail prefill runs; it is also already short) whose
        # shard-rounded bucket fits the horizon goes wide over the
        # 'model' partition.
        if self.prefill_seq_parallel and tail_start == 0:
            t_pad = -(-bucket // self._tp_n) * self._tp_n
            if t_pad <= self.max_len:
                return self._seq_prefill_run(
                    slot, prompt, P_len, tail_len, t_pad, bucket
                )

        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, :tail_len] = prompt[tail_start:]
        fn = self._prefill_fn(bucket)
        self._cache, tok = fn(*self._step_args(
            jnp.asarray(padded),
            jnp.int32(tail_len),
            jnp.full((1,), tail_start, jnp.int32),
            jnp.asarray([slot], jnp.int32),
            jnp.asarray(self._dummy_tables()[slot:slot + 1]),
            tail=(jnp.asarray(self._seeds[slot:slot + 1]),),
            tenant_rows=jnp.asarray(self._tenant_rows[slot:slot + 1]),
        ))
        tok = int(tok)
        self._positions[slot] = P_len
        self._last_tok[slot] = tok
        self._active[slot] = True
        self._history[slot] = [int(t) for t in prompt] + [tok]
        self._publish_full_blocks(slot, prompt, P_len)
        self._publish_pool_gauges()
        return slot, tok, bucket

    def _seq_prefill_run(self, slot, prompt, P_len, tail_len, t_pad,
                         bucket):
        """The sequence-parallel half of :meth:`prefill_join`: run the
        sharded forward (:meth:`_seq_prefill_fn`), then commit the SAME
        host metadata the monolithic join commits — the stream is
        indistinguishable downstream (that is the guarantee)."""
        import jax.numpy as jnp

        fn = self._seq_prefill_fn(t_pad)
        padded = np.full((1, t_pad), self.pad_id, np.int32)
        padded[0, :tail_len] = prompt
        self._cache, tok = fn(
            self._cache, self._vars, jnp.asarray(padded),
            jnp.int32(tail_len), jnp.asarray([slot], jnp.int32),
            jnp.asarray(self._dummy_tables()[slot:slot + 1]),
            jnp.asarray(self._seeds[slot:slot + 1]),
        )
        tok = int(tok)
        self._positions[slot] = P_len
        self._last_tok[slot] = tok
        self._active[slot] = True
        self._history[slot] = [int(t) for t in prompt] + [tok]
        self.last_prefill_seq_parallel = True
        self._publish_full_blocks(slot, prompt, P_len)
        self._publish_pool_gauges()
        return slot, tok, bucket

    def seq_prefill_compile_count(self) -> Optional[int]:
        """Compilations of the sequence-parallel prefill programs —
        bounded by the shard-rounded bucket ladder, like the monolithic
        prefill's. None when the path is off or the runtime hides the
        cache."""
        if not self._seq_prefill_jits:
            return None if not self.prefill_seq_parallel else 0
        sizes = [getattr(f, "_cache_size", None)
                 for f in self._seq_prefill_jits.values()]
        if any(s is None for s in sizes):
            return None
        return int(sum(s() for s in sizes))

    def _publish_full_blocks(self, slot: int, tokens,
                             n_positions: int) -> None:
        """Insert ``slot``'s FULL blocks covering the WRITTEN positions
        ``[0, n_positions)`` into the prefix trie — the ONE publish
        rule every path shares (prefill/fill completion, import_kv
        adoption, preemption): an adopted prefix walks existing nodes,
        only fresh full blocks add nodes, and the partial tail block is
        never inserted (the next write targets it). Inserts under the
        SLOT's tenant namespace (ISSUE 14): publication is as tenant-
        scoped as adoption, so cross-tenant block sharing is
        structurally impossible. No-op with sharing off."""
        if self._prefix is None:
            return
        bs = self._alloc.block_size
        full = int(n_positions) // bs
        if full:
            self._prefix.insert(
                [int(t) for t in tokens[:full * bs]],
                self._alloc.owned_blocks(slot)[:full],
                namespace=self._tenant_ids[slot],
            )

    def _admit_common(self, prompt, tenant_id: Optional[str] = None,
                      seed: Optional[int] = None):
        """Shared admission front half of :meth:`prefill_join` and
        :meth:`chunked_join`: validate the prompt (and, ISSUE 14, the
        tenant — its adapter row must be resident BEFORE any state
        mutates), consult the prefix trie under the TENANT's namespace,
        reserve the slot's pool blocks for the whole prompt plus
        the first decode write, COW-protect the unshared tail's
        boundary, commit the slot (tenant row + bank pin + sampling
        seed included) and account the admission. Returns
        ``(slot, prompt, P_len, tail_start, tail_len, matched, cow)``
        with the slot POPPED from the free list, or None to defer (host
        state untouched — the scheduler retries). ``last_prefix_info``
        is (re)set here, so both join flavours feed the same
        ``prefix_cache`` event."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        P_len = int(prompt.shape[0])
        if P_len < 1:
            raise ValueError("empty prompt")
        if P_len >= self.max_len:
            raise ValueError(
                f"prompt of {P_len} tokens leaves no room to generate "
                f"within max_len={self.max_len}"
            )
        row = 0
        if self.adapter_bank is not None:
            if self.adapter_impl == "merged":
                if tenant_id != self.merged_tenant:
                    raise ValueError(
                        f"this engine serves the merged tenant "
                        f"{self.merged_tenant!r} only — got "
                        f"{tenant_id!r} (route other tenants to a "
                        "gather-mode engine)"
                    )
            else:
                row = self.adapter_bank.row_of(tenant_id)
        if not self._free:
            return None
        slot = self._free[-1]  # peek; commit only after alloc succeeds
        self.last_prefix_info = None
        matched: list[int] = []
        if self._prefix is not None:
            matched = self._prefix.lookup(prompt, namespace=tenant_id)
            if len(matched) < self._min_shared_blocks:
                matched = []
        hit_tokens = len(matched) * (self._alloc.block_size
                                     if self._alloc else 0)
        # The tail must carry at least the LAST prompt token — its
        # logits sample the first generated token — so a hit covering
        # the whole prompt re-feeds one token into the boundary block.
        tail_start = min(hit_tokens, P_len - 1)
        tail_len = P_len - tail_start
        if self._alloc is not None:
            # Reserve only the REAL tokens plus the first decode write
            # (position P_len) — NOT the padded bucket: pad writes
            # beyond the reservation land in the scratch block by the
            # layout contract, and decode grows blocks incrementally,
            # so reserving bucket-width here would silently defeat the
            # oversubscription the pool exists for (review finding:
            # a prompt that falls back to the max_len bucket would
            # demand the whole horizon up front). Adoption precedes the
            # tail ensure (table order = position order); a refused
            # ensure rolls the adoption back via release — all-or-
            # nothing, as before.
            # A free slot's table row is all-scratch, so a rolled-back
            # deferral restores the EXACT prior table — restore the
            # version too, or every scheduler retry would invalidate
            # the engine's cached device tables and pay a full H2D
            # re-upload right after the decode loop's D2H (the
            # degradation trap the version key exists to avoid).
            v0 = self._alloc.version
            self._alloc.adopt(slot, matched)
            if not self._alloc.ensure(slot, P_len + 1):
                self._alloc.release(slot)
                self._alloc.version = v0
                return None
            # The boundary-block COW needs ONE block beyond ensure's
            # reservation; under genuine exhaustion defer the admission
            # (release rolls the adoption AND any copy back) — never an
            # error a cache-off engine wouldn't have raised.
            cow = self._cow_protect(slot, tail_start, tail_len,
                                    strict=False)
            if cow is None:
                self._alloc.release(slot)
                self._alloc.version = v0
                return None
        else:
            cow = 0
        self._free.pop()
        # Sampling-seed commit: the slot's counter-based key stream —
        # host metadata + one versioned H2D, like the tenant row below.
        self._set_slot_seed(slot, seed)
        # Tenant commit (ISSUE 14): the slot's adapter row + bank pin +
        # trie namespace — host metadata only, like everything above.
        self._tenant_ids[slot] = tenant_id
        if self._use_adapters:
            self.adapter_bank.pin(tenant_id)
            if self._tenant_rows[slot] != row:
                self._tenant_rows[slot] = row
                self._tenant_rows_ver += 1

        # Lifetime accounting covers ADMITTED requests only — a deferred
        # admission is retried by the scheduler, and counting each retry
        # would dilute the hit-rate gauge with duplicates.
        if self._prefix is not None:
            self.prefix_stats["lookups"] += 1
            self.prefix_stats["prompt_tokens"] += P_len
            self.prefix_stats["prefill_tokens"] += tail_len
        if matched:
            self.prefix_stats["hits"] += 1
            self.prefix_stats["hit_tokens"] += hit_tokens
        if self._prefix is not None:
            self.last_prefix_info = {
                "prompt_tokens": P_len,
                "hit_blocks": len(matched),
                "hit_tokens": hit_tokens,
                "prefill_tokens": tail_len,
                "cow_blocks": cow,
            }
        return slot, prompt, P_len, tail_start, tail_len, matched, cow

    def chunked_join(self, prompt, tenant_id: Optional[str] = None,
                     seed: Optional[int] = None):
        """Admit one request for CHUNKED prefill (``prefill_chunk > 0``,
        ISSUE 11): claim the slot and reserve its blocks EXACTLY like
        :meth:`prefill_join` — trie adoption, whole-prompt ensure,
        boundary-block COW — but run no forward here. The prompt's
        unshared tail is written ``prefill_chunk`` tokens per
        :meth:`mixed_step` tick while the remaining slots decode; the
        final chunk samples the first generated token and activates the
        slot. Returns the slot, or None to defer (host state untouched
        — the scheduler retries; same deferral contract as the
        monolithic join)."""
        if self.prefill_chunk <= 0:
            raise RuntimeError(
                "chunked_join needs prefill_chunk > 0 — use prefill_join"
            )
        res = self._admit_common(prompt, tenant_id, seed)
        if res is None:
            return None
        slot, prompt, P_len, tail_start, tail_len, _matched, _cow = res
        self._pending_fill[slot] = {
            "prompt": prompt, "pos": tail_start, "P_len": P_len,
            "chunks": 0,
        }
        self._publish_pool_gauges()
        return slot

    def decode_step(self):
        """One fused decode step over ALL slots. Returns ``(tokens,
        dur_s)`` — ``tokens[s]`` is slot ``s``'s next token (garbage for
        inactive slots; callers consult their own active set). Host
        metadata for active slots advances by one position."""
        import jax.numpy as jnp

        active = np.flatnonzero(self._active)
        for s in active:
            p = int(self._positions[s])
            if p + 1 > self.max_len:
                raise RuntimeError(
                    f"slot {int(s)} ran past the serving horizon "
                    f"max_len={self.max_len}; bound max_new_tokens"
                )
            if self._alloc is not None and not self._alloc.ensure(
                int(s), p + 1
            ):
                raise self._pool_exhausted_error()
            # COW guard (ISSUE 7): the write at position p must not land
            # in a block another slot or the trie still reads.
            self._cow_protect(int(s), p, 1)
        t0 = time.perf_counter()
        self._cache, toks = self._decode_step_jit(*self._step_args(
            jnp.asarray(self._last_tok, jnp.int32),
            jnp.asarray(self._positions, jnp.int32),
            self._tables_device(),
            tail=(self._seeds_device(),),
        ))
        toks = np.asarray(toks)  # device sync: honest per-step latency
        dur = time.perf_counter() - t0
        self._publish_pool_gauges()
        self._last_tok[active] = toks[active]
        self._positions[active] += 1
        for s in active:
            self._history[int(s)].append(int(toks[s]))
        return toks, dur

    def verify_step(self):
        """One speculative tick over ALL slots: draft up to K tokens per
        active slot from its own history, score every draft in ONE
        jitted verify forward, and commit the longest greedy-matching
        prefix plus the model's own next token.

        Returns ``(committed, dur_s, stats)``: ``committed[slot]`` is
        the list of 1..K+1 tokens slot ``slot`` advanced by this tick
        (every one of them a token the verify forward itself produced —
        argmax at temperature 0, the counter-keyed sample otherwise —
        so the stream is bit-identical to the plain path in BOTH modes;
        sampled acceptance is the rejection-sampling rule,
        :func:`~chainermn_tpu.serving.speculate.
        rejection_accept_length`); ``stats`` carries
        ``drafted``/``accepted`` token counts, the per-slot
        ``accept_lens`` and the sampling ``mode`` — the scheduler's
        ``speculate`` trace event.

        Rollback is HOST metadata only: rejected drafts leave their
        (stale) cache writes in place — positions are explicit, so the
        next tick's span ``[new_pos, new_pos+K]`` re-writes every stale
        row before any causal mask can admit it, and the jit cache stays
        pinned at one entry across churn and acceptance variation.
        Near the horizon (or when an oversubscribed paged pool cannot
        cover the whole span) acceptance is CAPPED, which costs
        throughput, never correctness.
        """
        import jax.numpy as jnp

        if self.spec_tokens <= 0:
            raise RuntimeError("verify_step needs spec_tokens > 0 — use "
                               "decode_step for the plain path")
        K = self.spec_tokens
        active = [int(s) for s in np.flatnonzero(self._active)]
        # Speculative block reservations are per-tick LEASES, not
        # commitments (review regression): an extension to p+K+1 holds
        # blocks for draft positions that may never be committed, and
        # letting those reservations accumulate across ticks — or
        # letting an earlier slot's optional extension grab the pool's
        # last blocks — would starve another slot of the plain-decode
        # minimum it needs just to make progress, turning a pool that
        # spec_tokens=0 serves fine into a crash. Three ordered passes
        # pin the degrade contract (caps cost throughput, never an
        # error plain decode would not raise):
        #   1. trim every active slot back to its committed frontier
        #      (p+1), returning earlier ticks' unused extensions;
        #   2. guarantee every slot the plain minimum — only genuine
        #      exhaustion (plain decode would also fail) raises;
        #   3. extend to the K-span where the remainder allows; a
        #      refused extension degrades that slot's room — drafted
        #      writes beyond the covered span land in the scratch block
        #      (unallocated table entries) and the acceptance cap keeps
        #      every COMMITTED token inside real blocks.
        if self._alloc is not None:
            for s in active:
                self._alloc.trim(s, int(self._positions[s]) + 1)
        for s in active:
            p = int(self._positions[s])
            if p + 1 > self.max_len:
                raise RuntimeError(
                    f"slot {s} ran past the serving horizon "
                    f"max_len={self.max_len}; bound max_new_tokens"
                )
            if self._alloc is not None and not self._alloc.ensure(
                s, p + 1
            ):
                raise self._pool_exhausted_error()
        room: dict[int, int] = {}
        for s in active:
            p = int(self._positions[s])
            covered = min(p + K + 1, self.max_len)
            if (self._alloc is not None and covered > p + 1
                    and not self._alloc.ensure(s, covered)):
                covered = p + 1
            room[s] = min(K, covered - p - 1, self.max_len - 1 - p)
            # COW guard (ISSUE 7): the whole verify span [p, p+room+1)
            # must write private blocks BEFORE the forward — a rejected
            # draft's stale write must never corrupt a shared ancestor
            # block (rollback stays host-metadata-only and composes).
            self._cow_protect(s, p, room[s] + 1)

        from chainermn_tpu.serving.speculate import (
            accept_length,
            rejection_accept_length,
        )

        accept = (rejection_accept_length if self.temperature > 0.0
                  else accept_length)
        drafts = np.zeros((self.num_slots, K), np.int64)
        prop_len: dict[int, int] = {}
        n_drafted = 0
        for s in active:
            # ask only for what could be accepted (room): near the
            # horizon a full-K proposal would be wasted drafter work
            # (K jitted forwards for a ModelDrafter) and would deflate
            # the accept-rate evidence the tuning cache stores.
            prop = list(
                self._drafter.propose(self._history[s], room[s])
            )[:room[s]]
            prop_len[s] = len(prop)
            n_drafted += len(prop)
            drafts[s, :len(prop)] = prop
        tokens = np.concatenate([self._last_tok[:, None], drafts], axis=1)

        t0 = time.perf_counter()
        self._cache, grid = self._verify_step_jit(*self._step_args(
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(self._positions, jnp.int32),
            self._tables_device(),
            tail=(self._seeds_device(),),
        ))
        grid = np.asarray(grid)  # device sync: honest tick latency
        dur = time.perf_counter() - t0

        committed: dict[int, list[int]] = {}
        accept_lens: list[int] = []
        n_accepted = 0
        for s in active:
            # acceptance never extends past the drafter's TRUE proposal
            # (a zero-padded verify column that happens to match the
            # model's own token would be a correct token, but crediting
            # it as "accepted speculation" would corrupt the tuning
            # signal).
            a = accept(drafts[s], grid[s],
                       min(room[s], prop_len[s]))
            toks = [int(t) for t in grid[s, :a + 1]]
            committed[s] = toks
            accept_lens.append(a)
            n_accepted += a
            self._history[s].extend(toks)
            self._last_tok[s] = toks[-1]
            self._positions[s] += a + 1
        stats = {"drafted": n_drafted, "accepted": n_accepted,
                 "accept_lens": accept_lens,
                 "mode": "sampled" if self.temperature > 0.0
                 else "greedy"}
        self._publish_pool_gauges()
        return committed, dur, stats

    def mixed_step(self, max_fill_rows: Optional[int] = None):
        """One fused chunk+decode tick over ALL slots (ISSUE 11
        tentpole). Fill rows (:meth:`chunked_join` admissions, FIFO)
        write their next ``prefill_chunk`` prompt tokens of KV at their
        true positions; active rows decode one token — or, with
        ``spec_tokens > 0``, draft-and-verify their span — in the SAME
        jitted forward (:meth:`_build_mixed_step`), so a long prompt's
        prefill no longer freezes every in-flight stream for a whole
        monolithic forward: per-tick interference is bounded by the
        chunk width.

        ``max_fill_rows`` caps how many fill rows advance this tick
        (the SLO scheduler's interference bound — host selection only,
        the compiled program never changes); stalled fill rows ride the
        grid as pad rows whose garbage writes land in their own
        reserved blocks and are re-written by the real chunk before
        any causal mask admits them (the speculative-rollback staleness
        argument).

        Returns ``(committed, fills, dur_s, spec_stats)``:
        ``committed[slot]`` = the decode tokens slot advanced by
        (1..K+1, every one a verify-grid token — argmax at temperature
        0, the counter-keyed sample otherwise — bit-identical to the
        plain stream in both modes); ``fills`` = one record per ADVANCED
        fill row
        (``slot``/``chunk`` index/``tokens`` written/``done`` and, on
        the final chunk, ``first_tok`` — the request's first generated
        token, sampled at the last prompt position exactly as the
        monolithic prefill would); ``spec_stats`` = the ``speculate``
        accounting (None when ``spec_tokens == 0``)."""
        import jax.numpy as jnp

        if self._mixed_step_jit is None:
            raise RuntimeError("mixed_step needs prefill_chunk > 0 — "
                               "use decode_step/verify_step")
        T, K, C = self._mixed_T, self.spec_tokens, self.prefill_chunk
        active = [int(s) for s in np.flatnonzero(self._active)]
        # Decode-side block discipline: verify_step's per-tick lease
        # rules verbatim at K > 0; the plain ensure at K == 0. (Fill
        # rows reserved their whole span at admission.)
        if self._alloc is not None and K > 0:
            for s in active:
                self._alloc.trim(s, int(self._positions[s]) + 1)
        for s in active:
            p = int(self._positions[s])
            if p + 1 > self.max_len:
                raise RuntimeError(
                    f"slot {s} ran past the serving horizon "
                    f"max_len={self.max_len}; bound max_new_tokens"
                )
            if self._alloc is not None and not self._alloc.ensure(
                s, p + 1
            ):
                raise self._pool_exhausted_error()
        room: dict[int, int] = {}
        for s in active:
            p = int(self._positions[s])
            if K > 0:
                covered = min(p + K + 1, self.max_len)
                if (self._alloc is not None and covered > p + 1
                        and not self._alloc.ensure(s, covered)):
                    covered = p + 1
                room[s] = min(K, covered - p - 1, self.max_len - 1 - p)
            else:
                room[s] = 0
            self._cow_protect(s, p, room[s] + 1)

        fill_slots = list(self._pending_fill)
        if max_fill_rows is not None:
            fill_slots = fill_slots[:max(0, int(max_fill_rows))]

        tokens = np.full((self.num_slots, T), self.pad_id, np.int64)
        positions = np.zeros(self.num_slots, np.int64)
        drafts = np.zeros((self.num_slots, max(K, 1)), np.int64)
        prop_len: dict[int, int] = {}
        n_drafted = 0
        for s in active:
            positions[s] = self._positions[s]
            tokens[s, 0] = self._last_tok[s]
            if K > 0:
                prop = list(
                    self._drafter.propose(self._history[s], room[s])
                )[:room[s]]
                prop_len[s] = len(prop)
                n_drafted += len(prop)
                for j, t in enumerate(prop):
                    drafts[s, j] = t
                    tokens[s, 1 + j] = t
        chunk_len: dict[int, int] = {}
        for s, st in self._pending_fill.items():
            # Stalled rows keep position = frontier with all-pad tokens:
            # their garbage lands in blocks the real chunk re-writes.
            positions[s] = st["pos"]
            if s in fill_slots:
                n = min(C, st["P_len"] - st["pos"])
                tokens[s, :n] = st["prompt"][st["pos"]:st["pos"] + n]
                chunk_len[s] = n

        t0 = time.perf_counter()
        self._cache, toks = self._mixed_step_jit(*self._step_args(
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            self._tables_device(),
            tail=(self._seeds_device(),),
        ))
        toks = np.asarray(toks)  # device sync: honest tick latency
        dur = time.perf_counter() - t0

        from chainermn_tpu.serving.speculate import (
            accept_length,
            rejection_accept_length,
        )

        accept = (rejection_accept_length if self.temperature > 0.0
                  else accept_length)
        committed: dict[int, list[int]] = {}
        accept_lens: list[int] = []
        n_accepted = 0
        for s in active:
            a = accept(
                drafts[s], toks[s], min(room[s], prop_len[s])
            ) if K > 0 else 0
            take = [int(t) for t in toks[s, :a + 1]]
            committed[s] = take
            if K > 0:
                accept_lens.append(a)
                n_accepted += a
            self._history[s].extend(take)
            self._last_tok[s] = take[-1]
            self._positions[s] += a + 1

        fills: list[dict] = []
        for s in fill_slots:
            st = self._pending_fill[s]
            n = chunk_len[s]
            st["pos"] += n
            st["chunks"] += 1
            done = st["pos"] >= st["P_len"]
            rec = {"slot": s, "chunk": st["chunks"] - 1, "tokens": n,
                   "done": done, "first_tok": None}
            if done:
                # The final chunk's last REAL column sits at position
                # P_len - 1: its grid token is the first generated
                # token, exactly what the monolithic prefill samples.
                first = int(toks[s, n - 1])
                prompt, P_len = st["prompt"], st["P_len"]
                del self._pending_fill[s]
                self._positions[s] = P_len
                self._last_tok[s] = first
                self._active[s] = True
                self._history[s] = [int(t) for t in prompt] + [first]
                self._publish_full_blocks(s, prompt, P_len)
                rec["first_tok"] = first
            fills.append(rec)
        stats = ({"drafted": n_drafted, "accepted": n_accepted,
                  "accept_lens": accept_lens,
                  "mode": "sampled" if self.temperature > 0.0
                  else "greedy"} if K > 0 else None)
        self._publish_pool_gauges()
        return committed, fills, dur, stats

    def preempt(self, slot: int) -> None:
        """Release ``slot`` mid-stream (the SLO scheduler's preemption
        hook, ISSUE 11), first publishing its WRITTEN full blocks into
        the prefix trie (when sharing is on) so a resumed request
        re-adopts its OWN KV through the ordinary trie-hit path and
        re-prefills only the partial tail block — resume costs one
        short prefill, not the whole history. Covers active slots AND
        in-progress chunked fills (their written chunks are cached
        too). Without the prefix cache the resume re-prefills the full
        history — slower, still bit-identical (greedy streams are
        deterministic, and sampled streams re-derive the same counter
        keys: the resumed prefill's first sample uses counter = the
        re-prefilled length, exactly the uninterrupted stream's counter
        at that position — provided the resume re-passes the request's
        ``seed``)."""
        pend = self._pending_fill.pop(slot, None)
        if pend is not None:
            self._publish_full_blocks(slot, pend["prompt"],
                                      int(pend["pos"]))
            self._release_slot(slot)
            return
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._publish_full_blocks(slot, self._history[slot],
                                  int(self._positions[slot]))
        self.leave(slot)

    # ------------------------------------------------------------------
    # cross-replica KV handoff (ISSUE 8): the engine-side hooks behind
    # chainermn_tpu.serving.cluster.kv_transfer — a prefill replica
    # EXPORTS a slot's finished KV as host numpy blocks, a decode
    # replica IMPORTS them into freshly-allocated blocks of its OWN
    # pool and adopts the slot metadata, so decode starts without
    # re-prefilling. Pure block slicing on the device plane (zero
    # collectives, structurally pinned); everything else is host state.

    def prefix_match_depth(self, prompt,
                           tenant_id: Optional[str] = None) -> int:
        """FULL blocks of ``prompt`` this engine's prefix trie holds
        UNDER ``tenant_id``'s namespace (ISSUE 14) — the router's
        cache-aware placement signal (read-only probe, no LRU touch).
        0 when prefix sharing is off."""
        if self._prefix is None:
            return 0
        return self._prefix.match_depth(
            [int(t) for t in np.asarray(prompt).reshape(-1)],
            namespace=tenant_id,
        )

    def expert_signature(self) -> Optional[tuple]:
        """MoE residency signature (ISSUE 20): ``None`` for a dense
        engine, ``(n_experts, experts_per_shard)`` when this engine's
        mesh hosts the model's expert shards. The router compares
        signatures the way it compares ``kv_signature`` — a dense
        replica cannot serve MoE traffic (it has no expert weights at
        all), so residency is a hard placement filter, not a score."""
        if self.n_experts <= 0:
            return None
        n = (int(self._mesh.shape["model"])
             if self._mesh is not None else 1)
        return (self.n_experts, self.n_experts // n)

    # ------------------------------------------------------------------
    # multi-tenant adapter surface (ISSUE 14)

    def adapter_resident(self, tenant_id: Optional[str]) -> bool:
        """Whether this engine can serve ``tenant_id`` RIGHT NOW — the
        router's adapter-residency placement signal. Bank-less engines
        serve every tenant (base model + namespace isolation only);
        merged engines serve exactly their folded tenant."""
        if self.adapter_bank is None:
            return True
        if self.adapter_impl == "merged":
            return tenant_id == self.merged_tenant
        return self.adapter_bank.resident(tenant_id)

    def _on_adapter_change(self, tenant_id: str) -> None:
        """Bank change hook (ISSUE 14 review finding): cached KV under
        ``tenant_id``'s trie namespace was computed with the PREVIOUS
        weights — a join after a re-registration must re-prefill under
        the current stacks, never adopt stale-adapter blocks (the
        bit-equivalence anchor would silently break)."""
        prefix = getattr(self, "_prefix", None)
        if prefix is not None:
            prefix.drop_namespace(tenant_id)

    def register_adapter(self, tenant_id: str, adapter=None) -> int:
        """Register a tenant on the bank (``adapter=None`` = a zero-
        adapter tenant riding the null row) and refresh the gauges.
        Returns the bank row. The NEXT step's cached upload picks the
        new stacks up (``bank.version``); the compiled programs never
        change — registration churn is host metadata + one H2D."""
        if self.adapter_bank is None:
            raise RuntimeError("this engine has no adapter_bank")
        if self.adapter_impl == "merged":
            raise RuntimeError(
                "a merged engine's weights are folded at construction "
                "— register tenants on a gather-mode engine"
            )
        row = self.adapter_bank.register(tenant_id, adapter)
        self._publish_pool_gauges()
        return row

    def evict_adapter(self, tenant_id: str) -> None:
        """Evict a tenant's row (refused while any slot serves it —
        the bank's refcount contract) and refresh the gauges."""
        if self.adapter_bank is None:
            raise RuntimeError("this engine has no adapter_bank")
        self.adapter_bank.evict(tenant_id)
        self._publish_pool_gauges()

    def tenant_of_slot(self, slot: int) -> Optional[str]:
        """The tenant occupying ``slot`` (None = base/unoccupied)."""
        return self._tenant_ids[slot]

    def kv_blocks_free(self) -> Optional[int]:
        """Free paged-pool blocks (None under dense) — the same number
        the ``kv_blocks_free`` gauge publishes; the router reads it
        before placing work."""
        return self._alloc.free_blocks if self._alloc is not None else None

    def kv_signature(self) -> tuple:
        """Layout fingerprint two engines must share for KV blocks to
        be portable between their pools: decode impl, paged block
        size, and every cache leaf's shape-minus-the-block-axis plus
        dtype (the block axis is ``ndim - 4`` — the pool's block count
        for paged, the slot axis for dense — and MAY differ between
        replicas; a TP stack's leading shard axis is part of the shape,
        so differing TP degrees refuse loudly)."""
        import jax

        leaves = jax.tree.leaves(self._cache)
        axis_sig = tuple(
            (leaf.shape[:leaf.ndim - 4] + leaf.shape[leaf.ndim - 3:],
             str(leaf.dtype))
            for leaf in leaves
        )
        return (self.decode_impl,
                self._alloc.block_size if self._alloc else None,
                self.max_len, axis_sig)

    def _kv_io(self):
        """The two (lazily built) handoff programs: ``extract(cache,
        blk)`` gathers one block across every pool leaf, ``inject
        (cache, blk, payload)`` scatters one serialized block back.
        No axis primitive anywhere, so ZERO collectives (the
        structural test compiles both and counts) — under TP they
        still ride a ``shard_map`` so the cache keeps its mesh
        sharding through the donation: a plain jit would return
        default-sharded leaves and the next decode step would
        RECOMPILE (caught live by dryrun phase J's compile-count pin);
        each shard simply slices its own block piece. The inject
        donates the cache: adoption never reallocates."""
        if self._kv_extract_jit is None:
            import jax

            from chainermn_tpu.ops.paged_kv import extract_block, \
                inject_block

            if self._mesh is None:
                self._kv_extract_jit = jax.jit(
                    lambda cache, blk: jax.tree.map(
                        lambda pool: extract_block(pool, blk), cache))
                self._kv_inject_jit = jax.jit(
                    lambda cache, blk, payload: jax.tree.map(
                        lambda pool, p: inject_block(pool, blk, p),
                        cache, payload),
                    donate_argnums=(0,),
                )
            else:
                from jax import shard_map
                from jax.sharding import PartitionSpec as P

                mesh = self._mesh

                def ex_local(cache, blk):
                    cache = jax.tree.map(lambda a: a[0], cache)
                    out = jax.tree.map(
                        lambda pool: extract_block(pool, blk), cache)
                    return jax.tree.map(lambda a: a[None], out)

                def in_local(cache, blk, payload):
                    cache = jax.tree.map(lambda a: a[0], cache)
                    payload = jax.tree.map(lambda a: a[0], payload)
                    out = jax.tree.map(
                        lambda pool, p: inject_block(pool, blk, p),
                        cache, payload)
                    return jax.tree.map(lambda a: a[None], out)

                self._kv_extract_jit = jax.jit(shard_map(
                    ex_local, mesh=mesh, in_specs=(P("model"), P()),
                    out_specs=P("model"), check_vma=False,
                ))
                self._kv_inject_jit = jax.jit(
                    shard_map(
                        in_local, mesh=mesh,
                        in_specs=(P("model"), P(), P("model")),
                        out_specs=P("model"), check_vma=False,
                    ),
                    donate_argnums=(0,),
                )
        return self._kv_extract_jit, self._kv_inject_jit

    def export_kv(self, slot: int) -> dict:
        """Serialize ``slot``'s written KV + stream metadata for
        adoption by another engine (:meth:`import_kv`). Paged engines
        ship only the blocks covering the written positions ``[0,
        position)``; dense engines ship the slot's whole ring row (one
        "block" — the honest cost of disaggregating a dense layout,
        and the reason the paged impl is the cluster default). The
        export only READS (the slot stays live — callers that hand the
        stream off ``leave()`` afterwards); trailing in-block garbage
        travels as-is and stays masked by positions on both sides."""
        import jax

        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        extract, _ = self._kv_io()
        import jax.numpy as jnp

        pos = int(self._positions[slot])
        if self._alloc is not None:
            bs = self._alloc.block_size
            phys = self._alloc.owned_blocks(slot)[:-(-pos // bs)]
        else:
            phys = [slot]
        # Dispatch every block's extract asynchronously, then ONE
        # device_get for the whole payload: a per-block np.asarray
        # would be a blocking D2H per leaf per block — the exact
        # tunnelled-TPU round-trip trap the version-keyed tables exist
        # to avoid (review finding).
        device_blocks = [
            jax.tree.leaves(extract(self._cache, jnp.int32(b)))
            for b in phys
        ]
        blocks = jax.device_get(device_blocks)
        return {
            "schema": 1,
            "signature": self.kv_signature(),
            "tokens": list(self._history[slot]),
            "position": pos,
            "last_tok": int(self._last_tok[slot]),
            "tenant": self._tenant_ids[slot],
            # The request's sampling seed rides the payload (read with
            # .get — schema stays 1, older payloads mean stream 0): the
            # importer re-derives the SAME counter keys, so a moved
            # sampled stream stays ONE stream bit-identically.
            "seed": int(self._seeds[slot]),
            "blocks": blocks,
            "nbytes": sum(a.nbytes for blk in blocks for a in blk),
        }

    def import_kv(self, payload: dict):
        """Adopt an :meth:`export_kv` payload: claim a slot, allocate
        covering blocks from THIS pool (fresh ids — the source's block
        numbering never leaks across allocators; refcounts start at 1
        here, so a release on either side can never corrupt the
        other), inject the serialized blocks, and restore the stream
        metadata so the next ``decode_step`` continues the stream
        bit-identically. Returns ``(slot, last_tok)``, or None when no
        slot / not enough pool right now (state untouched — the router
        retries, the deferred-admission contract). A layout mismatch
        raises: silently adopting foreign-shaped KV would corrupt
        streams, not degrade them. With prefix sharing on, the
        adopted FULL blocks are inserted into this engine's trie —
        followers of the same prefix hit locally without their own
        transfer."""
        import jax

        if payload.get("schema") != 1:
            raise ValueError(
                f"unknown kv payload schema {payload.get('schema')!r}")
        if tuple(payload["signature"]) != self.kv_signature():
            raise ValueError(
                "kv payload layout mismatch: source "
                f"{payload['signature']} vs target {self.kv_signature()} "
                "— replicas must share decode_impl/kv_block_size/"
                "max_len/model shape/TP degree"
            )
        pos = int(payload["position"])
        if pos + 1 > self.max_len:
            raise ValueError(
                f"payload position {pos} leaves no room within "
                f"max_len={self.max_len}"
            )
        # Tenant validation BEFORE any state mutates (ISSUE 14): an
        # adopted stream keeps decoding under its tenant's delta, so
        # the adapter must be resident HERE too.
        tenant = payload.get("tenant")
        row = 0
        if self.adapter_bank is not None:
            if self.adapter_impl == "merged":
                if tenant != self.merged_tenant:
                    raise ValueError(
                        f"merged engine serves {self.merged_tenant!r} "
                        f"only — payload carries tenant {tenant!r}"
                    )
            else:
                try:
                    row = self.adapter_bank.row_of(tenant)
                except KeyError as e:
                    raise ValueError(
                        f"kv payload tenant {tenant!r} has no resident "
                        "adapter on the importing engine — register it "
                        "before streaming"
                    ) from e
        if not self._free:
            return None
        slot = self._free[-1]  # peek; commit only after alloc succeeds
        if self._alloc is not None:
            if not self._alloc.ensure(slot, pos + 1):
                return None  # all-or-nothing: nothing was adopted yet
            bs = self._alloc.block_size
            targets = self._alloc.owned_blocks(slot)[:-(-pos // bs)]
        else:
            targets = [slot]
        if len(targets) != len(payload["blocks"]):
            # structurally impossible when signatures match — guard
            # against a truncated payload before touching the cache
            if self._alloc is not None:
                self._alloc.release(slot)
            raise ValueError(
                f"payload carries {len(payload['blocks'])} blocks, "
                f"target needs {len(targets)}"
            )
        import jax.numpy as jnp

        _, inject = self._kv_io()
        treedef = jax.tree.structure(self._cache)
        try:
            for tgt, leaves in zip(targets, payload["blocks"]):
                block_tree = jax.tree.unflatten(
                    treedef, [jnp.asarray(a) for a in leaves]
                )
                self._cache = inject(self._cache, jnp.int32(tgt),
                                     block_tree)
        except Exception:
            # Failed mid-injection (device OOM and kin): the slot was
            # never committed — return its reserved blocks so the
            # allocator stays consistent (written garbage is
            # unreachable once the table points back at scratch).
            if self._alloc is not None:
                self._alloc.release(slot)
            raise
        self._free.pop()
        self._positions[slot] = pos
        self._last_tok[slot] = int(payload["last_tok"])
        self._active[slot] = True
        self._history[slot] = [int(t) for t in payload["tokens"]]
        self._set_slot_seed(slot, payload.get("seed"))
        self._tenant_ids[slot] = tenant
        if self._use_adapters:
            self.adapter_bank.pin(tenant)
            if self._tenant_rows[slot] != row:
                self._tenant_rows[slot] = row
                self._tenant_rows_ver += 1
        # KV exists for tokens[:pos]; cache the FULL blocks (the shared
        # publish rule — partial tails never inserted).
        self._publish_full_blocks(slot, self._history[slot], pos)
        self._publish_pool_gauges()
        return slot, int(payload["last_tok"])

    def leave(self, slot: int) -> None:
        """Release a slot (host metadata + paged blocks only — the
        compiled program and the device cache are untouched; stale
        writes land in the slot's own rows or the scratch block)."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._release_slot(slot)

    def _release_slot(self, slot: int) -> None:
        """The ONE slot-release body :meth:`leave` and the mid-fill
        branch of :meth:`preempt` share (free list, history, paged
        blocks, tenant row/pin, gauges) — release-side accounting added
        here reaches both paths."""
        self._active[slot] = False
        self._free.append(int(slot))
        self._history[int(slot)] = []
        if self._alloc is not None:
            self._alloc.release(int(slot))
        # Tenant release (ISSUE 14): unpin the bank row and point the
        # slot back at the null adapter — a reused slot must never
        # gather a departed tenant's delta.
        if self._tenant_ids[slot] is not None:
            if self._use_adapters:
                self.adapter_bank.unpin(self._tenant_ids[slot])
            self._tenant_ids[slot] = None
        if self._tenant_rows[slot] != 0:
            self._tenant_rows[slot] = 0
            self._tenant_rows_ver += 1
        # Seed hygiene: a reused slot must never sample on a departed
        # request's stream (admission always rewrites, but garbage rows
        # also feed the grid programs for inactive slots).
        self._set_slot_seed(slot, 0)
        self._publish_pool_gauges()
