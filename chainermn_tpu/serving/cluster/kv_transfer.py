"""KV-block streaming between serving replicas (ISSUE 8 — the creative
step of disaggregated prefill/decode).

A prefill replica runs the bucketed prefill, then its slot's finished
KV blocks are serialized (``ServingEngine.export_kv``: per-block device
gathers — zero collectives — D2H'd to numpy) and streamed to a decode
replica, whose ``import_kv`` allocates covering blocks from its OWN
``BlockAllocator`` (fresh physical ids, refcount 1 — the source's block
numbering never crosses the wire, so a release on either side can never
corrupt the other) and injects the payload, and decode starts without
re-prefilling. The payload also carries the request's sampling ``seed``
(counter-based keys, docs/serving.md "Sampling"): the decode replica
re-derives the identical per-position keys, so a disaggregated SAMPLED
stream is bit-identical to a single-replica one — the same guarantee
the greedy path gets from determinism. HiCCL (2408.05962) and The Big Send-off (2504.18658)
argue exactly this: the cross-level transfer is a first-class,
topology-aware plane — here it gets its own module, its own trace
event, and its own byte accounting instead of being an engine side
effect.

Two planes:

- **Host plane (production).** Any object with ``send_obj``/
  ``recv_obj`` carries payloads — :class:`~chainermn_tpu.native
  .tcp_comm.TcpHostComm`/``TcpGroupComm`` across processes (per-pair
  FIFO, the property the pending-handoff queues lean on), or the
  in-process :class:`LoopbackHub` for single-process clusters and
  tests. Replicas keep independent compiled programs; the handoff adds
  **no HLO collectives anywhere** (structural pin in
  ``tests/test_cluster.py``).
- **In-mesh rehearsal.** When replicas share one mesh, the same block
  pytree can ride ICI: :func:`mesh_stream_blocks` wraps
  :func:`chainermn_tpu.functions.point_to_point.stream_blocks` (one
  ``lax.ppermute`` per leaf) so the device path is exercised and
  measured, not asserted — it is NOT the production path (a device
  collective would couple the replicas' programs).

Every successful handoff is one ``kv_transfer`` trace event
(``docs/observability.md``): request, src/dst replica, nbytes, block
count, ``dur_s`` (export → adoption — the latency inside the
disaggregated TTFT).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional


class LoopbackHub:
    """In-process transport hub mirroring the host-plane p2p surface:
    ``endpoint(rank)`` returns an object with ``send_obj``/``recv_obj``/
    ``probe`` over per-pair FIFO deques — the single-process cluster's
    stand-in for ``TcpHostComm`` (same interface, same ordering
    guarantee), so the router's transfer path is identical code
    whether replicas share a process or not."""

    def __init__(self) -> None:
        self._chans: dict = {}

    def _chan(self, src: int, dst: int) -> deque:
        return self._chans.setdefault((int(src), int(dst)), deque())

    def endpoint(self, rank: int) -> "LoopbackEndpoint":
        return LoopbackEndpoint(self, int(rank))


class LoopbackEndpoint:
    def __init__(self, hub: LoopbackHub, rank: int) -> None:
        self._hub = hub
        self.rank = rank

    def send_obj(self, obj: Any, dest: int) -> None:
        self._hub._chan(self.rank, dest).append(obj)

    def recv_obj(self, source: int) -> Any:
        chan = self._hub._chan(source, self.rank)
        if not chan:
            # Same-process loopback: a blocking wait here would be a
            # self-deadlock by construction — surface the protocol bug.
            raise LookupError(
                f"loopback recv from {source}: nothing pending "
                "(send before recv on an in-process hub)"
            )
        return chan.popleft()

    def probe(self, source: int) -> bool:
        return bool(self._hub._chan(source, self.rank))


def send_kv(transport, payload: dict, dest: int) -> int:
    """Ship one ``export_kv`` payload over the host plane (pickled by
    the transport — numpy blocks travel as-is). Returns the payload's
    block bytes (the wire accounting the router rolls up)."""
    transport.send_obj(payload, dest)
    return int(payload["nbytes"])


def recv_kv(transport, source: int) -> dict:
    """Receive one payload from ``source`` (blocking on the TCP plane;
    per-pair FIFO means it is the next one the peer sent)."""
    payload = transport.recv_obj(source)
    if not isinstance(payload, dict) or payload.get("schema") != 1:
        raise ValueError(
            f"kv_transfer: unexpected payload from rank {source}: "
            f"{type(payload).__name__}"
        )
    return payload


def transfer_kv(src_engine, dst_engine, slot: int, *,
                transport_src=None, transport_dst=None,
                src: int = 0, dst: int = 1,
                release: bool = True) -> Optional[tuple]:
    """One whole handoff, in-process: export ``slot`` from
    ``src_engine``, optionally round-trip the payload through a
    transport pair (loopback realism / byte accounting on the real
    plane), adopt into ``dst_engine``. Returns ``(new_slot, last_tok,
    nbytes, dur_s)`` or None when the destination cannot place it
    right now (source slot is left UNRELEASED in that case so nothing
    is lost — the caller retries or routes elsewhere).

    The router uses the split halves (export → queue → adopt) so a
    full destination defers instead of blocking; this fused form is
    the unit-test / notebook surface.
    """
    t0 = time.perf_counter()
    payload = src_engine.export_kv(slot)
    if transport_src is not None:
        send_kv(transport_src, payload, dst)
        payload = recv_kv(transport_dst, src)
    res = dst_engine.import_kv(payload)
    if res is None:
        return None
    if release:
        src_engine.leave(slot)
    new_slot, tok = res
    return new_slot, tok, int(payload["nbytes"]), time.perf_counter() - t0


def mesh_stream_blocks(blocks, src: int, dst: int, mesh,
                       axis_name: str = "replica"):
    """The in-mesh rehearsal: move a ``[n, ...]``-stacked block pytree
    from mesh shard ``src`` to shard ``dst`` in ONE jitted program
    (``lax.ppermute`` per leaf via
    :func:`~chainermn_tpu.functions.point_to_point.stream_blocks`).
    Returns the stacked pytree with ``dst``'s slice holding ``src``'s
    payload and zeros elsewhere — the caller slices its shard out.
    Rehearsal-only (see module docstring): the production handoff is
    host-plane by contract."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.functions.point_to_point import stream_blocks

    def local(tree):
        tree = jax.tree.map(lambda a: a[0], tree)
        out = stream_blocks(tree, src, dst, axis_name)
        return jax.tree.map(lambda a: a[None], out)

    fn = jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(axis_name),),
        out_specs=P(axis_name), check_vma=False,
    ))
    return fn(blocks)
