"""Serving replicas: N independent ``ServingEngine`` + ``Scheduler``
pairs over a ``replica × model`` device partition (ISSUE 8).

The single-engine ceiling was one slot array; a replica set keeps the
engine contract COMPLETELY unchanged — each replica owns its own
compiled programs, its own paged pool, its own prefix trie — and
scales by topology instead: replica ``r`` gets the device slice
``devices[r*tp : (r+1)*tp]`` as its own ``('model',)`` mesh, so
tensor-parallel decode inside a replica stays pinned at 2 all-reduces
per layer (the PR 4 HLO-count test re-asserted on a cluster replica in
``tests/test_cluster.py``) and NOTHING couples replicas on the device
plane — cross-replica traffic is host-plane only (the router and
``kv_transfer``).

The reference's whole inference surface was a per-sentence loop
(``examples/seq2seq/seq2seq.py`` †) — everything here is new-subsystem
territory; the partition shape follows the ROADMAP's "millions of
users is a topology question" framing.
"""

from __future__ import annotations

from typing import Optional, Sequence

#: what work a replica accepts from the router (disaggregated mode):
#: ``both`` = colocated prefill+decode, ``prefill`` = runs bucketed
#: prefills and streams the KV out, ``decode`` = adopts streamed KV
#: and decodes.
ROLES = ("both", "prefill", "decode")


class Replica:
    """One engine + scheduler under a router: identity (``replica_id``
    — the ``rank`` label on its gauges/events), role, and the load /
    cache signals the router's placement consults."""

    def __init__(self, engine, scheduler, replica_id: int,
                 role: str = "both") -> None:
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.engine = engine
        self.scheduler = scheduler
        self.replica_id = int(replica_id)
        self.role = role
        self.alive = True

    # ---- routing signals --------------------------------------------

    def load(self) -> int:
        """Queued + in-flight + mid-fill requests on this replica's
        scheduler — the least-loaded policy's primary signal (chunked
        admissions occupy a slot before their first token, ISSUE 11)."""
        return (self.scheduler.pending + self.scheduler.in_flight
                + getattr(self.scheduler, "filling", 0))

    def slots_free(self) -> int:
        return self.engine.free_slot_count

    def kv_blocks_free(self) -> Optional[int]:
        """Free paged-pool blocks (None under dense) — the PR 6
        ``kv_blocks_free`` gauge, read directly from engine state."""
        return self.engine.kv_blocks_free()

    def prefix_hit_blocks(self, prompt, tenant_id=None) -> int:
        """FULL blocks of ``prompt`` this replica's prefix trie already
        holds under ``tenant_id``'s namespace (read-only probe) — the
        cache-aware placement signal: a deeper hit means less prefill
        work HERE than anywhere else."""
        return self.engine.prefix_match_depth(prompt,
                                              tenant_id=tenant_id)

    def adapter_resident(self, tenant_id) -> bool:
        """Whether this replica can serve ``tenant_id`` right now
        (ISSUE 14) — the router's adapter-residency placement signal.
        Engines without a bank serve everyone (base model)."""
        fn = getattr(self.engine, "adapter_resident", None)
        return bool(fn(tenant_id)) if callable(fn) else True

    def expert_signature(self):
        """This replica's MoE residency signature (ISSUE 20): ``None``
        for a dense engine, ``(n_experts, experts_per_shard)`` when its
        mesh hosts the model's expert shards — the router's hard
        placement filter (the adapter-residency pattern: a replica
        without the expert weights cannot serve MoE traffic at all)."""
        fn = getattr(self.engine, "expert_signature", None)
        return fn() if callable(fn) else None

    def experts_resident(self, signature) -> bool:
        """Whether this replica hosts exactly the fleet's expert shards
        (``signature`` from :meth:`expert_signature`). Dense fleets
        (``signature is None``) accept every replica."""
        return signature is None or self.expert_signature() == signature

    # ---- drive ------------------------------------------------------

    def tick(self) -> bool:
        return self.scheduler.tick()

    @property
    def drained(self) -> bool:
        return self.scheduler.drained

    def summary(self) -> dict:
        return self.scheduler.summary()

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return (f"Replica(id={self.replica_id}, role={self.role}, "
                f"load={self.load()}, alive={self.alive})")


def make_replicas(model, params, n_replicas: int, *, tp: int = 1,
                  devices: Optional[Sequence] = None,
                  policy: str = "prefill_priority",
                  roles: Optional[Sequence[str]] = None,
                  **engine_kw) -> list[Replica]:
    """Build ``n_replicas`` engine+scheduler pairs over a ``replica ×
    model`` partition of ``devices``.

    ``tp >= 2``: replica ``r`` owns ``devices[r*tp:(r+1)*tp]`` as its
    ``('model',)`` mesh — tensor-parallel decode inside the replica,
    full device-plane isolation between replicas (raises when the
    device pool cannot cover ``n_replicas * tp``). ``tp == 1``:
    engines run unmeshed on the default device (same-process replicas
    then overlap through async dispatch only — the CPU-proxy/bench
    honest floor; give each replica real chips via ``tp``).

    ``roles`` (optional, per replica — default all ``'both'``) feeds
    the router's disaggregated mode. Remaining kwargs go to every
    ``ServingEngine`` verbatim (one config, N replicas: ``import_kv``
    refuses mismatched layouts loudly, so heterogeneous clusters must
    be assembled by hand, eyes open).
    """
    import numpy as np
    from jax.sharding import Mesh

    from chainermn_tpu.serving.engine import ServingEngine
    from chainermn_tpu.serving.scheduler import Scheduler

    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    if roles is not None and len(roles) != n_replicas:
        raise ValueError(
            f"roles covers {len(roles)} replicas, need {n_replicas}")
    if tp > 1:
        import jax

        devices = list(devices) if devices is not None else jax.devices()
        need = n_replicas * tp
        if len(devices) < need:
            raise ValueError(
                f"replica × model partition needs {need} devices "
                f"({n_replicas} replicas × tp={tp}), have {len(devices)}"
            )
    replicas = []
    for r in range(n_replicas):
        mesh = None
        if tp > 1:
            mesh = Mesh(np.array(devices[r * tp:(r + 1) * tp]),
                        ("model",))
        engine = ServingEngine(model, params, mesh=mesh, **engine_kw)
        replicas.append(Replica(
            engine, Scheduler(engine, policy=policy), r,
            role=roles[r] if roles is not None else "both",
        ))
    return replicas
