"""Multicast tree fan-out over the host p2p plane (ISSUE 16).

The serving plane's one-to-many paths — pushing a tenant adapter to N
replicas, warming N prefix tries from one prefilled donor — previously
cost the donor ``N-1`` sequential ``send_obj`` calls: the donor's
egress is the bottleneck and delivery latency is linear in the fleet.
A radix-``r`` multicast tree (the host-plane rendering of the ``bc``
stage in :mod:`chainermn_tpu.parallel.composition` — same
holder-doubling walk, same :func:`~chainermn_tpu.parallel.composition.
tree_depth`/:func:`~chainermn_tpu.parallel.composition.tree_sends`
arithmetic) delivers in ``ceil(log_r N)`` rounds: every member that
already holds the payload forwards it to up to ``r-1`` new members per
round, so the donor pays at most ``(r-1)·ceil(log_r N)`` sends — O(log
N) — and total wire sends stay ``N-1`` (every non-root receives exactly
once), just spread across the fleet instead of piled on the donor.

The transport contract is the existing one: anything with
``send_obj``/``recv_obj`` (``TcpHostComm`` across processes,
:class:`~chainermn_tpu.serving.cluster.kv_transfer.LoopbackHub` in
process). :func:`tree_push` is the HOST-ORCHESTRATED single-process
form — sends are issued strictly before their receives in topological
round order, which is exactly the ordering a per-rank distributed
driver would realize, and the in-process hub's recv-before-send
``LookupError`` makes any ordering bug loud instead of deadlocked.

Every push emits one ``tree_push`` trace event (``docs/
observability.md``): payload kind, fleet size, radix, rounds, total /
donor / sequential-baseline send counts, payload bytes.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

import numpy as np

from chainermn_tpu.observability import journey as _journey
from chainermn_tpu.observability import trace as _trace
from chainermn_tpu.parallel.composition import (
    DEFAULT_RADIX,
    tree_depth,
    tree_sends,
)


def tree_rounds(
    n: int, radix: int = DEFAULT_RADIX
) -> list[list[tuple[int, int]]]:
    """The tree's send schedule in COORDINATE space (0 = root):
    ``rounds[t]`` is the list of ``(src, dst)`` pairs of round ``t``,
    topologically ordered (every ``src`` holds the payload before round
    ``t`` starts). ``len(rounds) == tree_depth(n, radix)`` and the
    total pair count is ``n - 1`` (each non-root receives exactly
    once) — the same walk :func:`~chainermn_tpu.parallel.collectives.
    staged_broadcast` compiles to ppermutes."""
    n, r = int(n), int(radix)
    if r < 2:
        raise ValueError(f"radix must be >= 2, got {radix}")
    rounds: list[list[tuple[int, int]]] = []
    holders = 1
    while holders < n:
        pairs = [
            (s, s + j * holders)
            for j in range(1, r)
            for s in range(holders)
            if s + j * holders < n
        ]
        rounds.append(pairs)
        holders *= r
    return rounds


def tree_push(
    payload: Any,
    endpoints: Mapping[int, Any],
    ranks: Sequence[int],
    *,
    root: Optional[int] = None,
    radix: int = DEFAULT_RADIX,
    payload_kind: str = "object",
    nbytes: Optional[int] = None,
) -> tuple[dict[int, Any], dict]:
    """Deliver ``payload`` from ``root`` to every rank in ``ranks``
    along the radix-``radix`` tree. ``endpoints[rank]`` must expose
    ``send_obj(obj, dest)``/``recv_obj(source)`` for every
    participating rank. Forwarders relay the object THEY received
    (store-and-forward — exactly what a per-process driver would hold),
    so a transport that copies on the wire yields independent replicas
    of the payload, never N aliases of the donor's buffers.

    Returns ``(received, stats)``: ``received[rank]`` is what ``rank``
    holds afterwards (the original object at the root), ``stats`` the
    send accounting (``rounds``, ``sends``, ``donor_sends``,
    ``seq_sends`` — the N-1 sequential baseline)."""
    order = list(dict.fromkeys(int(r) for r in ranks))
    if root is None:
        root = order[0]
    root = int(root)
    if root not in order:
        raise ValueError(f"root {root} not in ranks {order}")
    order.remove(root)
    order.insert(0, root)
    n = len(order)
    for rk in order:
        if rk not in endpoints:
            raise ValueError(f"no endpoint for rank {rk}")
    received: dict[int, Any] = {root: payload}
    # Causal-id hop (ISSUE 17): a dict payload ALREADY carrying a
    # journey snapshot (a warm-up payload that started life as a
    # request's export_kv) continues that chain — the ADVANCED snapshot
    # is written back before any send so receivers (and any downstream
    # adoption) parent onto this push's span. A payload WITHOUT one
    # gets a chain minted for the trace event only: injecting the wire
    # key would change the delivered object, and delivery fidelity
    # (received == what the donor pushed) is the tree's contract.
    jfields: dict = {}
    if isinstance(payload, dict):
        wire = payload.get(_journey.WIRE_KEY)
        if wire:
            ctx = _journey.JourneyContext.from_wire(wire)
            jfields = ctx.begin_hop()
            payload[_journey.WIRE_KEY] = ctx.to_wire()
        else:
            jfields = _journey.new(f"{payload_kind}-push").begin_hop()
    donor_sends = 0
    total = 0
    rounds = tree_rounds(n, radix)
    for pairs in rounds:
        # sends strictly before receives, whole round at a time — the
        # ordering a distributed per-rank driver realizes, enforced
        # here so the loopback hub's recv-before-send guard stays loud
        for s, d in pairs:
            src, dst = order[s], order[d]
            endpoints[src].send_obj(received[src], dst)
            total += 1
            if src == root:
                donor_sends += 1
        for s, d in pairs:
            src, dst = order[s], order[d]
            received[dst] = endpoints[dst].recv_obj(src)
    stats = {
        "n": n,
        "radix": int(radix),
        "rounds": len(rounds),
        "depth": tree_depth(n, radix),
        "sends": total,
        "donor_sends": donor_sends,
        "seq_sends": max(0, n - 1),
    }
    assert total == max(0, n - 1), (total, n)  # every non-root once
    rec = _trace.active()
    if rec is not None:
        rec.event(
            "tree_push", payload_kind=payload_kind, **stats,
            **({"nbytes": int(nbytes)} if nbytes is not None else {}),
            **jfields,
        )
    return received, stats


def _adapter_payload(adapter, tenant_id: str) -> dict:
    layers = [
        {tgt: (np.asarray(A, np.float32), np.asarray(B, np.float32))
         for tgt, (A, B) in layer.items()}
        for layer in adapter.layers
    ]
    return {
        "schema": 1,
        "kind": "adapter",
        "tenant": str(tenant_id),
        "scale": float(adapter.scale),
        "layers": layers,
        "nbytes": sum(A.nbytes + B.nbytes
                      for layer in layers for A, B in layer.values()),
    }


def push_adapter(
    adapter,
    tenant_id: str,
    replicas: Sequence,
    hub,
    *,
    root: Optional[int] = None,
    radix: int = DEFAULT_RADIX,
) -> dict:
    """Install ``tenant_id``'s adapter on EVERY replica's bank via one
    tree push (the one-to-many serving-plane rendering of the ``bc``
    stage): the donor serializes once, the payload rides the
    radix-``radix`` tree over ``hub`` endpoints, and each replica
    registers its received copy into its OWN
    :class:`~chainermn_tpu.serving.adapters.AdapterBank` — bit-identical
    rows everywhere (registration is deterministic in the payload), the
    donor paying O(log N) sends instead of N-1.

    Replicas without a bank refuse loudly — silently skipping one would
    strand a tenant on a subset of the fleet. Returns the
    :func:`tree_push` stats."""
    from chainermn_tpu.serving.adapters import LowRankAdapter

    reps = {int(r.replica_id): r for r in replicas}
    for rid, rep in reps.items():
        if getattr(rep.engine, "adapter_bank", None) is None:
            raise ValueError(
                f"replica {rid} has no adapter_bank — cannot push "
                f"tenant {tenant_id!r} to a bankless fleet member"
            )
    payload = _adapter_payload(adapter, tenant_id)
    endpoints = {rid: hub.endpoint(rid) for rid in reps}
    received, stats = tree_push(
        payload, endpoints, list(reps), root=root, radix=radix,
        payload_kind="adapter", nbytes=payload["nbytes"],
    )
    for rid, rep in reps.items():
        got = received[rid]
        if not isinstance(got, dict) or got.get("kind") != "adapter":
            raise ValueError(
                f"replica {rid}: unexpected tree-push payload "
                f"{type(got).__name__}"
            )
        rep.engine.adapter_bank.register(
            got["tenant"],
            LowRankAdapter(got["layers"], scale=got["scale"]),
        )
    return stats


def warm_prefix_trie(
    replicas: Sequence,
    donor_slot: int,
    hub,
    *,
    root: Optional[int] = None,
    radix: int = DEFAULT_RADIX,
) -> dict:
    """Warm every replica's prefix trie from ONE prefilled donor slot:
    the donor exports the slot's KV payload once
    (``ServingEngine.export_kv``), it rides the tree, and each other
    replica adopts it (``import_kv`` — with prefix sharing on the full
    blocks land in that replica's trie) and immediately ``leave``\\ s
    the scratch slot, keeping the warmth without holding a slot. The
    donor's slot stays live (callers own its lifecycle).

    ``root`` defaults to the first replica; it must identify the
    replica that owns ``donor_slot``. Refuses loudly when a replica
    cannot place the payload (warm-up assumes capacity). Returns the
    :func:`tree_push` stats plus per-replica adopted slot bookkeeping
    under ``"adopted"``."""
    reps = {int(r.replica_id): r for r in replicas}
    rids = list(reps)
    if root is None:
        root = rids[0]
    root = int(root)
    donor = reps[root]
    payload = donor.engine.export_kv(donor_slot)
    endpoints = {rid: hub.endpoint(rid) for rid in reps}
    received, stats = tree_push(
        payload, endpoints, rids, root=root, radix=radix,
        payload_kind="kv_warm", nbytes=payload["nbytes"],
    )
    adopted: dict[int, int] = {}
    for rid, rep in reps.items():
        if rid == root:
            continue
        res = rep.engine.import_kv(received[rid])
        if res is None:
            raise RuntimeError(
                f"replica {rid} could not place the warm-up payload "
                "(no free slot/blocks) — trie warm-up assumes capacity"
            )
        slot, _ = res
        rep.engine.leave(slot)  # trie keeps the blocks, slot freed
        adopted[rid] = slot
    stats = dict(stats)
    stats["adopted"] = adopted
    return stats


__all__ = [
    "push_adapter",
    "tree_push",
    "tree_rounds",
    "tree_sends",
    "warm_prefix_trie",
]
