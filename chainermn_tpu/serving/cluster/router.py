"""Cluster front door: least-loaded / prefix-cache-aware placement
over a replica set, sticky multi-turn routing, and disaggregated
prefill/decode with KV streaming (ISSUE 8 tentpole).

One host loop drives everything: ``submit`` places each request on a
replica (consulting the signals the PR 6/7 planes already expose —
queue depth, ``kv_blocks_free``, and each replica's prefix-trie hit
depth via a read-only probe), ``run`` interleaves every replica's
admissions and decode steps through ``Scheduler.tick``. Requests that
cannot be admitted right now ride the existing deferred-admission
path (``prefill_join``/``import_kv`` returning None keeps them queued
— requeue-on-full, never an error another capacity state wouldn't
raise).

**Disaggregated mode** (``mode='disaggregated'``, or ``'auto'``
through the tuning registry — decision ``cluster_disagg``, table
default colocated: the transfer hop must earn adoption): designated
prefill replicas run the bucketed prefill, the finished KV blocks
stream to a decode replica over the host plane
(:mod:`~chainermn_tpu.serving.cluster.kv_transfer`), and the decode
replica's scheduler adopts the in-flight stream
(``Scheduler.admit_prefilled``) — compute-bound prefill and
latency-bound decode stop competing for the same chips, and the
decode replicas' compiled steps carry exactly the pre-cluster
collective set (nothing new on the wire; pinned structurally).

**Equivalence contract** (the suite pins it end to end): every token
stream routed through the cluster is bit-identical to sequential
``generate`` on a single device — including streams whose KV was
prefilled on a different replica than the one that decoded them.

**Replica loss**: :meth:`Router.fail_replica` evacuates a dead
replica's queued AND in-flight requests and re-routes them to the
survivors (streams are deterministic — greedy, or counter-key sampled
under the ``Request.seed`` that rides the re-routed object — so the
re-prefilled stream is identical; the client never sees the loss, only
latency); see docs/fault_tolerance.md.

Observability: one ``route`` trace event per placement and one
``kv_transfer`` event per handoff (docs/observability.md), plus
``rank``-labeled per-replica gauges (``serving_replica_queue_depth`` /
``_inflight`` / ``_kv_blocks_free``) so a multi-replica process is
inspectable live (``tools/metrics_dump.py --ports`` merges several
replica endpoints into one table).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Optional, Sequence

from chainermn_tpu.observability import journey as _journey
from chainermn_tpu.serving.cluster.replica import Replica
from chainermn_tpu.serving.scheduler import (
    Request,
    check_session_tenant,
    keep_arrival,
    pin_session_tenant,
)

ROUTE_POLICIES = ("least_loaded", "prefix_aware")
#: tuning-registry candidates for the cluster topology decision.
#: ``colocated_chunked`` (ISSUE 11) routes exactly like ``colocated``
#: but declares that the replicas run CHUNKED engines
#: (``prefill_chunk > 0``) — the third competitor the bench's bursty
#: phase prices against plain colocated and disaggregated: chunking
#: removes the monolithic-prefill decode stall WITHOUT the
#: disaggregation hop's transfer cost.
DISAGG_MODES = ("colocated", "disaggregated", "colocated_chunked")

#: process-global router id sequence: replica schedulers OUTLIVE any
#: one router (bench repeats build a fresh Router over warm replicas),
#: and their results dicts reject id reuse — so router-assigned ids
#: must never restart per instance.
_ROUTER_IDS = itertools.count()


class Router:
    """Front door over a replica set; see module docstring.

    Args:
      replicas: the :class:`~chainermn_tpu.serving.cluster.replica
        .Replica` set (``make_replicas``). All replicas a transfer can
        cross must share a KV layout (``import_kv`` refuses loudly).
      policy: ``'prefix_aware'`` (default — deepest trie hit wins,
        load breaks ties) or ``'least_loaded'``.
      mode: ``'colocated'`` | ``'disaggregated'`` | ``'auto'``
        (registry decision ``cluster_disagg`` under the first
        replica's serving key; forced colocated — with provenance —
        when the set is too small to split).
      prefill_replicas: replica ids that prefill in disaggregated mode
        (default: replicas whose ``role`` is ``'prefill'``, else the
        first replica). Every other replica decodes.
    """

    def __init__(self, replicas: Sequence[Replica], *,
                 policy: str = "prefix_aware", mode: str = "auto",
                 prefill_replicas: Optional[Sequence[int]] = None) -> None:
        if not replicas:
            raise ValueError("Router needs at least one replica")
        if policy not in ROUTE_POLICIES:
            raise ValueError(
                f"policy must be one of {ROUTE_POLICIES}, got {policy!r}")
        self.replicas = {r.replica_id: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("duplicate replica_id in the replica set")
        self.policy = policy
        self.decisions: list[dict] = []

        # ---- mode resolution (the serving-decision pattern)
        if mode not in DISAGG_MODES + ("auto",):
            raise ValueError(
                f"mode must be one of {DISAGG_MODES + ('auto',)}, got "
                f"{mode!r}"
            )
        key = replicas[0].engine.decision_key
        chunked_engines = all(
            getattr(r.engine, "prefill_chunk", 0) > 0 for r in replicas
        )
        if mode == "auto":
            if len(replicas) < 2:
                mode = "colocated"
                self.decisions.append({
                    "name": "cluster_disagg", "key": key,
                    "winner": mode, "source": "forced:single-replica",
                })
            else:
                from chainermn_tpu import tuning

                mode = tuning.choice("cluster_disagg", DISAGG_MODES, key)
                recs = [d for d in tuning.decisions_taken()
                        if d["name"] == "cluster_disagg"
                        and d["key"] == key]
                if recs:
                    self.decisions.append(dict(recs[-1]))
                if mode == "colocated_chunked" and not chunked_engines:
                    # The cache says chunking wins this shape, but THIS
                    # replica set was built monolithic — route as plain
                    # colocated (honest provenance) rather than promise
                    # a mixed step nobody compiled.
                    mode = "colocated"
                    self.decisions.append({
                        "name": "cluster_disagg", "key": key,
                        "winner": mode,
                        "source": "forced:unchunked-engines",
                    })
        else:
            if mode == "disaggregated" and len(replicas) < 2:
                raise ValueError(
                    "disaggregated mode needs >= 2 replicas (one "
                    "prefill + one decode)"
                )
            if mode == "colocated_chunked" and not chunked_engines:
                raise ValueError(
                    "mode='colocated_chunked' needs every replica "
                    "engine built with prefill_chunk > 0"
                )
            self.decisions.append({"name": "cluster_disagg", "key": key,
                                   "winner": mode, "source": "explicit"})
        self.mode = mode

        # ---- role partition (disaggregated only)
        if self.mode == "disaggregated":
            if prefill_replicas is None:
                prefill_replicas = [r.replica_id for r in replicas
                                    if r.role == "prefill"]
                if not prefill_replicas:
                    prefill_replicas = [replicas[0].replica_id]
            self._prefill_ids = [int(i) for i in prefill_replicas]
            for i in self._prefill_ids:
                if i not in self.replicas:
                    raise ValueError(f"unknown prefill replica id {i}")
                self.replicas[i].role = "prefill"
            self._decode_ids = [i for i in self.replicas
                                if i not in self._prefill_ids]
            if not self._decode_ids:
                raise ValueError(
                    "disaggregated mode left no decode replicas")
            for i in self._decode_ids:
                self.replicas[i].role = "decode"
            # one signature across the transfer boundary, checked ONCE
            # here instead of per-handoff deep in a serving loop
            sigs = {i: self.replicas[i].engine.kv_signature()
                    for i in self.replicas}
            if len(set(sigs.values())) != 1:
                raise ValueError(
                    f"replicas disagree on KV layout — blocks are not "
                    f"portable across this set: {sigs}"
                )
            #: per-prefill-replica router queues (arrival-ordered)
            self._pqueues = {i: deque() for i in self._prefill_ids}
            #: per-decode-replica pending handoffs awaiting adoption
            self._pending = {i: deque() for i in self._decode_ids}
        else:
            self._prefill_ids = []
            self._decode_ids = list(self.replicas)
            self._pqueues = {}
            self._pending = {}

        # ---- expert-shard residency (ISSUE 20): the fleet's MoE
        # signature, derived ONCE like the KV-layout check above. MoE
        # replicas must agree on the expert set — a2a dispatch shapes
        # bake n_experts into the compiled programs, so a mismatched
        # replica would produce different streams, not just worse ones.
        # Dense replicas may coexist (they serve nothing in a MoE
        # fleet — the hard filter below excludes them) so a mixed pool
        # mid-migration fails at placement, loudly, not mid-decode.
        esigs = {i: r.expert_signature() for i, r in self.replicas.items()}
        moe_sigs = {s for s in esigs.values() if s is not None}
        if len(moe_sigs) > 1:
            raise ValueError(
                f"replicas disagree on the expert set — MoE dispatch "
                f"is not portable across this pool: {esigs}"
            )
        #: fleet-wide expert signature; None = dense fleet (no filter)
        self._expert_sig = moe_sigs.pop() if moe_sigs else None

        self._ids = _ROUTER_IDS
        self._seen_ids: set = set()
        self._sessions: dict = {}
        #: session -> tenant pinning (the ISSUE 14 consistency guard —
        #: same rule as Scheduler.submit's).
        self._session_tenants: dict = {}
        #: requests that finished at the router (done at prefill —
        #: no decode leg, no transfer); merged into :meth:`run`'s
        #: result dict beside the replicas' own results.
        self.results: dict = {}
        self._events: list[dict] = []
        self.events_dropped = 0
        self._route_counts: dict = {}
        self._ttfts: list[float] = []
        self.transfers = 0
        self.transfer_bytes = 0
        self._wall: Optional[float] = None
        # Live-telemetry front door, same gate as Scheduler.__init__
        try:
            from chainermn_tpu.observability import exporter as _exporter

            _exporter.maybe_start_from_env()
        except Exception:
            pass

    # ------------------------------------------------------------------

    def _event(self, _kind: str, **fields) -> None:
        from chainermn_tpu.observability import trace

        if len(self._events) < trace.MAX_BUFFERED_EVENTS:
            self._events.append({"kind": _kind, **fields})
        else:
            self.events_dropped += 1
        rec = trace.active()
        if rec is not None:
            rec.event(_kind, **fields)

    def _publish_gauges(self) -> None:
        """Per-replica ``rank``-labeled gauges (ISSUE 8): the router is
        the one place that sees every replica, so cluster-wide load
        lands on ONE endpoint — and ``merge_peer_snapshots`` / the
        multi-port ``metrics_dump`` merge keeps the same label when
        replicas live in separate processes."""
        from chainermn_tpu.observability import metrics

        reg = metrics.active_registry()
        if reg is None:
            return
        for i, rep in self.replicas.items():
            rank = str(i)
            # Dead replicas publish 0s (their load was evacuated) plus
            # an explicit liveness flag — frozen last-breath gauges
            # would read as "alive and loaded" to a monitor, masking
            # the exact failure they exist to surface (review finding).
            reg.gauge("serving_replica_alive",
                      "1 while the replica is in rotation, 0 after "
                      "fail_replica").set(
                1.0 if rep.alive else 0.0, rank=rank)
            if rep.alive:
                depth = rep.scheduler.pending + len(
                    self._pqueues.get(i, ())) + len(
                    self._pending.get(i, ()))
                inflight = rep.scheduler.in_flight
                free = rep.kv_blocks_free()
            else:
                depth, inflight, free = 0, 0, 0
            reg.gauge("serving_replica_queue_depth",
                      "requests waiting on a replica (scheduler queue "
                      "+ router prefill queue + pending KV handoffs)"
                      ).set(depth, rank=rank)
            reg.gauge("serving_replica_inflight",
                      "requests decoding on a replica").set(
                inflight, rank=rank)
            if free is not None:
                reg.gauge("serving_replica_kv_blocks_free",
                          "free paged KV blocks per replica").set(
                    free, rank=rank)

    # ------------------------------------------------------------------
    # placement

    def _alive(self, ids) -> list[Replica]:
        return [self.replicas[i] for i in ids if self.replicas[i].alive]

    def _resident(self, candidates: Sequence[Replica],
                  tenant_id) -> list[Replica]:
        """Restrict ``candidates`` to replicas whose bank holds
        ``tenant_id`` (review finding: residency was only a SCORE
        bonus, so a tenant resident nowhere in the candidate set was
        still placed — and crashed the drive loop with a KeyError at
        ``prefill_join``/``import_kv`` instead of refusing). Raises
        the front-door error when none qualify (a resident replica can
        die between submit and placement). ``tenant_id=None`` filters
        too: a merged replica serves exactly its folded tenant, so a
        base-model request must not be placed on it."""
        out = [rep for rep in candidates
               if rep.adapter_resident(tenant_id)]
        if not out:
            who = (f"tenant {tenant_id!r}" if tenant_id is not None
                   else "a base-model (tenantless) request")
            raise RuntimeError(
                f"{who} has no serving-capable candidate replica "
                "(adapter not resident / merged-tenant mismatch) — "
                "register it (or revive the replica) before routing "
                "traffic"
            )
        return out

    def _expert_hosts(self, candidates: Sequence[Replica]
                      ) -> list[Replica]:
        """Restrict ``candidates`` to replicas hosting the fleet's
        expert shards (ISSUE 20, the adapter-residency pattern made a
        HARD filter): a dense engine has no expert weights, so placing
        MoE traffic on it is not a degraded choice — it is impossible.
        No-op for dense fleets. Raises loudly when no candidate
        qualifies (e.g. every MoE replica died and only dense spares
        remain) instead of letting ``_choose`` pick an engine that
        cannot run the model."""
        if self._expert_sig is None:
            return list(candidates)
        out = [rep for rep in candidates
               if rep.experts_resident(self._expert_sig)]
        if not out:
            raise RuntimeError(
                f"no candidate replica hosts the model's expert shards "
                f"{self._expert_sig} — MoE traffic cannot be placed on "
                "a dense engine; revive an expert-bearing replica "
                "before routing traffic"
            )
        return out

    def _score(self, rep: Replica, prompt, tenant_id=None,
               extra_queue: int = 0):
        """Placement score, maximized. ADAPTER RESIDENCY dominates for
        tenant-bearing requests (ISSUE 14: a replica whose bank holds
        the tenant's rows can serve it NOW — anywhere else needs a
        registration first, and a merged replica serves exactly its
        folded tenant); then prefix hit depth under ``prefix_aware``
        (a deeper hit is prefill work NOT done — worth more than
        perfect load balance, and probed under the TENANT's namespace);
        load breaks ties; free pool blocks break those (a starved pool
        defers admissions, the latency the gauges exist to predict)."""
        resident = int(rep.adapter_resident(tenant_id))
        hit = rep.prefix_hit_blocks(prompt, tenant_id=tenant_id) if (
            self.policy == "prefix_aware") else 0
        load = rep.load() + extra_queue
        free = rep.kv_blocks_free()
        return (resident, hit, -load, free if free is not None else 0,
                -rep.replica_id)

    def _choose(self, candidates: Sequence[Replica], request: Request,
                extra=None) -> Replica:
        return max(candidates, key=lambda rep: self._score(
            rep, request.prompt, request.tenant_id,
            (extra or {}).get(rep.replica_id, 0)))

    def _route(self, request: Request, requeue: bool = False) -> int:
        """Place one request; returns the chosen replica id. Sticky:
        a session's first placement pins its later turns (while the
        replica lives) so the per-replica trie stays warm."""
        target_ids = (self._prefill_ids if self.mode == "disaggregated"
                      else self._decode_ids)
        candidates = self._alive(target_ids)
        if not candidates:
            raise RuntimeError("no alive replica can accept requests")
        candidates = self._expert_hosts(candidates)
        sticky = False
        rep = None
        sid = request.session_id
        if sid is not None and sid in self._sessions:
            pinned = self._sessions[sid]
            if (pinned in self.replicas and self.replicas[pinned].alive
                    and pinned in target_ids
                    and self.replicas[pinned].adapter_resident(
                        request.tenant_id)):
                rep = self.replicas[pinned]
                sticky = True
        if rep is None:
            extra = {i: len(self._pqueues.get(i, ()))
                     for i in self.replicas}
            rep = self._choose(
                self._resident(candidates, request.tenant_id),
                request, extra)
        if sid is not None:
            self._sessions[sid] = rep.replica_id
        if self.mode == "disaggregated":
            self._pqueues[rep.replica_id].append(request)
        else:
            rep.scheduler.submit(request)
        rid = rep.replica_id
        self._route_counts[rid] = self._route_counts.get(rid, 0) + 1
        ev_extra = ({"tenant": request.tenant_id,
                     "adapter_resident": rep.adapter_resident(
                         request.tenant_id)}
                    if request.tenant_id is not None else {})
        self._event(
            "route", request=request.request_id, replica=rid,
            policy=self.policy, mode=self.mode, sticky=sticky,
            requeue=bool(requeue),
            hit_blocks=rep.prefix_hit_blocks(
                request.prompt, tenant_id=request.tenant_id),
            load=rep.load(),
            kv_blocks_free=rep.kv_blocks_free(),
            **ev_extra,
            **_journey.fields(request),
        )
        self._publish_gauges()
        return rid

    def submit(self, request: Request) -> str:
        """Admit one request into the cluster; returns its id. The
        horizon check runs here (every replica shares the engine
        shape) so an impossible request fails at the front door, not
        mid-stream on whichever replica drew it."""
        engine = next(iter(self.replicas.values())).engine
        total = len(request.prompt) + request.max_new_tokens
        if total > engine.max_len:
            raise ValueError(
                f"request needs {total} positions but the cluster "
                f"engine horizon is max_len={engine.max_len}"
            )
        if request.request_id is None:
            request.request_id = f"c{next(self._ids)}"
        if request.request_id in self._seen_ids:
            raise ValueError(
                f"duplicate request_id {request.request_id!r}")
        # Sticky-session/tenant consistency (ISSUE 14 satellite): the
        # ONE shared validate half; the pin commits below, after the
        # residency validation — a refused submission must not poison
        # the session id (review finding).
        check_session_tenant(self._session_tenants, request)
        # Tenant must be placeable on EVERY role its journey touches
        # (review finding: "resident somewhere" passed a tenant whose
        # adapter lived only on a decode replica, and the prefill pump
        # then crashed mid-run): colocated needs a resident decode
        # replica; disaggregated needs one per plane — prefill runs
        # the forward, and import_kv validates residency on the decode
        # side before adopting.
        needed = ([("prefill", self._prefill_ids),
                   ("decode", self._decode_ids)]
                  if self.mode == "disaggregated"
                  else [("decode", self._decode_ids)])
        for role, ids in needed:
            if not any(rep.adapter_resident(request.tenant_id)
                       for rep in self._alive(ids)):
                who = (f"tenant {request.tenant_id!r} has no resident "
                       "adapter"
                       if request.tenant_id is not None
                       else "a base-model (tenantless) request has no "
                            "serving-capable replica")
                raise ValueError(
                    f"{who} on any alive {role} replica — register "
                    "the adapter (or add a non-merged replica) before "
                    "routing traffic"
                )
        self._seen_ids.add(request.request_id)
        pin_session_tenant(self._session_tenants, request)
        # The ONE stamp rule (ISSUE 11 satellite): set only when unset,
        # so this front door, Scheduler.submit and the preemption
        # requeue can never disagree about when the journey began.
        keep_arrival(request)
        _journey.ensure(request)  # the causal-id sibling of the rule
        self._route(request)
        return request.request_id

    # ------------------------------------------------------------------
    # disaggregated pumps

    def _pump_prefill(self) -> bool:
        """Admit router-queued requests into prefill replicas (strict
        arrival order per replica — the scheduler's FCFS discipline),
        export + release each finished prefill, and queue the payload
        for a decode replica. A refused ``prefill_join`` leaves the
        head queued: the deferred-admission path, retried next
        sweep."""
        progressed = False
        for i in self._prefill_ids:
            rep = self.replicas[i]
            if not rep.alive:
                continue
            q = self._pqueues[i]
            while q:
                req = q[0]
                t_admit = time.perf_counter()
                join_kw = ({"tenant_id": req.tenant_id}
                           if req.tenant_id is not None else {})
                res = rep.engine.prefill_join(req.prompt, **join_kw)
                if res is None:
                    break
                q.popleft()
                slot, tok, _bucket = res
                progressed = True
                if req.max_new_tokens <= 1 or (
                    req.eos_id is not None and tok == req.eos_id
                ):
                    # Done at prefill: nothing to decode, nothing to
                    # stream — finish at the router.
                    rep.engine.leave(slot)
                    self.results[req.request_id] = {
                        "tokens": list(req.prompt) + [tok],
                        "generated": [tok],
                    }
                    self._ttfts.append(time.perf_counter() - req._arrival)
                    continue
                # t_export stamps AFTER the prefill: the kv_transfer
                # event's dur_s is the HANDOFF latency (export →
                # adoption), not prefill compute (review finding); the
                # admission-to-adoption total rides admit_prefilled's
                # dur_s instead.
                t_export = time.perf_counter()
                payload = rep.engine.export_kv(slot)
                rep.engine.leave(slot)
                # Journey snapshot ON the payload (ISSUE 17): in
                # process the same Request object continues the chain;
                # over a real wire the decode rank restores it from
                # exactly this key (journey.adopt_payload).
                _journey.attach_payload(payload, req)
                dst = self._choose_decode(req.tenant_id)
                self._pending[dst.replica_id].append(
                    (req, payload, t_export, t_admit, i))
        return progressed

    def _choose_decode(self, tenant_id=None) -> Replica:
        """Decode placement: most free pool blocks, then least loaded
        (pending handoffs count as load — they land next). Tenant-
        bearing handoffs only consider resident replicas —
        ``import_kv`` validates residency before adopting, so a
        non-resident pick would crash the adopt pump. Alive is checked
        FIRST so a dead-pool outage reads as what it is, not as a
        residency problem (review finding)."""
        alive = self._alive(self._decode_ids)
        if not alive:
            raise RuntimeError("no alive decode replica")
        cands = self._resident(self._expert_hosts(alive), tenant_id)
        return max(cands, key=lambda rep: (
            rep.kv_blocks_free() or 0,
            -(rep.load() + len(self._pending[rep.replica_id])),
            -rep.replica_id,
        ))

    def _pump_adopt(self) -> bool:
        """Adopt pending handoffs into decode replicas. ``import_kv``
        returning None (no slot / pool full right now) keeps the
        payload queued — requeue-on-full, FIFO per replica so the
        per-pair ordering of the TCP plane is preserved end to end."""
        progressed = False
        for i in self._decode_ids:
            rep = self.replicas[i]
            if not rep.alive:
                continue
            dq = self._pending[i]
            while dq:
                req, payload, t_export, t_admit, src = dq[0]
                res = rep.engine.import_kv(payload)
                if res is None:
                    break
                dq.popleft()
                slot, tok = res
                now = time.perf_counter()
                self.transfers += 1
                self.transfer_bytes += int(payload["nbytes"])
                self._event(
                    "kv_transfer", request=req.request_id, src=src,
                    dst=i, nbytes=int(payload["nbytes"]),
                    blocks=len(payload["blocks"]),
                    dur_s=round(now - t_export, 9),
                    **_journey.fields(req),
                )
                rep.scheduler.admit_prefilled(req, slot, tok,
                                              dur_s=now - t_admit)
                progressed = True
        return progressed

    # ------------------------------------------------------------------
    # drive

    @property
    def drained(self) -> bool:
        return (not self.work_pending()
                and all(rep.drained for rep in self.replicas.values()
                        if rep.alive))

    def work_pending(self) -> int:
        return (sum(len(q) for q in self._pqueues.values())
                + sum(len(q) for q in self._pending.values()))

    def run(self, max_steps: int = 100_000,
            max_seconds: Optional[float] = None) -> dict:
        """Drive the whole cluster until every stream drains; returns
        the merged ``{request_id: {'tokens', 'generated'}}`` dict
        (router-local finishes + every replica's results).
        ``max_seconds`` bounds the run by wall clock, stopping cleanly
        (unfinished requests stay queued/in flight); ``max_steps``
        stays the runaway guard and raises."""
        from chainermn_tpu.observability import flight as _flight

        for rep in self.replicas.values():
            if rep.alive:
                rep.scheduler.start_window()
        t0 = time.perf_counter()
        steps = 0
        try:
            while not self.drained:
                _flight.beat(steps)
                if max_seconds is not None and (
                    time.perf_counter() - t0 >= max_seconds
                ):
                    break
                progressed = False
                if self.mode == "disaggregated":
                    progressed |= self._pump_prefill()
                    progressed |= self._pump_adopt()
                for i in self._decode_ids:
                    rep = self.replicas[i]
                    if rep.alive and not rep.drained:
                        progressed |= rep.tick()
                if not progressed:
                    inflight = sum(rep.scheduler.in_flight
                                   for rep in self.replicas.values()
                                   if rep.alive)
                    if inflight == 0:
                        queued = self.work_pending() + sum(
                            rep.scheduler.pending
                            for rep in self.replicas.values()
                            if rep.alive)
                        raise RuntimeError(
                            f"cluster stalled with {queued} request(s) "
                            "unplaceable on idle replicas (slot/pool "
                            "shortage everywhere)"
                        )
                steps += 1
                if steps > max_steps:
                    raise RuntimeError(
                        f"exceeded max_steps={max_steps} with work "
                        "still in flight")
                self._publish_gauges()
        finally:
            _flight.quiesce()
        for rep in self.replicas.values():
            if rep.alive:
                rep.scheduler.close_window()
        self._wall = time.perf_counter() - t0
        return self.collect_results()

    def collect_results(self) -> dict:
        """THIS router's finished streams, wherever they landed.
        Replica schedulers are cumulative and outlive any one router
        (the warm-replica bench pattern) — filtering by the ids this
        router assigned keeps a fresh router from returning a previous
        router's streams (review finding)."""
        out = dict(self.results)
        for rep in self.replicas.values():
            for rid, res in rep.scheduler.results.items():
                if rid in self._seen_ids:
                    out[rid] = res
        return out

    def preempt_request(self, request_id: str,
                        exclude_replica: bool = True) -> int:
        """Preempt one in-flight (or mid-fill) request and RE-ROUTE it
        (ISSUE 11): the holding replica's scheduler parks the partial
        stream as resume state ON the request
        (:meth:`~chainermn_tpu.serving.scheduler.Scheduler.preempt`
        with ``requeue=False``), and the router places it again — on a
        DIFFERENT replica when ``exclude_replica`` and one is alive
        (the load-shedding migration move), else back on the source.
        Resumed requests are ALWAYS submitted straight to a
        decode-capable replica's scheduler, never a disaggregated
        prefill queue: the prefill pump joins from the ORIGINAL prompt
        and ``admit_prefilled`` re-samples TTFT, both of which would
        break the resume contract (review finding). The arrival stamp
        survives the hop (keep_arrival, the unified rule) and stream
        determinism — greedy, or counter-key sampled under the
        ``Request.seed`` travelling on the same object — makes the
        resumed stream bit-identical wherever it lands. Returns the
        new replica id."""
        src = None
        for i, rep in self.replicas.items():
            if not rep.alive:
                continue
            slot = rep.scheduler.slot_of(request_id)
            if slot is not None:
                src = (i, slot)
                break
        if src is None:
            raise ValueError(
                f"request {request_id!r} is not in flight on any "
                "alive replica")
        src_id, slot = src
        ids = [i for i in self._decode_ids if i != src_id] \
            if exclude_replica else list(self._decode_ids)
        cands = self._alive(ids) or self._alive(self._decode_ids)
        if not cands:
            raise RuntimeError("no alive decode replica to resume on")
        # Residency filter BEFORE preempting (review finding: _choose
        # treats residency as a score, not a filter — a non-resident
        # winner would refuse the submit and strand the just-preempted
        # request). Failing here leaves the stream running in place.
        tenant = getattr(self.replicas[src_id].engine,
                         "tenant_of_slot", lambda s: None)(slot)
        cands = self._resident(self._expert_hosts(cands), tenant)
        req = self.replicas[src_id].scheduler.preempt(slot, requeue=False)
        # Same scoring as _route's placement, pending prefill queues
        # included in the load tiebreak (review finding: a diverging
        # re-implementation scored migrations differently).
        extra = {i: len(self._pqueues.get(i, ()))
                 for i in self.replicas}
        rep = self._choose(cands, req, extra)
        rep.scheduler.submit(req)
        rid = rep.replica_id
        if req.session_id is not None:
            # re-pin the session so later turns follow the migration
            self._sessions[req.session_id] = rid
        self._route_counts[rid] = self._route_counts.get(rid, 0) + 1
        self._event(
            "route", request=req.request_id, replica=rid,
            policy=self.policy, mode=self.mode, sticky=False,
            requeue=True, preempted_from=src_id,
            hit_blocks=rep.prefix_hit_blocks(
                req.prompt, tenant_id=req.tenant_id),
            load=rep.load(), kv_blocks_free=rep.kv_blocks_free(),
            **({"tenant": req.tenant_id}
               if req.tenant_id is not None else {}),
            **_journey.fields(req),
        )
        self._publish_gauges()
        return rid

    # ------------------------------------------------------------------
    # replica loss

    def fail_replica(self, replica_id: int) -> list[str]:
        """Take ``replica_id`` out of rotation and re-route everything
        it held — queued requests, pending handoffs, AND in-flight
        streams (their partial output is discarded; deterministic
        streams — greedy, or counter-key sampled under the seed riding
        each Request — mean the re-run is bit-identical, so the client
        sees latency, not corruption). Returns the re-routed request
        ids. Raises when the survivors cannot cover the dead
        replica's role."""
        rep = self.replicas.get(replica_id)
        if rep is None or not rep.alive:
            raise ValueError(f"replica {replica_id} unknown or already "
                             "failed")
        # Role coverage is validated BEFORE any mutation: raising
        # halfway would discard the just-evacuated requests and leave
        # the router half-updated for a caller that catches the error
        # (review finding).
        if replica_id in self._prefill_ids and not self._alive(
            [i for i in self._prefill_ids if i != replica_id]
        ) and self.mode == "disaggregated":
            raise RuntimeError(
                "last prefill replica failed — no survivor can cover "
                "its role")
        if replica_id in self._decode_ids and not self._alive(
            [i for i in self._decode_ids if i != replica_id]
        ):
            raise RuntimeError(
                "last decode replica failed — no survivor can cover "
                "its role")
        rep.alive = False
        orphans: list[Request] = []
        orphans.extend(self._pqueues.pop(replica_id, ()))
        if replica_id in self._prefill_ids:
            self._prefill_ids.remove(replica_id)
        for entry in self._pending.pop(replica_id, ()):
            # the payload targeted the dead pool; re-prefill elsewhere
            orphans.append(entry[0])
        if replica_id in self._decode_ids:
            self._decode_ids.remove(replica_id)
        orphans.extend(rep.scheduler.evacuate())
        for sid, pinned in list(self._sessions.items()):
            if pinned == replica_id:
                del self._sessions[sid]
        orphans.sort(key=lambda r: r._arrival)
        for req in orphans:
            self._route(req, requeue=True)
        return [r.request_id for r in orphans]

    # ------------------------------------------------------------------

    def summary(self) -> dict:
        """Cluster rollup: per-replica scheduler summaries plus the
        router's own accounting — route counts, transfer count/bytes,
        cluster-wide goodput (FINISHED generated tokens of THIS
        router's requests / router wall) and TTFT percentiles over the
        live replicas' windows. Counts come from the merged results,
        not event windows: dead replicas' stale windows describe
        discarded partial streams, and warm replicas may carry other
        routers' traffic (review finding) — neither belongs in this
        router's goodput."""
        from chainermn_tpu.observability.stats import nearest_rank

        ttfts = list(self._ttfts)
        merged = self.collect_results()
        requests = len(merged)
        tokens = sum(len(r["generated"]) for r in merged.values())
        per_replica = {}
        for i, rep in self.replicas.items():
            s = rep.summary()
            s["alive"] = rep.alive
            per_replica[i] = s
            if not rep.alive:
                continue
            for ev in rep.scheduler.event_window:
                if (ev.get("kind") == "serving"
                        and ev.get("phase") == "prefill"
                        and ev.get("ttft_s") is not None):
                    ttfts.append(float(ev["ttft_s"]))
        out = {
            "mode": self.mode,
            "policy": self.policy,
            "replicas": per_replica,
            "requests": requests,
            "generated_tokens": tokens,
            "routes": dict(sorted(self._route_counts.items())),
            "kv_transfer": {"transfers": self.transfers,
                            "bytes": self.transfer_bytes},
            "ttft_ms_p50": (round(nearest_rank(ttfts, 0.5) * 1e3, 4)
                            if ttfts else None),
            "ttft_ms_p99": (round(nearest_rank(ttfts, 0.99) * 1e3, 4)
                            if ttfts else None),
        }
        if self._wall is not None:
            out["wall_s"] = round(self._wall, 4)
            if self._wall > 0:
                out["goodput_tokens_per_sec"] = round(
                    tokens / self._wall, 2)
        if self.events_dropped:
            out["events_dropped"] = self.events_dropped
        return out
