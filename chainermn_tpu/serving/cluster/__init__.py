"""Cluster serving plane (ISSUE 8): a multi-replica router with
disaggregated prefill/decode and KV-block streaming over the host p2p
plane. See docs/serving.md "Cluster serving" for the contract — the
short form: N independent engines over a ``replica × model`` device
partition, a front door doing least-loaded / prefix-cache-aware /
sticky placement, and (disaggregated) prefill replicas streaming
finished KV blocks to decode replicas so decode starts without
re-prefilling — with every routed stream bit-identical to sequential
``generate``."""

from chainermn_tpu.serving.cluster.kv_transfer import (
    LoopbackHub,
    mesh_stream_blocks,
    recv_kv,
    send_kv,
    transfer_kv,
)
from chainermn_tpu.serving.cluster.replica import (
    ROLES,
    Replica,
    make_replicas,
)
from chainermn_tpu.serving.cluster.router import (
    DISAGG_MODES,
    ROUTE_POLICIES,
    Router,
)
from chainermn_tpu.serving.cluster.tree_push import (
    push_adapter,
    tree_push,
    tree_rounds,
    warm_prefix_trie,
)

__all__ = [
    "Replica",
    "Router",
    "LoopbackHub",
    "DISAGG_MODES",
    "ROLES",
    "ROUTE_POLICIES",
    "make_replicas",
    "mesh_stream_blocks",
    "push_adapter",
    "recv_kv",
    "send_kv",
    "transfer_kv",
    "tree_push",
    "tree_rounds",
    "warm_prefix_trie",
]
