"""Continuous-batching serving engine (ISSUE 4): slot-scheduled decode
with a paged KV cache, a bucketed prefill/decode split, and tokens/s
accounting — plus speculative draft-and-verify decoding (ISSUE 5):
per-tick n-gram/model drafting, one jitted multi-token verify step,
host-metadata rollback — plus multi-tenant adapter serving (ISSUE 14):
stacked low-rank deltas over one base model, deficit-round-robin
fair-share admission, per-tenant SLO accounting. See docs/serving.md
for the engine contract."""

from chainermn_tpu.serving.adapters import (
    ADAPTER_IMPLS,
    ADAPTER_TARGETS,
    AdapterBank,
    LowRankAdapter,
    random_adapter,
    shard_adapter_stacks,
)
from chainermn_tpu.serving.engine import (
    DECODE_ATTEND_IMPLS,
    DECODE_IMPLS,
    KV_BLOCK_SIZES,
    MIN_SHARED_BLOCKS,
    PREFILL_CHUNKS,
    PREFIX_CACHE,
    SPEC_TOKENS,
    ServingEngine,
    resolve_adapter_impl,
    resolve_decode_attend_impl,
    resolve_decode_impl,
    resolve_kv_block_size,
    resolve_min_shared_blocks,
    resolve_prefill_chunk,
    resolve_prefix_cache,
    resolve_spec_tokens,
    serving_decision_key,
    shard_lm_params,
)
from chainermn_tpu.serving.kv_blocks import (
    BlockAllocator,
    PrefixCache,
    default_num_blocks,
    init_serving_cache,
)
from chainermn_tpu.serving.scheduler import (
    POLICIES,
    DeficitRoundRobin,
    Request,
    Scheduler,
)
from chainermn_tpu.serving.speculate import (
    ModelDrafter,
    NgramDrafter,
    accept_length,
)

__all__ = [
    "ServingEngine",
    "Scheduler",
    "Request",
    "AdapterBank",
    "LowRankAdapter",
    "DeficitRoundRobin",
    "BlockAllocator",
    "PrefixCache",
    "ADAPTER_IMPLS",
    "ADAPTER_TARGETS",
    "DECODE_ATTEND_IMPLS",
    "DECODE_IMPLS",
    "KV_BLOCK_SIZES",
    "MIN_SHARED_BLOCKS",
    "PREFILL_CHUNKS",
    "PREFIX_CACHE",
    "SPEC_TOKENS",
    "POLICIES",
    "ModelDrafter",
    "NgramDrafter",
    "accept_length",
    "default_num_blocks",
    "init_serving_cache",
    "random_adapter",
    "resolve_adapter_impl",
    "resolve_decode_attend_impl",
    "resolve_decode_impl",
    "resolve_kv_block_size",
    "resolve_min_shared_blocks",
    "resolve_prefill_chunk",
    "resolve_prefix_cache",
    "resolve_spec_tokens",
    "serving_decision_key",
    "shard_adapter_stacks",
    "shard_lm_params",
]
