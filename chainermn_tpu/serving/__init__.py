"""Continuous-batching serving engine (ISSUE 4): slot-scheduled decode
with a paged KV cache, a bucketed prefill/decode split, and tokens/s
accounting. See docs/serving.md for the engine contract."""

from chainermn_tpu.serving.engine import (
    DECODE_IMPLS,
    KV_BLOCK_SIZES,
    ServingEngine,
    resolve_decode_impl,
    resolve_kv_block_size,
    serving_decision_key,
    shard_lm_params,
)
from chainermn_tpu.serving.kv_blocks import (
    BlockAllocator,
    default_num_blocks,
    init_serving_cache,
)
from chainermn_tpu.serving.scheduler import POLICIES, Request, Scheduler

__all__ = [
    "ServingEngine",
    "Scheduler",
    "Request",
    "BlockAllocator",
    "DECODE_IMPLS",
    "KV_BLOCK_SIZES",
    "POLICIES",
    "default_num_blocks",
    "init_serving_cache",
    "resolve_decode_impl",
    "resolve_kv_block_size",
    "serving_decision_key",
    "shard_lm_params",
]
