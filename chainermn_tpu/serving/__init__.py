"""Continuous-batching serving engine (ISSUE 4): slot-scheduled decode
with a paged KV cache, a bucketed prefill/decode split, and tokens/s
accounting — plus speculative draft-and-verify decoding (ISSUE 5):
per-tick n-gram/model drafting, one jitted multi-token verify step,
host-metadata rollback. See docs/serving.md for the engine contract."""

from chainermn_tpu.serving.engine import (
    DECODE_IMPLS,
    KV_BLOCK_SIZES,
    MIN_SHARED_BLOCKS,
    PREFILL_CHUNKS,
    PREFIX_CACHE,
    SPEC_TOKENS,
    ServingEngine,
    resolve_decode_impl,
    resolve_kv_block_size,
    resolve_min_shared_blocks,
    resolve_prefill_chunk,
    resolve_prefix_cache,
    resolve_spec_tokens,
    serving_decision_key,
    shard_lm_params,
)
from chainermn_tpu.serving.kv_blocks import (
    BlockAllocator,
    PrefixCache,
    default_num_blocks,
    init_serving_cache,
)
from chainermn_tpu.serving.scheduler import POLICIES, Request, Scheduler
from chainermn_tpu.serving.speculate import (
    ModelDrafter,
    NgramDrafter,
    accept_length,
)

__all__ = [
    "ServingEngine",
    "Scheduler",
    "Request",
    "BlockAllocator",
    "PrefixCache",
    "DECODE_IMPLS",
    "KV_BLOCK_SIZES",
    "MIN_SHARED_BLOCKS",
    "PREFILL_CHUNKS",
    "PREFIX_CACHE",
    "SPEC_TOKENS",
    "POLICIES",
    "ModelDrafter",
    "NgramDrafter",
    "accept_length",
    "default_num_blocks",
    "init_serving_cache",
    "resolve_decode_impl",
    "resolve_kv_block_size",
    "resolve_min_shared_blocks",
    "resolve_prefill_chunk",
    "resolve_prefix_cache",
    "resolve_spec_tokens",
    "serving_decision_key",
    "shard_lm_params",
]
