"""Multi-tenant adapter bank: stacked low-rank deltas over one frozen
base model (ISSUE 14 tentpole).

The north star says "millions of users", which means thousands of
*variants*, not one model — and the reference's whole inference surface
was a single-model per-sentence loop (``examples/seq2seq/seq2seq.py``
†). This module serves many per-tenant fine-tuning deltas over
ONE compiled program, riding the codebase's signature discipline: all
variation lives in host metadata.

- :class:`LowRankAdapter` — one tenant's delta: per layer, per hooked
  projection (``qkv``/``proj``/``ff_up``/``ff_down``), a rank-r pair
  ``A [d_in, r]`` / ``B [r, d_out]`` plus a scalar ``scale``
  (folded into ``B`` at registration so every consumer — the engine's
  per-slot gather, the ``generate`` reference, the merged fold — reads
  the identical values).
- :class:`AdapterBank` — the device-feedable store: per layer, per
  target, ``[capacity, ...]``-stacked A/B arrays. Row 0 is the NULL
  adapter (all zeros, never evicted): a zero delta contributes an
  exact 0, so a zero-adapter tenant is bitwise the base model.
  ``register``/``evict`` mutate host numpy + bump ``version`` (the
  engine re-uploads its device copy only then — the block-table
  discipline); refcounts pin a tenant's row while any slot serves it,
  so an evict can never yank weights out from under a live stream.

Engine contract (:class:`~chainermn_tpu.serving.engine.ServingEngine`
with ``adapter_bank=``): each slot carries a host-side tenant row, the
ONE jitted decode/verify/mixed/prefill program gathers that slot's A/B
rows from the stacks and adds the rank-r delta inside the forward
(``TransformerBlock._lora_delta``) — tenant churn mutates host metadata
only (jit cache pinned at 1), and under TP the stacks are sharded along
the existing Megatron column/row split so the compiled step keeps
EXACTLY the pre-adapter collective set (2 all-reduces/layer, pinned by
HLO count in tests/test_adapters.py).

``adapter_impl`` (tuning decision, table ``gather``): ``'gather'`` =
the per-slot stack gather above (mixed-tenant traffic); ``'merged'`` =
:func:`merge_adapter_params` folds one tenant's delta into the base
weights at construction (zero per-step delta cost — the single-tenant-
dominant deployment; the engine then refuses other tenants loudly).
"""

from __future__ import annotations

import weakref
from typing import Mapping, Optional, Sequence

import numpy as np

#: the hooked projections, in block order. ``qkv``/``ff_up`` are
#: column-parallel under TP (B sharded with the kernel's output
#: columns), ``proj``/``ff_down`` row-parallel (A sharded with the
#: kernel's input rows; the partial delta rides the layer's existing
#: psum).
ADAPTER_TARGETS = ("qkv", "proj", "ff_up", "ff_down")

#: tuning-registry candidates for the ``adapter_impl`` decision.
ADAPTER_IMPLS = ("gather", "merged")


def _target_dims(model) -> dict:
    """``target -> (d_in, d_out)`` for one block of ``model``."""
    kv = model.num_kv_heads or model.num_heads
    dh = model.head_dim or model.d_model // model.num_heads
    return {
        "qkv": (model.d_model, (model.num_heads + 2 * kv) * dh),
        "proj": (model.num_heads * dh, model.d_model),
        "ff_up": (model.d_model, model.d_ff),
        "ff_down": (model.d_ff, model.d_model),
    }


class LowRankAdapter:
    """One tenant's low-rank delta over the hooked projections.

    Args:
      layers: per-layer mapping ``target -> (A, B)`` with ``A
        [d_in, r]`` and ``B [r, d_out]`` (float32 host arrays; a layer
        may hook any subset of :data:`ADAPTER_TARGETS`, missing targets
        delta nothing). ``len(layers)`` must equal the model's layer
        count at registration.
      scale: the LoRA alpha/r multiplier, folded into ``B`` at
        registration (every consumer sees the folded values — the
        gather path, the ``generate`` reference, and the merged fold
        cannot drift on scaling).
    """

    def __init__(self, layers: Sequence[Mapping[str, tuple]],
                 scale: float = 1.0) -> None:
        self.layers = [dict(layer) for layer in layers]
        self.scale = float(scale)
        for li, layer in enumerate(self.layers):
            for tgt, pair in layer.items():
                if tgt not in ADAPTER_TARGETS:
                    raise ValueError(
                        f"layer {li}: unknown adapter target {tgt!r} "
                        f"(one of {ADAPTER_TARGETS})"
                    )
                A, B = pair
                A = np.asarray(A, np.float32)
                B = np.asarray(B, np.float32)
                if A.ndim != 2 or B.ndim != 2 or A.shape[1] != B.shape[0]:
                    raise ValueError(
                        f"layer {li} {tgt}: A {A.shape} / B {B.shape} "
                        "must be [d_in, r] / [r, d_out] with matching r"
                    )
                layer[tgt] = (A, B)

    @property
    def rank(self) -> int:
        return max(
            (pair[0].shape[1] for layer in self.layers
             for pair in layer.values()),
            default=0,
        )


def random_adapter(model, rank: int, *, seed: int = 0,
                   targets: Sequence[str] = ADAPTER_TARGETS,
                   scale: float = 1.0,
                   init_scale: float = 0.02) -> LowRankAdapter:
    """A random rank-``rank`` adapter for ``model`` (tests/bench/dryrun
    workload material — NOT a training story). Both A and B are drawn
    ~N(0, init_scale²) so the delta is small but nonzero everywhere:
    a stream served through it must actually diverge from base."""
    rs = np.random.RandomState(seed)
    dims = _target_dims(model)
    layers = []
    for _ in range(model.num_layers):
        layer = {}
        for tgt in targets:
            d_in, d_out = dims[tgt]
            layer[tgt] = (
                rs.normal(0.0, init_scale, (d_in, rank)).astype(
                    np.float32),
                rs.normal(0.0, init_scale, (rank, d_out)).astype(
                    np.float32),
            )
        layers.append(layer)
    return LowRankAdapter(layers, scale=scale)


class AdapterBank:
    """Stacked per-tenant A/B rows over one base model; see module
    docstring.

    Args:
      model: the base ``TransformerLM`` (full — pre-TP — shape; the
        engine shards the stacks itself when it runs under a mesh).
      capacity: tenant rows INCLUDING the reserved null row 0 — at most
        ``capacity - 1`` adapter-bearing tenants resident at once.
      rank: the stack's rank budget; a registered adapter of smaller
        rank is zero-padded (exact — zero columns delta nothing), a
        larger one is refused.
      targets: hooked projections (default all four).
    """

    def __init__(self, model, capacity: int, rank: int,
                 targets: Sequence[str] = ADAPTER_TARGETS) -> None:
        if capacity < 2:
            raise ValueError(
                f"capacity must be >= 2 (row 0 is the null adapter), "
                f"got {capacity}"
            )
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        for tgt in targets:
            if tgt not in ADAPTER_TARGETS:
                raise ValueError(
                    f"unknown adapter target {tgt!r} (one of "
                    f"{ADAPTER_TARGETS})"
                )
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.targets = tuple(targets)
        self.num_layers = int(model.num_layers)
        self._dims = _target_dims(model)
        #: per-layer ``{target: (A [cap, d_in, r], B [cap, r, d_out])}``
        #: host stacks (float32; row 0 stays all-zero forever).
        self._stacks = [
            {
                tgt: (
                    np.zeros((capacity, self._dims[tgt][0], rank),
                             np.float32),
                    np.zeros((capacity, rank, self._dims[tgt][1]),
                             np.float32),
                )
                for tgt in self.targets
            }
            for _ in range(self.num_layers)
        ]
        #: tenant -> row. Row 0 is shared by every ZERO-adapter tenant
        #: (registered with ``adapter=None``) — bitwise the base model.
        self._rows: dict[str, int] = {}
        self._free = list(range(capacity - 1, 0, -1))
        #: tenant -> live-slot refcount (the engine pins at join,
        #: unpins at leave); an evict of a pinned tenant refuses.
        self._pins: dict[str, int] = {}
        #: bumped on every register/evict that changes row CONTENTS —
        #: the engine keys its device copy on it (the block-table
        #: re-upload discipline: registration churn, not decode ticks,
        #: pays the H2D).
        self.version = 0
        #: lifetime register/evict counts (dryrun/bench visibility).
        self.registrations = 0
        self.evictions = 0
        #: weak refs to per-engine change hooks (:meth:`add_listener`).
        self._listeners: list = []

    # ------------------------------------------------------------------

    def add_listener(self, fn) -> None:
        """Subscribe a ``fn(tenant_id)`` hook fired on every register/
        evict of that tenant (bound methods held weakly — a dropped
        engine unsubscribes itself). The serving engine uses this to
        invalidate the tenant's prefix-trie namespace: cached KV was
        computed under the OLD weights, and adopting it after a
        re-registration would silently diverge from ``generate`` under
        the new adapter."""
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        self._listeners.append(ref)

    def _notify(self, tenant_id: str) -> None:
        for ref in list(self._listeners):
            fn = ref()
            if fn is None:
                self._listeners.remove(ref)
            else:
                fn(tenant_id)

    def residents(self) -> list[str]:
        """Registered tenants, registration order."""
        return list(self._rows)

    @property
    def free_rows(self) -> int:
        return len(self._free)

    def resident(self, tenant_id: Optional[str]) -> bool:
        return tenant_id is None or tenant_id in self._rows

    def row_of(self, tenant_id: Optional[str]) -> int:
        """The stack row serving ``tenant_id`` (None -> the null row).
        Unknown tenants raise — silently serving the base model for a
        tenant whose adapter never registered would corrupt streams the
        quiet way."""
        if tenant_id is None:
            return 0
        row = self._rows.get(tenant_id)
        if row is None:
            raise KeyError(
                f"tenant {tenant_id!r} has no registered adapter on "
                f"this bank (residents: {self.residents()})"
            )
        return row

    def pin(self, tenant_id: Optional[str]) -> None:
        if tenant_id is None:
            return
        self.row_of(tenant_id)  # must be resident
        self._pins[tenant_id] = self._pins.get(tenant_id, 0) + 1

    def unpin(self, tenant_id: Optional[str]) -> None:
        if tenant_id is None:
            return
        n = self._pins.get(tenant_id, 0)
        if n <= 0:  # pragma: no cover - internal guard
            raise AssertionError(f"tenant {tenant_id!r} pin underflow")
        if n == 1:
            del self._pins[tenant_id]
        else:
            self._pins[tenant_id] = n - 1

    def refcount(self, tenant_id: str) -> int:
        return self._pins.get(tenant_id, 0)

    # ------------------------------------------------------------------

    def register(self, tenant_id: str,
                 adapter: Optional[LowRankAdapter] = None) -> int:
        """Install ``tenant_id``'s delta; returns its row. ``None`` =
        a ZERO-adapter tenant riding the shared null row (bitwise the
        base model — tenancy for isolation/accounting only). Re-
        registering a resident tenant with new weights is refused while
        any slot serves it (the refcount contract) and otherwise
        overwrites in place."""
        if not tenant_id:
            raise ValueError("tenant_id must be a non-empty string")
        if tenant_id in self._rows and self._pins.get(tenant_id):
            raise RuntimeError(
                f"tenant {tenant_id!r} is pinned by "
                f"{self._pins[tenant_id]} live slot(s) — re-registering "
                "would swap weights under an in-flight stream"
            )
        if adapter is None:
            if tenant_id in self._rows and self._rows[tenant_id] != 0:
                self._release_row(tenant_id)
            self._rows[tenant_id] = 0
            self.registrations += 1
            self._notify(tenant_id)
            return 0
        if len(adapter.layers) != self.num_layers:
            raise ValueError(
                f"adapter covers {len(adapter.layers)} layers, bank "
                f"holds {self.num_layers}"
            )
        if adapter.rank > self.rank:
            raise ValueError(
                f"adapter rank {adapter.rank} exceeds the bank's rank "
                f"budget {self.rank}"
            )
        for layer in adapter.layers:
            for tgt, (A, B) in layer.items():
                if tgt not in self.targets:
                    raise ValueError(
                        f"adapter hooks {tgt!r} but the bank stacks "
                        f"only {self.targets}"
                    )
                d_in, d_out = self._dims[tgt]
                if A.shape[0] != d_in or B.shape[1] != d_out:
                    raise ValueError(
                        f"{tgt}: A {A.shape} / B {B.shape} do not match "
                        f"the model's ({d_in}, r) / (r, {d_out})"
                    )
        row = self._rows.get(tenant_id)
        if row is None or row == 0:
            if not self._free:
                raise RuntimeError(
                    f"adapter bank full ({self.capacity - 1} rows; "
                    f"residents: {self.residents()}) — evict a tenant "
                    "first"
                )
            row = self._free.pop()
            if self._rows.get(tenant_id) == 0:
                del self._rows[tenant_id]
        for li, layer in enumerate(adapter.layers):
            for tgt in self.targets:
                As, Bs = self._stacks[li][tgt]
                As[row] = 0.0
                Bs[row] = 0.0
                if tgt in layer:
                    A, B = layer[tgt]
                    r = A.shape[1]
                    As[row, :, :r] = A
                    # scale folds into B ONCE: gather, generate
                    # reference and merged fold all read B*scale.
                    Bs[row, :r, :] = B * adapter.scale
        self._rows[tenant_id] = row
        self.version += 1
        self.registrations += 1
        self._notify(tenant_id)
        return row

    def _release_row(self, tenant_id: str) -> None:
        row = self._rows.pop(tenant_id)
        if row != 0:
            self._free.append(row)

    def evict(self, tenant_id: str) -> None:
        """Drop ``tenant_id``'s row (refused while pinned by live
        slots). The row's stale stack values are harmless — nothing
        gathers an unmapped row — and the next registration overwrites
        them."""
        if tenant_id not in self._rows:
            raise KeyError(f"tenant {tenant_id!r} is not resident")
        if self._pins.get(tenant_id):
            raise RuntimeError(
                f"tenant {tenant_id!r} is pinned by "
                f"{self._pins[tenant_id]} live slot(s) — drain before "
                "evicting"
            )
        self._release_row(tenant_id)
        self.evictions += 1
        self._notify(tenant_id)

    # ------------------------------------------------------------------
    # consumer views

    def stacks(self) -> list:
        """The per-layer host stacks (live references — read-only by
        contract): ``[{target: (A [cap, d_in, r], B [cap, r, d_out])}]``.
        The engine uploads/shards these, keyed on :attr:`version`."""
        return self._stacks

    def adapter_arrays(self, tenant_id: Optional[str]) -> list:
        """The unbatched per-layer ``{target: (A, B)}`` view of one
        tenant's row — EXACTLY the values the serving programs gather
        (scale already folded into B), so ``generate(...,
        adapters=bank.adapter_arrays(t))`` is the engine's bit-
        equivalence reference."""
        row = self.row_of(tenant_id)
        return [
            {tgt: (As[row], Bs[row])
             for tgt, (As, Bs) in layer.items()}
            for layer in self._stacks
        ]

    def merge_adapter_params(self, params, tenant_id: Optional[str]):
        """Offline-merge ``tenant_id``'s delta into a base param tree:
        every hooked kernel becomes ``W + A @ B`` (float32 — the
        ``adapter_impl='merged'`` fold and the ISSUE 14 offline-merged
        reference). The null row merges exact zeros, so a zero-adapter
        tenant's fold IS the base tree bitwise."""
        import jax

        row = self.row_of(tenant_id)
        deltas = [
            {tgt: (As[row].astype(np.float64) @ Bs[row].astype(
                np.float64)).astype(np.float32)
             for tgt, (As, Bs) in layer.items()}
            for layer in self._stacks
        ]

        def merge_leaf(path, leaf):
            names = [str(getattr(p, "key", p)) for p in path]
            li = next((int(n.split("_", 1)[1]) for n in names
                       if n.startswith("block_")), None)
            if li is None or names[-1] != "kernel":
                return leaf
            tgt = next((t for t in ADAPTER_TARGETS if t in names), None)
            if tgt is None or tgt not in deltas[li]:
                return leaf
            d = deltas[li][tgt]
            if not d.any():
                return leaf  # null row: the base tree, bitwise
            return leaf + d.astype(leaf.dtype)

        return jax.tree_util.tree_map_with_path(merge_leaf, params)


def shard_adapter_stacks(model, stacks, n: int):
    """Shard the bank's ``[capacity, ...]`` stacks for tensor-parallel
    decode over ``n`` model shards, mirroring
    :func:`~chainermn_tpu.serving.engine.shard_lm_params`'s Megatron
    placement so the delta adds shard-locally:

    - ``qkv``: A replicated; B column-sharded through the q|k|v head
      grouping (:func:`~chainermn_tpu.parallel.tensor
      .shard_qkv_columns`);
    - ``ff_up``: A replicated; B column-sharded on ``d_ff``;
    - ``proj``/``ff_down``: A row-sharded on the input dim (each
      shard's partial ``(x_sh @ A_sh) @ B`` rides the layer's existing
      psum — no new collective); B replicated.

    Returns per-layer dicts of ``[n, capacity, ...]`` jnp stacks (feed
    through ``shard_map`` with ``P('model')`` on the leading axis).
    """
    import jax.numpy as jnp

    from chainermn_tpu.parallel.tensor import (
        shard_qkv_columns,
        stack_tp_params,
    )

    n_heads = model.num_heads
    kv_heads = model.num_kv_heads or model.num_heads
    head_dim = model.head_dim or model.d_model // model.num_heads

    def repl(a):
        a = jnp.asarray(a)
        return jnp.broadcast_to(a[None], (n,) + a.shape)

    out = []
    for layer in stacks:
        sharded = {}
        for tgt, (A, B) in layer.items():
            A = jnp.asarray(A)
            B = jnp.asarray(B)
            cap, r = A.shape[0], A.shape[2]
            if tgt == "qkv":
                Bs = shard_qkv_columns(
                    B.reshape(cap * r, B.shape[2]),
                    n_heads, kv_heads, head_dim, n,
                ).reshape(n, cap, r, -1)
                sharded[tgt] = (repl(A), Bs)
            elif tgt == "ff_up":
                sharded[tgt] = (repl(A), stack_tp_params(B, n, 2))
            else:  # proj / ff_down: row-parallel input split
                sharded[tgt] = (stack_tp_params(A, n, 1), repl(B))
        out.append(sharded)
    return out
