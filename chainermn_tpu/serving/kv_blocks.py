"""Host-side paged-KV bookkeeping: the block allocator and cache init.

The device-plane half of paging (pool scatter/gather) lives in
:mod:`chainermn_tpu.ops.paged_kv`; this module owns everything that
may change per request without touching the compiled program:

- :class:`BlockAllocator` — a free-list over physical pool blocks and
  the per-slot block tables. Join/leave/growth mutate numpy state only;
  the tables ride into the jitted step as a traced ``[slots,
  max_blocks]`` int32 argument, so occupancy changes NEVER recompile
  (the engine's structural no-recompile test pins this).
- :func:`init_serving_cache` — allocate the engine's cache pytree by
  shape evaluation of the model's slot-decode path (zero FLOPs), the
  serving analog of ``models.transformer.init_cache``.

Layout contract (shared with ``ops.paged_kv``): physical block 0 is
SCRATCH — never owned by a slot; released or never-grown table entries
point at it, so stale writes land in a garbage block instead of a
block that may since belong to another request.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np


class BlockAllocator:
    """Free-list allocator over a paged KV pool.

    ``num_blocks`` counts the WHOLE pool including scratch, matching
    the device pool's leading dimension; ``num_blocks - 1`` blocks are
    allocatable. Allocation failure returns False (the scheduler defers
    admission) — never raises mid-stream.
    """

    SCRATCH = 0

    def __init__(self, num_blocks: int, block_size: int, num_slots: int,
                 max_len: int) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got "
                f"{num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_slots = int(num_slots)
        self.max_blocks = math.ceil(max_len / block_size)
        # LIFO free list: recently released blocks are reused first
        # (warm HBM lines on chip; deterministic tables in tests).
        self._free = list(range(self.num_blocks - 1, self.SCRATCH, -1))
        self.tables = np.full((num_slots, self.max_blocks), self.SCRATCH,
                              np.int32)
        self._owned: list[list[int]] = [[] for _ in range(num_slots)]
        #: bumped on every table mutation — the engine keys its cached
        #: device copy of ``tables`` on it, so the steady-state decode
        #: loop re-uploads only when an admit/grow/release actually
        #: changed a row (H2D-after-D2H is the tunnelled-TPU latency
        #: trap; see .claude/skills/verify/SKILL.md).
        self.version = 0

    # ------------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def utilization(self) -> float:
        """Fraction of the allocatable pool currently owned by slots."""
        denom = self.num_blocks - 1
        return self.blocks_in_use / denom if denom else 0.0

    def blocks_for(self, n_positions: int) -> int:
        """Blocks needed to cover positions ``[0, n_positions)``."""
        return math.ceil(n_positions / self.block_size)

    def can_cover(self, slot: int, n_positions: int) -> bool:
        need = self.blocks_for(n_positions) - len(self._owned[slot])
        return need <= len(self._free)

    def ensure(self, slot: int, n_positions: int) -> bool:
        """Grow ``slot``'s table to cover positions ``[0, n_positions)``.

        Returns False (state unchanged) when the pool cannot supply the
        missing blocks — all-or-nothing, so a deferred admission leaves
        no half-grown table behind.
        """
        if n_positions > self.max_blocks * self.block_size:
            raise ValueError(
                f"slot {slot}: {n_positions} positions exceed the table "
                f"horizon {self.max_blocks * self.block_size}"
            )
        owned = self._owned[slot]
        need = self.blocks_for(n_positions) - len(owned)
        if need > len(self._free):
            return False
        if need > 0:
            self.version += 1
        for _ in range(max(0, need)):
            blk = self._free.pop()
            self.tables[slot, len(owned)] = blk
            owned.append(blk)
        return True

    def trim(self, slot: int, n_positions: int) -> None:
        """Shrink ``slot``'s table to cover no more than positions
        ``[0, n_positions)`` — :meth:`ensure`'s inverse for the tail.
        Freed blocks return to the pool and their table entries point
        back at scratch, so any stale writes they hold become
        unreachable (the :meth:`release` guarantee, per block). The
        engine uses this to make speculative span reservations per-tick
        LEASES: trimming to the committed frontier each tick returns an
        earlier tick's unused extension before it can starve another
        slot. Trimming below the committed history would lose data —
        callers trim to the frontier, never below."""
        owned = self._owned[slot]
        keep = self.blocks_for(n_positions)
        if keep >= len(owned):
            return
        self.version += 1
        while len(owned) > keep:
            blk = owned.pop()
            self.tables[slot, len(owned)] = self.SCRATCH
            self._free.append(blk)

    def release(self, slot: int) -> None:
        """Return ``slot``'s blocks to the pool and point its table back
        at scratch (stale in-flight writes become harmless)."""
        if self._owned[slot]:
            self.version += 1
        self._free.extend(reversed(self._owned[slot]))
        self._owned[slot] = []
        self.tables[slot] = self.SCRATCH


def default_num_blocks(num_slots: int, block_size: int, max_len: int) -> int:
    """Worst-case pool: every slot at ``max_len`` simultaneously, plus
    scratch. Oversubscribe deliberately (smaller ``num_blocks``) when the
    expected resident-token sum is below the worst case — admission then
    defers on pool exhaustion instead of OOMing."""
    return num_slots * math.ceil(max_len / block_size) + 1


def init_serving_cache(model, params, num_slots: int,
                       block_tables: Optional[np.ndarray] = None):
    """Zero-initialised cache pytree for the slot-decode path.

    Pure shape evaluation (``jax.eval_shape``) of one slot-array decode
    step — dense layouts get ``[num_slots, decode_cache_len, kvh, dh]``
    per block, paged layouts get the shared pools. Returns the ``cache``
    collection dict the engine threads through its jitted step.
    """
    import jax
    import jax.numpy as jnp

    dummy = jnp.zeros((num_slots, 1), jnp.int32)
    pos = jnp.zeros((num_slots,), jnp.int32)
    bt = None
    if model.kv_layout == "paged":
        if block_tables is not None:
            bt = jnp.asarray(block_tables, jnp.int32)
        else:
            max_blocks = math.ceil(
                (model.decode_cache_len or model.max_len)
                / model.kv_block_size
            )
            bt = jnp.zeros((num_slots, max_blocks), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.apply(
            params, dummy, train=False, decode=True,
            decode_positions=pos, block_tables=bt, mutable=["cache"],
        )[1]
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), variables
    )["cache"]
