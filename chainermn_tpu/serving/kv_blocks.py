"""Host-side paged-KV bookkeeping: the block allocator and cache init.

The device-plane half of paging (pool scatter/gather) lives in
:mod:`chainermn_tpu.ops.paged_kv`; this module owns everything that
may change per request without touching the compiled program:

- :class:`BlockAllocator` — a free-list over physical pool blocks and
  the per-slot block tables. Join/leave/growth mutate numpy state only;
  the tables ride into the jitted step as a traced ``[slots,
  max_blocks]`` int32 argument, so occupancy changes NEVER recompile
  (the engine's structural no-recompile test pins this). ISSUE 7 grows
  it per-block REFCOUNTS: a physical block may appear in several slots'
  tables (cross-request prefix sharing) and in the prefix trie's cache;
  ``release``/``trim`` decrement instead of freeing, and a block
  returns to the free list only when no slot references it and the trie
  no longer caches it.
- :class:`PrefixCache` — a block-granular radix trie over token ids
  (one node = one FULL block's tokens at its exact block index, so a
  cached block is only ever valid at the depth it was written for —
  position encodings are baked into the KV). A joining request adopts
  the longest matching full-block chain and prefills only the unshared
  tail; completed prefills insert their full blocks. Eviction is LRU
  over refcount-0 leaves, driven through the allocator's reclaim hook
  when ``ensure`` would otherwise fail — the trie is a best-effort
  cache that can never starve a live slot.
- :func:`init_serving_cache` — allocate the engine's cache pytree by
  shape evaluation of the model's slot-decode path (zero FLOPs), the
  serving analog of ``models.transformer.init_cache``.

Layout contract (shared with ``ops.paged_kv``): physical block 0 is
SCRATCH — never owned by a slot; released or never-grown table entries
point at it, so stale writes land in a garbage block instead of a
block that may since belong to another request.

Copy-on-write contract (the engine's step wrappers enforce it): a
device-plane WRITE may only target a block that exactly one slot
references and the trie does not cache (:meth:`BlockAllocator
.shared_for_write`); the engine copies the block first
(:func:`chainermn_tpu.ops.paged_kv.copy_block`) and repoints the
writing slot's table row (:meth:`BlockAllocator.cow_replace`) — host
rewrite for the writer only, every other reader (and the trie's cached
copy) untouched. Partial tail blocks are never inserted into the trie,
so COW only ever triggers on the boundary block of a full-prefix hit.
"""

from __future__ import annotations

import heapq
import itertools
import math
from typing import Callable, Optional, Sequence

import numpy as np


class BlockAllocator:
    """Free-list allocator over a paged KV pool.

    ``num_blocks`` counts the WHOLE pool including scratch, matching
    the device pool's leading dimension; ``num_blocks - 1`` blocks are
    allocatable. Allocation failure returns False (the scheduler defers
    admission) — never raises mid-stream.
    """

    SCRATCH = 0

    def __init__(self, num_blocks: int, block_size: int, num_slots: int,
                 max_len: int) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got "
                f"{num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_slots = int(num_slots)
        self.max_blocks = math.ceil(max_len / block_size)
        # LIFO free list: recently released blocks are reused first
        # (warm HBM lines on chip; deterministic tables in tests).
        self._free = list(range(self.num_blocks - 1, self.SCRATCH, -1))
        self.tables = np.full((num_slots, self.max_blocks), self.SCRATCH,
                              np.int32)
        self._owned: list[list[int]] = [[] for _ in range(num_slots)]
        #: per-block slot-table reference counts (scratch stays 0).
        #: A block may appear in several slots' tables (prefix sharing);
        #: it returns to the free list only at refcount 0 AND not
        #: trie-cached.
        self.refcounts = np.zeros(self.num_blocks, np.int32)
        #: blocks held by the prefix trie's cache — kept out of the free
        #: list at refcount 0 until evicted (best-effort cache).
        self._cached: set[int] = set()
        #: reclaim hook (set by :class:`PrefixCache`): called with the
        #: block shortfall when ``ensure`` would fail; returns how many
        #: blocks it freed. Live slots can therefore never be starved by
        #: cached-but-unreferenced blocks.
        self.reclaimer: Optional[Callable[[int], int]] = None
        #: capacity twin of the reclaim hook (set by :class:`PrefixCache`
        #: alongside it): how many blocks the hook could free RIGHT NOW.
        #: Strictly less than :meth:`blocks_cached` when a live slot
        #: references a cached chain's descendant — those ancestors never
        #: become evictable leaves.
        self.reclaim_capacity: Optional[Callable[[], int]] = None
        #: bumped on every table mutation — the engine keys its cached
        #: device copy of ``tables`` on it, so the steady-state decode
        #: loop re-uploads only when an admit/grow/release actually
        #: changed a row (H2D-after-D2H is the tunnelled-TPU latency
        #: trap; see .claude/skills/verify/SKILL.md).
        self.version = 0

    # ------------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by at least one slot's table (cached-but-
        unreferenced trie blocks are NOT in use — they are reclaimable,
        counted by :meth:`blocks_cached`)."""
        return int((self.refcounts > 0).sum())

    def blocks_cached(self) -> int:
        """Trie-cached blocks no slot references. An upper bound on what
        eviction can free — a cached ancestor whose descendant a live
        slot references is counted here but pinned; the deliverable
        number is the ``reclaim_capacity`` hook."""
        return sum(1 for b in self._cached if self.refcounts[b] == 0)

    def blocks_shared(self) -> int:
        """Blocks referenced by MORE than one slot's table."""
        return int((self.refcounts > 1).sum())

    def utilization(self) -> float:
        """Fraction of the allocatable pool currently owned by slots."""
        denom = self.num_blocks - 1
        return self.blocks_in_use / denom if denom else 0.0

    def blocks_for(self, n_positions: int) -> int:
        """Blocks needed to cover positions ``[0, n_positions)``."""
        return math.ceil(n_positions / self.block_size)

    def can_cover(self, slot: int, n_positions: int) -> bool:
        """Whether :meth:`ensure` for ``n_positions`` would succeed right
        now. Counts only blocks the reclaim hook could ACTUALLY free —
        not every cached refcount-0 block: a cached ancestor whose
        descendant is referenced by a live slot never becomes an
        evictable leaf, so it must not be promised here."""
        need = self.blocks_for(n_positions) - len(self._owned[slot])
        spare = len(self._free)
        if self.reclaim_capacity is not None:
            spare += self.reclaim_capacity()
        return need <= spare

    def owned_blocks(self, slot: int) -> list[int]:
        """``slot``'s physical blocks in table order (a copy)."""
        return list(self._owned[slot])

    def _take_free(self, need: int) -> bool:
        """Whether the free list can supply ``need`` blocks, reclaiming
        cached-but-unreferenced trie blocks (leaf-first LRU, via the
        hook) before giving up. A HOPELESS request — more than free +
        reclaimable — evicts nothing: flushing the hot cache for an
        admission that defers anyway would regress every follower."""
        if need > len(self._free) and self.reclaimer is not None:
            if self.reclaim_capacity is not None:
                if need > len(self._free) + self.reclaim_capacity():
                    return False
            self.reclaimer(need - len(self._free))
        return need <= len(self._free)

    def _unref(self, blk: int) -> None:
        """Drop one slot-table reference; the block returns to the free
        list only when nothing references it and the trie does not
        cache it."""
        self.refcounts[blk] -= 1
        if self.refcounts[blk] < 0:  # pragma: no cover - internal guard
            raise AssertionError(f"block {blk} refcount underflow")
        if self.refcounts[blk] == 0 and blk not in self._cached:
            self._free.append(blk)

    def ensure(self, slot: int, n_positions: int) -> bool:
        """Grow ``slot``'s table to cover positions ``[0, n_positions)``.

        Returns False (state unchanged) when the pool cannot supply the
        missing blocks — all-or-nothing, so a deferred admission leaves
        no half-grown table behind. Before deferring, cached-but-
        unreferenced prefix-trie blocks are reclaimed through the
        allocator's hook (leaf-first LRU), so the best-effort cache can
        never starve a live slot.
        """
        if n_positions > self.max_blocks * self.block_size:
            raise ValueError(
                f"slot {slot}: {n_positions} positions exceed the table "
                f"horizon {self.max_blocks * self.block_size}"
            )
        owned = self._owned[slot]
        need = self.blocks_for(n_positions) - len(owned)
        if need > 0 and not self._take_free(need):
            return False
        if need > 0:
            self.version += 1
        for _ in range(max(0, need)):
            blk = self._free.pop()
            self.refcounts[blk] = 1
            self.tables[slot, len(owned)] = blk
            owned.append(blk)
        return True

    def adopt(self, slot: int, blocks: Sequence[int]) -> None:
        """Append already-filled ``blocks`` to ``slot``'s table (the
        prefix-trie hit path): each gains one reference — nothing is
        popped from the free list, nothing is copied. Callers adopt
        BEFORE :meth:`ensure`-ing the tail, so the table stays
        position-ordered."""
        if not blocks:
            return
        owned = self._owned[slot]
        if len(owned) + len(blocks) > self.max_blocks:
            raise ValueError(
                f"slot {slot}: adopting {len(blocks)} blocks over "
                f"{len(owned)} owned exceeds the table horizon"
            )
        self.version += 1
        for blk in blocks:
            if blk == self.SCRATCH:
                raise ValueError("cannot adopt the scratch block")
            self.refcounts[blk] += 1
            self.tables[slot, len(owned)] = blk
            owned.append(blk)

    def shared_for_write(self, blk: int) -> bool:
        """Whether a device-plane write to ``blk`` must copy first:
        another slot references it, or the prefix trie caches it (a
        write would corrupt the trie's pristine copy for future
        adopters)."""
        return bool(self.refcounts[blk] > 1 or blk in self._cached)

    def alloc_block(self) -> Optional[int]:
        """Pop one free block (refcount 1, unattached to any table) —
        the copy-on-write destination. None on genuine exhaustion
        (after the reclaim hook ran)."""
        if not self._take_free(1):
            return None
        blk = self._free.pop()
        self.refcounts[blk] = 1
        return blk

    def cow_replace(self, slot: int, index: int, new_blk: int) -> int:
        """Repoint table entry ``index`` of ``slot`` at ``new_blk`` (a
        block from :meth:`alloc_block`, already holding the copied
        contents) and drop the old block's reference. Host rewrite for
        the WRITING slot only — every other reader of the old block,
        and the trie's cached copy, are untouched. Returns the old
        physical block id."""
        old = self._owned[slot][index]
        self.version += 1
        self._owned[slot][index] = int(new_blk)
        self.tables[slot, index] = new_blk
        self._unref(old)
        return old

    # ---- trie-cache bookkeeping (driven by PrefixCache) --------------

    def mark_cached(self, blk: int) -> None:
        self._cached.add(int(blk))

    def uncache(self, blk: int) -> None:
        """Drop the trie's hold on ``blk`` (eviction); frees it when no
        slot references it."""
        blk = int(blk)
        self._cached.discard(blk)
        if self.refcounts[blk] == 0:
            self._free.append(blk)

    def trim(self, slot: int, n_positions: int) -> None:
        """Shrink ``slot``'s table to cover no more than positions
        ``[0, n_positions)`` — :meth:`ensure`'s inverse for the tail.
        Freed blocks return to the pool and their table entries point
        back at scratch, so any stale writes they hold become
        unreachable (the :meth:`release` guarantee, per block). The
        engine uses this to make speculative span reservations per-tick
        LEASES: trimming to the committed frontier each tick returns an
        earlier tick's unused extension before it can starve another
        slot. Trimming below the committed history would lose data —
        callers trim to the frontier, never below."""
        owned = self._owned[slot]
        keep = self.blocks_for(n_positions)
        if keep >= len(owned):
            return
        self.version += 1
        while len(owned) > keep:
            blk = owned.pop()
            self.tables[slot, len(owned)] = self.SCRATCH
            self._unref(blk)

    def release(self, slot: int) -> None:
        """Drop ``slot``'s references and point its table back at
        scratch (stale in-flight writes become harmless). Blocks still
        referenced by other slots, or cached by the prefix trie, stay
        out of the free list (the refcount contract); a second release
        of an already-released slot is a no-op (idempotent — no version
        churn)."""
        if self._owned[slot]:
            self.version += 1
        for blk in reversed(self._owned[slot]):
            self._unref(blk)
        self._owned[slot] = []
        self.tables[slot] = self.SCRATCH


class _TrieNode:
    """One full block's tokens at one block depth. ``children`` keys are
    the NEXT block's token tuple; ``block`` is the physical pool block
    holding this node's KV."""

    __slots__ = ("tokens", "block", "children", "parent", "last_used")

    def __init__(self, tokens, block, parent) -> None:
        self.tokens = tokens
        self.block = block
        self.children: dict = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Block-granular radix trie over token ids (ISSUE 7 tentpole).

    One node = one FULL block's tokens at its exact depth, so a lookup
    walks the prompt in ``block_size`` chunks from the root: the chain
    of matches is the longest cached prefix, and its physical blocks
    can be adopted verbatim (KV for a given token prefix at given
    positions is deterministic — the engine's equivalence suite pins
    shared == unshared streams bitwise). Partial tail blocks are never
    inserted, which is what confines copy-on-write to the boundary
    block of a full-prefix hit.

    Registers itself as the allocator's reclaim hook: when ``ensure``
    would fail, refcount-0 LEAVES are evicted LRU-first (an interior
    node is never evicted before its descendants, so a cached chain can
    never dangle). Thread-unsafe like the allocator — both are owned by
    the engine's host loop.
    """

    def __init__(self, allocator: BlockAllocator) -> None:
        self.alloc = allocator
        self.block_size = allocator.block_size
        #: per-NAMESPACE trie roots (ISSUE 14: tenant isolation — a
        #: lookup/insert only ever walks its own namespace's tree, so a
        #: cross-tenant block adoption is structurally impossible, not
        #: merely policy). ``None`` is the default namespace
        #: (single-tenant engines never see another).
        self._roots: dict = {
            None: _TrieNode((), BlockAllocator.SCRATCH, None)
        }
        self._clock = itertools.count(1)
        #: number of cached nodes (== cached blocks, the trie-size
        #: gauge), summed across namespaces
        self.n_nodes = 0
        #: lifetime eviction count (bench/dryrun visibility)
        self.evictions = 0
        allocator.reclaimer = self.reclaim
        allocator.reclaim_capacity = self.reclaimable

    def _root_for(self, namespace, create: bool = False):
        root = self._roots.get(namespace)
        if root is None and create:
            root = _TrieNode((), BlockAllocator.SCRATCH, None)
            self._roots[namespace] = root
        return root

    def _chunks(self, tokens: Sequence[int]):
        bs = self.block_size
        for i in range(0, (len(tokens) // bs) * bs, bs):
            yield tuple(int(t) for t in tokens[i:i + bs])

    def lookup(self, tokens: Sequence[int],
               namespace=None) -> list[int]:
        """Physical blocks of the longest cached FULL-block prefix of
        ``tokens`` under ``namespace`` (possibly empty). Touches the
        matched chain's LRU stamps — a hit protects its ancestors from
        eviction ordering."""
        node = self._root_for(namespace)
        if node is None:
            return []
        out: list[int] = []
        stamp = next(self._clock)
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_used = stamp
            out.append(child.block)
            node = child
        return out

    def match_depth(self, tokens: Sequence[int], namespace=None) -> int:
        """How many FULL blocks of ``tokens`` the trie holds under
        ``namespace`` — a READ-ONLY probe (no LRU stamp: the cluster
        router consults every replica's trie per routing decision, and
        a probe that touched stamps would let mere consideration pin
        chains a real adoption never used). :meth:`lookup` remains the
        adopting walk."""
        node = self._root_for(namespace)
        if node is None:
            return 0
        depth = 0
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            depth += 1
            node = child
        return depth

    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               namespace=None) -> int:
        """Cache the FULL blocks of a completed prefill under
        ``namespace``: ``blocks[j]`` holds the KV of
        ``tokens[j*bs:(j+1)*bs]``. Chunks already cached
        are left as-is (first writer wins — the existing node's block is
        the one future joins adopt; the inserting slot simply keeps its
        private copy). Returns how many new nodes were cached."""
        node = self._root_for(namespace, create=True)
        added = 0
        stamp = next(self._clock)
        for j, chunk in enumerate(self._chunks(tokens)):
            if j >= len(blocks):
                break
            child = node.children.get(chunk)
            if child is None:
                child = _TrieNode(chunk, int(blocks[j]), node)
                node.children[chunk] = child
                self.alloc.mark_cached(child.block)
                self.n_nodes += 1
                added += 1
            child.last_used = stamp
            node = child
        return added

    def drop_namespace(self, namespace) -> int:
        """Invalidate EVERY cached block under ``namespace`` (ISSUE 14
        review finding: an adapter re-registration changes the weights
        that produced the tenant's cached KV — a later join adopting
        those blocks would silently diverge from ``generate`` under the
        new adapter, so the engine drops the namespace on
        register/evict). Blocks are uncached, not force-freed: a live
        slot still reading one keeps it until release. Returns the
        number of nodes dropped."""
        root = self._roots.pop(namespace, None)
        if root is None:
            return 0
        dropped = 0
        stack = list(root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.alloc.uncache(node.block)
            dropped += 1
        self.n_nodes -= dropped
        self.evictions += dropped
        if namespace is None:
            # The default namespace always exists (single-tenant
            # engines consult it unconditionally).
            self._root_for(None, create=True)
        return dropped

    def namespace_blocks(self, namespace=None) -> int:
        """Cached nodes under one namespace (the per-tenant trie-size
        probe; the isolation test pins zero overlap between tenants'
        block sets)."""
        root = self._root_for(namespace)
        if root is None:
            return 0
        n, stack = 0, [root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not root:
                n += 1
        return n

    def _evictable_leaves(self) -> list[_TrieNode]:
        out = []
        for root in self._roots.values():
            stack = [root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (node is not root and not node.children
                        and self.alloc.refcounts[node.block] == 0):
                    out.append(node)
        return out

    def reclaimable(self) -> int:
        """Blocks :meth:`reclaim` could free right now: cached nodes
        whose WHOLE subtree is refcount-0. A live descendant pins its
        cached ancestors — they never become evictable leaves — so this
        is strictly tighter than the allocator's ``blocks_cached``
        gauge (the allocator's ``can_cover`` promise reads this)."""
        def walk(node: _TrieNode, root: _TrieNode) -> tuple[int, bool]:
            n, subtree_free = 0, True
            for child in node.children.values():
                cn, cf = walk(child, root)
                n += cn
                subtree_free = subtree_free and cf
            if node is root:
                return n, subtree_free
            if subtree_free and self.alloc.refcounts[node.block] == 0:
                return n + 1, True
            return n, False

        return sum(walk(root, root)[0] for root in self._roots.values())

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` blocks, LRU leaf first (the allocator's
        ensure-would-fail hook). Evicting a leaf may expose its parent
        as the next candidate — the parent joins the candidate heap
        then, so one trie scan serves the whole batch (refcounts don't
        change during eviction). Returns the blocks actually freed."""
        roots = set(map(id, self._roots.values()))
        heap = [(nd.last_used, id(nd), nd)
                for nd in self._evictable_leaves()]
        heapq.heapify(heap)
        freed = 0
        while freed < n and heap:
            _, _, victim = heapq.heappop(heap)
            del victim.parent.children[victim.tokens]
            self.alloc.uncache(victim.block)
            self.n_nodes -= 1
            self.evictions += 1
            freed += 1
            parent = victim.parent
            if (id(parent) not in roots and not parent.children
                    and self.alloc.refcounts[parent.block] == 0):
                heapq.heappush(
                    heap, (parent.last_used, id(parent), parent))
        return freed


def default_num_blocks(num_slots: int, block_size: int, max_len: int) -> int:
    """Worst-case pool: every slot at ``max_len`` simultaneously, plus
    scratch. Oversubscribe deliberately (smaller ``num_blocks``) when the
    expected resident-token sum is below the worst case — admission then
    defers on pool exhaustion instead of OOMing."""
    return num_slots * math.ceil(max_len / block_size) + 1


def init_serving_cache(model, params, num_slots: int,
                       block_tables: Optional[np.ndarray] = None):
    """Zero-initialised cache pytree for the slot-decode path.

    Pure shape evaluation (``jax.eval_shape``) of one slot-array decode
    step — dense layouts get ``[num_slots, decode_cache_len, kvh, dh]``
    per block, paged layouts get the shared pools. Returns the ``cache``
    collection dict the engine threads through its jitted step.
    """
    import jax
    import jax.numpy as jnp

    dummy = jnp.zeros((num_slots, 1), jnp.int32)
    pos = jnp.zeros((num_slots,), jnp.int32)
    bt = None
    if model.kv_layout == "paged":
        if block_tables is not None:
            bt = jnp.asarray(block_tables, jnp.int32)
        else:
            max_blocks = math.ceil(
                (model.decode_cache_len or model.max_len)
                / model.kv_block_size
            )
            bt = jnp.zeros((num_slots, max_blocks), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.apply(
            params, dummy, train=False, decode=True,
            decode_positions=pos, block_tables=bt, mutable=["cache"],
        )[1]
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), variables
    )["cache"]
