"""Speculative draft-and-verify decoding: the drafter side (ISSUE 5
tentpole).

PR 4's engine decodes one token per jitted step per slot, so its
steady-state throughput is bounded by per-step latency — and under
tensor-parallel decode every step pays 2 tiny all-reduces per layer,
exactly the small-collective latency regime the related transport work
targets (HiCCL, arXiv:2408.05962; The Big Send-off, arXiv:2504.18658).
Speculation attacks the same cost from the SCHEDULE side: draft K cheap
token guesses per slot, score all of them in ONE jitted verify forward
(``[slots, K+1]`` positions through
``TransformerBlock._slot_decode_attend``'s per-row spans), and keep the
longest prefix that matches the model's own greedy choices — the
launch overhead and the per-token collectives amortize by the accepted
length, and the output stream is bit-identical to sequential greedy
decode by construction (every emitted token IS an argmax the verify
forward produced).

Drafter contract (the engine's ``drafter=`` argument): an object with

    propose(history, k) -> sequence of at most k draft token ids

where ``history`` is the slot's committed stream so far (prompt +
generated, including the pending last token). Proposals are HINTS, not
promises: a wrong draft costs one wasted verify column, never a wrong
token — acceptance filters everything through the model's own token at
each position: its argmax at temperature 0, its counter-keyed sample at
temperature > 0 (the rejection-sampling rule,
:func:`rejection_accept_length`; docs/serving.md "Speculative decoding"
and "Sampling"). Returning fewer than ``k`` (or nothing) is fine; the
engine pads the verify batch and caps acceptance at the true proposal
length.

Two dependency-free drafters ship here:

- :class:`NgramDrafter` — prompt-lookup speculation over the request's
  OWN token history (the assisted-generation idea of arXiv:2304.04487
  /  HF ``prompt_lookup_num_tokens``, reduced to its no-second-model
  core): propose the continuation of the most recent earlier occurrence
  of the stream's tail n-gram. Zero state, zero FLOPs, surprisingly
  strong on the repetitive tails LMs actually emit.
- :class:`ModelDrafter` — the optional small-draft-model path reusing
  :class:`~chainermn_tpu.models.transformer.TransformerLM`: greedy
  continuations from a cheaper model, forwarded over the bucketed
  history (compiles bounded by the bucket ladder, the prefill
  discipline). Pay draft FLOPs only when a cheap model that imitates
  the target well is actually available.
"""

from __future__ import annotations

from typing import Optional, Sequence

from chainermn_tpu.datasets.bucketing import DEFAULT_BUCKETS, bucket_length


class NgramDrafter:
    """Prompt-lookup drafter: match the stream's tail n-gram against its
    own earlier history and propose what followed the MOST RECENT match.

    Longer n-grams are tried first (``max_ngram`` down to 1) — a longer
    match is more specific, so its continuation is a better guess; the
    most recent occurrence wins because generation drifts (the tokens
    right before the tail describe the current context best). The scan
    only looks back ``max_scan`` tokens: proposing is on the per-slot
    per-tick hot path, and an unbounded backward scan would grow each
    tick linearly with the stream — a long-lived slot's miss (the
    common case for a 1-gram tail that never repeats) must stay O(1)
    -ish, and matches beyond the window are too far from the current
    context to draft well anyway.
    """

    def __init__(self, max_ngram: int = 3, max_scan: int = 512) -> None:
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        if max_scan < 2:
            raise ValueError(f"max_scan must be >= 2, got {max_scan}")
        self.max_ngram = int(max_ngram)
        self.max_scan = int(max_scan)

    def propose(self, history: Sequence[int], k: int) -> list:
        h = list(history)[-self.max_scan:]
        L = len(h)
        if k < 1 or L < 2:
            return []
        for n in range(min(self.max_ngram, L - 1), 0, -1):
            tail = h[L - n:]
            # scan for the most recent occurrence strictly before the tail
            for i in range(L - n - 1, -1, -1):
                if h[i:i + n] == tail:
                    return h[i + n:i + n + k]
        return []


class ModelDrafter:
    """Draft with a (smaller) ``TransformerLM``: greedy continuations of
    the slot's history, one forward per drafted token.

    The forward runs the plain (non-decode) causal path over the history
    right-padded to the bucket ladder — causal attention makes trailing
    pads invisible to the true last position, so one compiled program
    per bucket covers every history length (the prefill discipline;
    drafting never touches the TARGET model's jit cache). No KV cache is
    kept: the drafter re-reads its whole context per token, which is the
    deliberate trade — zero per-slot draft state to roll back, at draft
    FLOPs that only pay off when the draft model is much cheaper than
    the target.
    """

    def __init__(self, model, params, *,
                 prefill_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 pad_id: int = 0) -> None:
        from chainermn_tpu.models.transformer import TransformerLM

        if not isinstance(model, TransformerLM):
            raise TypeError(
                f"ModelDrafter drafts with a TransformerLM, got "
                f"{type(model).__name__}"
            )
        if model.return_hidden or not model.causal:
            raise ValueError("drafting needs a causal LM with logits "
                             "(return_hidden=False, causal=True)")
        self.model = model
        self.params = params
        self.pad_id = int(pad_id)
        self._buckets = tuple(
            b for b in sorted(set(prefill_buckets)) if b <= model.max_len
        ) or (model.max_len,)
        if self._buckets[-1] < model.max_len:
            self._buckets = self._buckets + (model.max_len,)
        self._jits: dict = {}

    def _fwd(self, bucket: int):
        if bucket in self._jits:
            return self._jits[bucket]
        import jax
        import jax.numpy as jnp

        model, params = self.model, self.params

        def fn(tokens, true_len):
            logits = model.apply(params, tokens, train=False)
            last = jnp.take(logits[0], true_len - 1, axis=0)  # [V]
            return jnp.argmax(last, axis=-1).astype(jnp.int32)

        self._jits[bucket] = jax.jit(fn)
        return self._jits[bucket]

    def propose(self, history: Sequence[int], k: int) -> list:
        import jax.numpy as jnp
        import numpy as np

        toks = list(history)
        out: list = []
        for _ in range(max(0, k)):
            if len(toks) >= self.model.max_len:
                break  # the draft model's own context is exhausted
            bucket = bucket_length(len(toks), self._buckets)
            padded = np.full((1, bucket), self.pad_id, np.int32)
            padded[0, :len(toks)] = toks
            nxt = int(self._fwd(bucket)(
                jnp.asarray(padded), jnp.int32(len(toks))
            ))
            out.append(nxt)
            toks.append(nxt)
        return out


def accept_length(drafts: Sequence[int], greedy: Sequence[int],
                  room: Optional[int] = None) -> int:
    """Longest accepted draft prefix: ``drafts[t]`` is accepted while it
    equals ``greedy[t]`` — the model's own argmax at the same position —
    so the committed stream is exactly the greedy stream regardless of
    what was drafted. ``room`` additionally caps acceptance (horizon or
    paged-coverage limits); the cap costs throughput, never
    correctness."""
    limit = min(len(drafts), len(greedy))
    if room is not None:
        limit = min(limit, max(0, int(room)))
    a = 0
    while a < limit and int(drafts[a]) == int(greedy[a]):
        a += 1
    return a


def rejection_accept_length(drafts: Sequence[int], sampled: Sequence[int],
                            room: Optional[int] = None) -> int:
    """Sampled-mode acceptance: the standard speculative rejection-
    sampling rule, specialised to DETERMINISTIC (point-mass) drafters.

    The general rule (Leviathan et al., arXiv:2211.17192) accepts draft
    token ``t`` with probability ``min(1, p(t)/q(t))`` and, on
    rejection, emits a sample from the residual ``max(p − q, 0)``
    renormalised — the pair that makes the committed stream's law equal
    sequential sampling from ``p`` for ANY proposal ``q``. Both shipped
    drafters propose deterministically, so ``q`` is a point mass
    ``δ_d`` at the drafted token ``d``. Realise the rule by the maximal
    coupling: draw ``x ~ p`` with the position's counter key
    (``engine._sample`` over the verify grid) and accept the draft iff
    ``x == d``. That IS the rule — acceptance happens with probability
    ``p(d) = min(1, p(d)/q(d)) · q(d)``-mass, and on rejection the
    emitted ``x``, conditioned on ``x ≠ d``, has law ``p(·)/(1 − p(d))``
    off ``d``, which is exactly the renormalised residual
    ``max(p − δ_d, 0)``.

    So the comparison loop is :func:`accept_length` verbatim, run
    against the SAMPLED verify grid instead of the argmax grid — which
    also makes the committed stream BIT-IDENTICAL to sequential
    counter-keyed sampling at a fixed seed (every committed token is
    the very sample the sequential path would have drawn at that
    position given the identical history), a stronger property than
    distribution-exactness alone. Distribution-exactness is pinned
    statistically in tests/test_sampling.py; ``room`` caps acceptance
    exactly as in the greedy rule (horizon/paged coverage — throughput,
    never correctness)."""
    return accept_length(drafts, sampled, room)
