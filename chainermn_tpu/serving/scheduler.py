"""Host-side admission loop over a :class:`ServingEngine` (ISSUE 4).

FCFS by construction (the queue is arrival-ordered); the
``prefill_priority`` policy additionally drains every admissible queued
request into free slots BEFORE each decode step (prefill-priority in
the continuous-batching sense: new requests never wait behind decode
cadence when a slot is open), while plain ``fcfs`` admits at most one
request per decode round so in-flight decode latency stays level.
The ``slo`` policy (ISSUE 11) admits like ``prefill_priority`` but
schedules against per-request TTFT/TPOT targets
(:class:`Request.ttft_target_ms` / ``tpot_target_ms``): chunk rows per
mixed tick are capped while any in-flight stream is over its TPOT
budget (decode-interference bound), and a queue head whose TTFT target
is at risk may PREEMPT the in-flight request deepest over its own TPOT
budget — the victim's partial stream parks as resume state on the
Request and it re-enters the BACK of the queue with its ORIGINAL
arrival stamp, so queue_wait/TTFT keep measuring the whole journey
(:func:`keep_arrival`, the one stamp rule all three submission paths
share). Resume re-prefills only what the prefix trie cannot serve —
the engine's :meth:`~ServingEngine.preempt` publishes the victim's
written blocks into the trie first — and the resumed stream is
bit-identical to the uninterrupted one (greedy determinism at
temperature 0, pinned in tests/test_chunked_prefill.py; counter-based
sampling keys plus the stored ``Request.seed`` at temperature > 0,
pinned in tests/test_sampling.py).

With a CHUNKED engine (``engine.prefill_chunk > 0``, ISSUE 11) the
scheduler admits through ``chunked_join`` (no forward at admission) and
drives ``mixed_step`` instead of decode/verify steps: each tick
advances up to ``prefill_chunk`` prompt tokens per filling slot while
active slots decode in the same compiled program, emitting one
``prefill_chunk`` event per advanced fill row; the ``prefill`` event
(TTFT sample) is emitted when the fill COMPLETES and the first token is
sampled.

Every phase emits a schema-versioned ``serving`` trace event (the wire
-event discipline of PR 2 — ``tools/trace_report.py`` grows a serving
section from exactly these):

- ``phase='queue_wait'`` — request, ``dur_s`` from submit to admission;
- ``phase='prefill'`` — request, slot, bucket, prompt_len, ``dur_s``,
  ``ttft_s`` (submit → first token: the TTFT sample — the prefill
  samples the request's first token);
- ``phase='decode_step'`` — ``n_active``/``n_slots`` (occupancy),
  ``tokens`` produced, ``dur_s`` (the per-token latency sample under
  plain decode: each active request got exactly one token; under
  speculation it is the TICK latency for 1..K+1 tokens per request);
- ``phase='finish'`` — request, generated count, ``dur_s`` from submit.

Speculative ticks (``engine.spec_tokens > 0``) additionally emit one
``speculate`` event per tick — ``drafted``/``accepted`` token counts
and the per-slot ``accept_lens`` — the accounting behind the
acceptance-rate rollup and trace_report's accept-length histogram.

Prefix-sharing admissions (``engine.prefix_cache_enabled``, ISSUE 7)
emit one ``prefix_cache`` event per admitted request — prompt/hit/
prefilled token counts and COW copies — the MEASURED record that a
cache-hit request prefilled only its unshared tail (the bench
acceptance reads exactly these), rolled up by
``trace.summarize_serving`` and mirrored as live counters by the
metrics tap. Admission also refreshes the ``kv_prefix_hit_rate`` and
``kv_prefix_trie_blocks`` gauges (engine state, not events).

:meth:`Scheduler.summary` rolls the same numbers up locally (tokens/s,
p50/p99 per-token latency, mean occupancy) so callers without a trace
recorder still get the accounting.
"""

from __future__ import annotations

import itertools
import math
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

# Pure-stdlib causal-id plumbing (ISSUE 17): per-request events gain
# journey/span/parent fields; host metadata only, so recorder-on and
# recorder-off programs still lower identically.
from chainermn_tpu.observability import journey as _journey

POLICIES = ("fcfs", "prefill_priority", "slo")


class DeficitRoundRobin:
    """Weighted fair-share pick over per-tenant backlogs (ISSUE 14).

    Classic deficit round robin adapted to an admission queue: each
    backlogged tenant accrues ``weight * quantum`` credit per round,
    and a tenant is served when its deficit covers its head request's
    cost (here: the request's ``max_new_tokens`` — decode work is the
    contended resource under saturation). Under sustained saturation
    the admitted work converges to the weight ratio; the math is
    pinned in isolation in tests/test_adapters.py.

    Invariants the tests drive:

    - **Weighted shares under saturation** — admissions track
      ``weight`` proportionally, whatever the per-request costs.
    - **No idle hoarding** — a tenant with nothing queued has its
      deficit RESET (``select`` drops tenants absent from the
      backlog), so returning after an idle stretch cannot burst-starve
      the tenants that kept the engine busy.
    - **Quota churn mid-run** — :meth:`set_weight` takes effect on the
      next ``select``; no restart, no queue reshuffle.

    ``select`` never mutates queues — it names the tenant whose head
    should be TRIED next; the caller charges the cost via
    :meth:`charge` only when admission actually succeeds (a refused
    admission must not burn the tenant's credit)."""

    def __init__(self, quantum: float = 1.0) -> None:
        if quantum <= 0:
            raise ValueError(f"quantum must be > 0, got {quantum}")
        self.quantum = float(quantum)
        self._weights: dict = {}
        self._deficit: dict = {}
        self._last = None

    def set_weight(self, tenant, weight: float) -> None:
        """Set ``tenant``'s share weight (> 0; unlisted tenants weigh
        1.0). Takes effect on the next :meth:`select` — quota churn
        mid-run is the supported path, not an edge case."""
        if weight <= 0:
            raise ValueError(
                f"tenant weight must be > 0, got {weight} for "
                f"{tenant!r}"
            )
        self._weights[tenant] = float(weight)

    def weight(self, tenant) -> float:
        return self._weights.get(tenant, 1.0)

    def deficit(self, tenant) -> float:
        """Current credit (test/introspection surface)."""
        return self._deficit.get(tenant, 0.0)

    @staticmethod
    def _order_key(tenant):
        return (tenant is not None, str(tenant))

    def select(self, costs: Mapping) -> Optional[object]:
        """Pick the tenant to serve next from ``costs`` (tenant ->
        head-request cost, backlogged tenants only). Tenants absent
        from ``costs`` lose their deficit (idle reset). Credit is
        granted in whole rounds — just enough that SOME tenant can
        afford its head — then the first affordable tenant after the
        last-served one (stable round-robin order) wins."""
        for t in [t for t in self._deficit if t not in costs]:
            del self._deficit[t]
        if not costs:
            return None
        order = sorted(costs, key=self._order_key)
        if self._last in order:
            i = order.index(self._last) + 1
            order = order[i:] + order[:i]
        rounds = min(
            max(0, math.ceil(
                (float(costs[t]) - self._deficit.get(t, 0.0))
                / (self.weight(t) * self.quantum)
            ))
            for t in order
        )
        if rounds:
            for t in order:
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + rounds * self.quantum
                                    * self.weight(t))
        for t in order:
            if self._deficit.get(t, 0.0) >= float(costs[t]):
                return t
        return order[0]  # pragma: no cover - rounds guarantee coverage

    def charge(self, tenant, cost: float) -> None:
        """Spend ``tenant``'s credit for a SUCCESSFUL admission and
        advance the round-robin pointer."""
        self._deficit[tenant] = self._deficit.get(tenant, 0.0) - float(
            cost)
        self._last = tenant


class _AdmissionQueue:
    """Arrival-ordered admission backlog over PER-TENANT deques
    (ISSUE 15 satellite — the ROADMAP's named PR 14 follow-up).

    Fair-share admission used to rebuild the per-tenant heads by
    scanning the ONE FIFO on every admission: O(backlog) per admit, a
    quadratic drain at thousands of queued requests. Here each tenant
    keeps its own arrival-ordered deque of ``(seq, request)`` entries
    (``seq`` is a global submission counter, so total arrival order is
    preserved exactly), which makes the admission path O(1) amortized
    in the backlog:

    - :meth:`tenant_heads` is O(backlogged tenants), not O(backlog);
    - :meth:`remove` of an admission candidate — always a tenant
      head — is an O(1) popleft (identity-checked: the by-identity
      semantics of the scan ``_dequeue`` are kept, and a non-head
      removal falls back to a scan of that ONE tenant's deque);
    - the global FCFS head is the min over tenant heads by ``seq``.

    Iteration yields requests in arrival order (the ``evacuate`` /
    duplicate-check surface) and ``q[0]`` is the arrival head, so the
    drop-in surface matches the old ``deque``. Admission order is
    pinned unchanged vs the scan implementation by regression test on
    a 1k-request backlog (tests/test_serving.py)."""

    def __init__(self) -> None:
        self._seq = itertools.count()
        self._tenants: dict = {}  # tenant_id -> deque[(seq, Request)]
        self._n = 0

    def append(self, request) -> None:
        self._tenants.setdefault(
            request.tenant_id, deque()
        ).append((next(self._seq), request))
        self._n += 1

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self):
        entries = sorted(
            (e for dq in self._tenants.values() for e in dq),
            key=lambda e: e[0],
        )
        return (r for _, r in entries)

    def iter_unordered(self):
        """Requests in per-tenant (not global arrival) order — the
        membership/duplicate-check surface. ``__iter__``'s global sort
        is only needed where arrival order matters (``evacuate``);
        submit-time checks use this O(backlog) early-exit walk (review
        finding: paying the sort twice per submit made submit
        O(B log B), worse than the old single-deque scan)."""
        for dq in self._tenants.values():
            for _, r in dq:
                yield r

    def __getitem__(self, i: int):
        if i != 0:
            raise IndexError(
                "_AdmissionQueue indexes only its head ([0])")
        head = self.head()
        if head is None:
            raise IndexError("empty admission queue")
        return head

    def head(self):
        """The global arrival head: min over tenant heads by seq —
        O(backlogged tenants), independent of backlog depth."""
        best = None
        for dq in self._tenants.values():
            if dq and (best is None or dq[0][0] < best[0]):
                best = dq[0]
        return best[1] if best else None

    def tenant_heads(self) -> dict:
        """``{tenant_id: earliest queued request}`` for every
        backlogged tenant — what the DRR picker ranks; O(backlogged
        tenants) where the scan implementation walked the backlog."""
        return {t: dq[0][1] for t, dq in self._tenants.items() if dq}

    def remove(self, request) -> None:
        """Remove ``request`` by IDENTITY. The admission path always
        removes a tenant head (O(1)); anything else (defensive) scans
        only that tenant's own deque."""
        dq = self._tenants.get(request.tenant_id)
        found = False
        if dq:
            if dq[0][1] is request:
                dq.popleft()
                found = True
            else:
                for i, (_, r) in enumerate(dq):
                    if r is request:
                        del dq[i]
                        found = True
                        break
        if not found:
            raise ValueError(
                f"request {request.request_id!r} is not queued")
        self._n -= 1
        if not dq:
            # drop the empty deque so tenant_heads stays O(backlogged
            # tenants), not O(ever-seen tenants)
            del self._tenants[request.tenant_id]

    def clear(self) -> None:
        self._tenants.clear()
        self._n = 0


def keep_arrival(request) -> None:
    """Stamp ``request._arrival`` ONLY when unset — the ONE rule every
    (re)submission path shares (ISSUE 11 satellite): the scheduler's
    :meth:`Scheduler.submit`, the cluster router's front door, and the
    preemption requeue all route through it, so queue_wait and TTFT
    always measure the WHOLE journey from first arrival — a requeue or
    evacuation can never silently reset the clock."""
    if not request._arrival:
        request._arrival = time.perf_counter()


def check_session_tenant(pins: Mapping, request) -> None:
    """VALIDATE half of the sticky-session/tenant rule (ISSUE 14
    satellite), the ONE implementation both front doors —
    :meth:`Scheduler.submit` and the cluster Router's ``submit`` —
    share: a session re-submitted under a different tenant raises
    loudly (one tenant's conversation history must never continue
    under another's identity). Commit the pin separately via
    :func:`pin_session_tenant` AFTER every other validation passed —
    pinning first left a REFUSED submission's session permanently
    bound to the wrong tenant (review finding)."""
    sid = request.session_id
    if sid is not None and sid in pins and pins[sid] != request.tenant_id:
        raise ValueError(
            f"session {sid!r} belongs to tenant {pins[sid]!r} but was "
            f"re-submitted as {request.tenant_id!r} — sessions never "
            "change tenants"
        )


def pin_session_tenant(pins: dict, request) -> None:
    """COMMIT half of the sticky-session/tenant rule: record a NEW
    session's tenant (no-op on later turns). Call only once the
    submission is certain to be accepted."""
    if (request.session_id is not None
            and request.session_id not in pins):
        pins[request.session_id] = request.tenant_id


@dataclass
class Request:
    """One serving request: ``prompt`` tokens in, up to
    ``max_new_tokens`` generated tokens out (generation also stops at
    ``eos_id`` when given — the emitted EOS counts as generated, like
    :func:`generate`'s fixed-horizon streams truncated at EOS).

    ``tenant_id`` (optional, ISSUE 14) names the serving tenant: the
    engine gathers that tenant's adapter rows for the slot, the prefix
    cache is consulted under the tenant's namespace, fair-share
    admission buckets by it, and every event/rollup carries it.
    ``None`` = the base model (the ``'default'`` tenant in rollups).

    ``session_id`` (optional) marks a multi-turn conversation: the
    cluster router (ISSUE 8) pins every request of a session to the
    replica that served its first turn, so the per-replica prefix trie
    stays warm across turns. The single-engine scheduler ignores the
    pinning but, like the router, REFUSES a session re-submitted under
    a different ``tenant_id`` (ISSUE 14 satellite: a silent re-pin
    would hand one tenant's conversation history to another).

    ``ttft_target_ms`` / ``tpot_target_ms`` (optional, ISSUE 11) are
    the request's SLO targets — submit-to-first-token and mean
    inter-token latency. The ``slo`` policy schedules against them
    (chunk-interference cap, preemption of over-budget streams), every
    policy reports against them (``slo_ttft_ok``/``slo_tpot_ok`` on
    the finish event → the ``slo_attainment`` rollup).

    ``seed`` (optional) is the request's sampling-stream seed: under a
    sampled engine (``temperature > 0``) token ``i`` of this request
    draws with ``fold_in(fold_in(base_key, seed), i)`` (counter-based
    keys, docs/serving.md "Sampling"). ``None`` → :meth:`Scheduler.
    submit` derives ``crc32(request_id) & 0x7FFFFFFF`` and STORES it,
    so preemption/requeue and cross-replica re-routes reuse the same
    stream. Ignored by greedy engines.
    """

    prompt: Sequence[int]
    max_new_tokens: int
    request_id: Optional[str] = None
    eos_id: Optional[int] = None
    tenant_id: Optional[str] = None
    session_id: Optional[str] = None
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    seed: Optional[int] = None
    _arrival: float = field(default=0.0, repr=False)
    #: preemption resume state (stream so far / generated count / first
    #: -token stamp) — parked ON the request so a requeue OR a cross-
    #: replica re-route resumes identically; cleared at re-admission.
    _resume: Optional[dict] = field(default=None, repr=False)
    #: set by preemption: the request was admitted once already, so a
    #: re-admission must not emit a second whole-journey queue_wait
    #: sample (a mid-fill preemption has no _resume to signal it).
    _requeued: bool = field(default=False, repr=False)
    #: causal journey context (ISSUE 17) — set once at the first front
    #: door (``journey.ensure``, the keep_arrival sibling rule) and
    #: carried across requeues/migrations; a cross-process handoff
    #: restores it from the payload (``journey.adopt_payload``).
    _journey: Optional[_journey.JourneyContext] = field(
        default=None, repr=False)

    def __post_init__(self) -> None:
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        for name in ("ttft_target_ms", "tpot_target_ms"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")


@dataclass
class _InFlight:
    request: Request
    slot: int
    stream: list  # prompt + generated tokens
    generated: int
    #: perf_counter stamp of the request's FIRST token (original
    #: admission — survives preemption/resume) — the TPOT clock.
    first_token_t: float = 0.0


@dataclass
class _Filling:
    """A chunked admission still writing prompt KV (ISSUE 11): holds
    the request between ``chunked_join`` and the mixed tick whose final
    chunk samples its first token."""

    request: Request
    slot: int
    t_admit: float
    resume: Optional[dict] = None


class Scheduler:
    """Admission + completion loop; see module docstring.

    ``tenant_weights`` (ISSUE 14): a ``{tenant_id: weight}`` mapping
    turns on deficit-round-robin FAIR-SHARE admission — the queue is
    still arrival-ordered WITHIN a tenant, but which tenant's head is
    tried next follows the weighted shares (:class:`DeficitRoundRobin`;
    unlisted tenants weigh 1.0, ``None`` = base traffic). Composes
    with every policy: ``prefill_priority``/``slo`` keep draining every
    admissible request per round, only the ORDER changes, and the slo
    policy's chunk-cap/preemption discipline is untouched. Quotas can
    churn mid-run via :meth:`set_tenant_weight`."""

    def __init__(self, engine, policy: str = "fcfs",
                 tenant_weights: Optional[Mapping[str, float]] = None
                 ) -> None:
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got "
                             f"{policy!r}")
        self.engine = engine
        self.policy = policy
        #: fair-share state (active once any weight is configured).
        self._drr = DeficitRoundRobin()
        self._fair_share = False
        if tenant_weights:
            for t, w in tenant_weights.items():
                self.set_tenant_weight(t, w)
        #: session -> tenant pinning (the sticky-consistency guard).
        self._session_tenants: dict = {}
        # Live-telemetry front door (ISSUE 6): a serving process driven
        # only by the scheduler has no trainer loop to honour the
        # metrics-port env gate — check it here too (no-op when unset).
        try:
            from chainermn_tpu.observability import exporter as _exporter

            _exporter.maybe_start_from_env()
        except Exception:
            pass
        #: per-tenant admission deques (ISSUE 15 satellite): drop-in
        #: arrival-ordered surface, O(1)-amortized fair-share admission.
        self._queue = _AdmissionQueue()
        self._inflight: dict[int, _InFlight] = {}
        #: chunked admissions mid-fill, keyed by slot (ISSUE 11).
        self._filling: dict[int, _Filling] = {}
        #: lifetime preemption count (the ``preempt`` events carry the
        #: per-request detail; this is the cheap gauge read).
        self.preemptions = 0
        self._ids = itertools.count()
        #: request_id -> {'tokens': prompt+generated, 'generated': [...]}
        self.results: dict = {}
        #: local copy of every emitted serving event — summary() feeds
        #: them to trace.summarize_serving (the ONE rollup owner) so the
        #: accounting works with the recorder off and cannot drift from
        #: what tools/trace_report.py computes. Reset per run() and
        #: capped like the Recorder's buffer (a week-long stream must
        #: not eat the host; ``events_dropped`` counts the overflow).
        self._events: list[dict] = []
        self.events_dropped = 0
        self._wall: Optional[float] = None

    # ------------------------------------------------------------------

    def set_tenant_weight(self, tenant_id: Optional[str],
                          weight: float) -> None:
        """Set (or change, mid-run) a tenant's fair-share weight and
        activate fair-share admission (ISSUE 14)."""
        self._drr.set_weight(tenant_id, weight)
        self._fair_share = True

    @property
    def fair_share(self) -> bool:
        """Whether deficit-round-robin admission is active."""
        return self._fair_share

    def _event(self, _kind: str = "serving", **fields) -> None:
        from chainermn_tpu.observability import trace

        if len(self._events) < trace.MAX_BUFFERED_EVENTS:
            self._events.append({"kind": _kind, **fields})
        else:
            self.events_dropped += 1
        rec = trace.active()
        if rec is not None:
            rec.event(_kind, **fields)

    def _publish_gauges(self) -> None:
        """Direct queue/occupancy gauges (ISSUE 6): the admission
        queue and the slot array are STATE, not events — the recorder
        tap cannot see them, so every queue/in-flight mutation refreshes
        the gauges here. One global read when the metrics plane is off
        (the trace.active() discipline)."""
        from chainermn_tpu.observability import metrics

        reg = metrics.active_registry()
        if reg is None:
            return
        reg.gauge("serving_queue_depth",
                  "requests waiting for admission").set(len(self._queue))
        reg.gauge("serving_inflight",
                  "requests occupying a decode slot").set(
            len(self._inflight))
        eng = self.engine
        reg.gauge("serving_slots", "decode slots in the compiled "
                  "step").set(getattr(eng, "num_slots", 0))
        reg.gauge("serving_active_slots", "decode slots currently "
                  "occupied").set(getattr(eng, "n_active", 0))
        stats = getattr(eng, "prefix_stats", None)
        if stats and stats.get("lookups"):
            reg.gauge(
                "kv_prefix_hit_rate",
                "fraction of admitted prompt tokens served from the "
                "prefix cache (lifetime)",
            ).set(stats["hit_tokens"] / max(1, stats["prompt_tokens"]))
        trie_blocks = getattr(eng, "prefix_trie_blocks", None)
        if callable(trie_blocks):
            n = trie_blocks()
            if n is not None:
                reg.gauge(
                    "kv_prefix_trie_blocks",
                    "KV blocks held by the prefix trie",
                ).set(n)

    def submit(self, request: Request) -> str:
        """Enqueue; returns the request id (assigned when absent).

        Rejects a request that could never finish inside the engine's
        horizon UP FRONT — ``prompt + max_new_tokens`` must fit in
        ``max_len``. (Catching it here costs one comparison; catching it
        mid-stream would abort every other in-flight request.)"""
        total = len(request.prompt) + request.max_new_tokens
        if total > self.engine.max_len:
            raise ValueError(
                f"request needs {total} positions (prompt "
                f"{len(request.prompt)} + max_new_tokens "
                f"{request.max_new_tokens}) but the engine horizon is "
                f"max_len={self.engine.max_len}"
            )
        # Tenant validation up front (ISSUE 14): an unregistered
        # adapter or a merged-engine mismatch fails HERE, not mid-run
        # in the admission loop where it would abort every other
        # in-flight stream.
        resident = getattr(self.engine, "adapter_resident", None)
        if callable(resident) and not resident(request.tenant_id):
            # Covers tenant_id=None too (review finding): a merged
            # engine serves exactly its folded tenant, so a BASE-model
            # request must also be refused here, not mid-run.
            who = (f"tenant {request.tenant_id!r}"
                   if request.tenant_id is not None
                   else "a base-model (tenantless) request")
            raise ValueError(
                f"{who} cannot be served by this engine (adapter not "
                "resident / merged-tenant mismatch) — register the "
                "adapter or route elsewhere"
            )
        # Sticky-session consistency guard (ISSUE 14 satellite): the
        # shared validate half; the pin commits below, after EVERY
        # other check passed.
        check_session_tenant(self._session_tenants, request)
        # Requests are mutable (the id is written onto them): the same
        # OBJECT queued twice would alias one stream across two entries,
        # and a stale id from a previous scheduler can collide with this
        # scheduler's own sequence — both are caller bugs surfaced here,
        # not silently-merged results.
        if any(r is request for r in self._queue.iter_unordered()) or any(
            fl.request is request for fl in self._inflight.values()
        ) or any(f.request is request for f in self._filling.values()):
            raise ValueError("request object is already queued/in flight")
        if request.request_id is None:
            request.request_id = f"r{next(self._ids)}"
        rid = request.request_id
        # Sampling-seed derivation (documented on Request.seed): fill an
        # omitted seed deterministically from the id — crc32, masked
        # into int32 — and STORE it, so a preemption requeue or a
        # cross-replica re-submit (same id, same Request) lands on the
        # same counter-key stream. Callers wanting i.i.d. streams per
        # retry pass their own seeds.
        if request.seed is None:
            request.seed = zlib.crc32(str(rid).encode()) & 0x7FFFFFFF
        if rid in self.results or any(
            r.request_id == rid for r in self._queue.iter_unordered()
        ) or any(fl.request.request_id == rid
                 for fl in self._inflight.values()) or any(
            f.request.request_id == rid for f in self._filling.values()
        ):
            raise ValueError(
                f"duplicate request_id {rid!r} (reusing a Request from "
                f"another scheduler? pass a fresh request_id)"
            )
        # Keep an existing arrival stamp (the cluster router stamps at
        # ITS front door before placing — and re-places a dead
        # replica's requests; preemption requeues the same way):
        # queue-wait and TTFT then cover the whole journey, not just
        # the last hop. keep_arrival is the ONE rule all three paths
        # share (ISSUE 11 satellite).
        keep_arrival(request)
        _journey.ensure(request)
        pin_session_tenant(self._session_tenants, request)
        self._queue.append(request)
        self._publish_gauges()
        return request.request_id

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def in_flight(self) -> int:
        return len(self._inflight)

    @property
    def filling(self) -> int:
        """Chunked admissions still writing prompt KV (ISSUE 11)."""
        return len(self._filling)

    def slot_of(self, request_id: str) -> Optional[int]:
        """The slot ``request_id`` currently occupies — in flight or
        mid-fill — or None. The lookup the cluster router's preemption
        path uses, so it never reaches into this scheduler's private
        bookkeeping."""
        for slot, fl in self._inflight.items():
            if fl.request.request_id == request_id:
                return slot
        for slot, f in self._filling.items():
            if f.request.request_id == request_id:
                return slot
        return None

    # ------------------------------------------------------------------

    @staticmethod
    def _tenant_field(req: Request) -> dict:
        """The per-event tenant tag (ISSUE 14): present only for
        tenant-bearing requests, so pre-tenant traces — and fake-engine
        tests — keep their exact shape (the rollup's ``'default'``
        fallback covers the absent case)."""
        return ({"tenant": req.tenant_id}
                if req.tenant_id is not None else {})

    def _finish(self, fl: _InFlight) -> None:
        self.engine.leave(fl.slot)
        del self._inflight[fl.slot]
        req = fl.request
        now = time.perf_counter()
        dur = now - req._arrival
        self.results[req.request_id] = {
            "tokens": list(fl.stream),
            "generated": list(fl.stream[len(req.prompt):]),
        }
        ev: dict = dict(phase="finish", request=req.request_id,
                        generated=fl.generated, dur_s=round(dur, 9),
                        **self._tenant_field(req),
                        **_journey.fields(req))
        # TPOT (ISSUE 11 satellite): mean inter-token latency of THIS
        # request, first token -> finish over generated-1 intervals.
        # Preemption gaps are inside it by construction — the whole-
        # journey rule again.
        tpot_ms = None
        if fl.generated > 1 and fl.first_token_t:
            tpot_ms = ((now - fl.first_token_t)
                       / (fl.generated - 1) * 1e3)
            ev["tpot_ms"] = round(tpot_ms, 6)
        # SLO verdicts ride the finish event so the rollup (and the
        # metrics tap's violation counters) need no target plumbing.
        if req.ttft_target_ms is not None and fl.first_token_t:
            ttft_ms = (fl.first_token_t - req._arrival) * 1e3
            ev["slo_ttft_ok"] = bool(ttft_ms <= req.ttft_target_ms)
        if req.tpot_target_ms is not None and tpot_ms is not None:
            ev["slo_tpot_ok"] = bool(tpot_ms <= req.tpot_target_ms)
        self._event(**ev)
        self._publish_gauges()

    def _begin_stream(self, req: Request, slot: int, tok: int, *,
                      bucket, t_admit: float, resume: Optional[dict],
                      chunks: Optional[int] = None) -> None:
        """Register the in-flight entry for a freshly sampled first
        token — the ONE tail both admission flavours (monolithic
        ``prefill_join``, chunked fill completion) and both journeys
        (fresh, preemption resume) share. Fresh admissions emit the
        ``prefill`` event with its ``ttft_s`` sample; resumes emit it
        with ``resumed=True`` and NO ttft (the first token was already
        delivered before the preemption — re-sampling it must not
        re-enter the TTFT percentile). ONE ``now`` stamp feeds both
        ``dur_s`` (admission -> here) and ``ttft_s``: call sites used
        to stamp their own end time, leaving a per-admission clock gap
        between ``queue_wait + prefill`` and ``ttft_s`` — the journey
        decomposition check (ISSUE 17) holds that identity to
        microseconds."""
        now = time.perf_counter()
        ev: dict = dict(phase="prefill", request=req.request_id,
                        slot=slot, bucket=bucket,
                        prompt_len=len(req.prompt),
                        dur_s=round(now - t_admit, 9),
                        **self._tenant_field(req),
                        **_journey.fields(req))
        if chunks is not None:
            ev["chunks"] = chunks
        if getattr(self.engine, "last_prefill_seq_parallel", False):
            # ISSUE 13: this admission's forward ran sharded over the
            # 'model' partition — the TTFT percentiles can be split by
            # this field when pricing the wide-prefill adoption.
            ev["seq_parallel"] = True
        if resume is None:
            ev["ttft_s"] = round(now - req._arrival, 9)
            fl = _InFlight(req, slot, list(req.prompt) + [int(tok)], 1,
                           first_token_t=now)
        else:
            ev["resumed"] = True
            fl = _InFlight(req, slot, list(resume["stream"]) + [int(tok)],
                           int(resume["generated"]) + 1,
                           first_token_t=resume["first_token_t"] or now)
            req._resume = None
        self._event(**ev)
        self._inflight[slot] = fl
        self._publish_gauges()
        if fl.generated >= req.max_new_tokens or (
            req.eos_id is not None and int(tok) == req.eos_id
        ):
            self._finish(fl)

    def _next_candidate(self) -> Optional[Request]:
        """The queued request admission tries next: the strict arrival
        head (FCFS — a blocked head blocks the queue), or, with fair
        share active (ISSUE 14), the earliest request of the tenant
        the deficit-round-robin picker names (arrival order WITHIN a
        tenant is always preserved). The heads come straight off the
        per-tenant deques (ISSUE 15 satellite) — O(backlogged tenants)
        per admission where the scan implementation walked the whole
        backlog (O(backlog) per admit, quadratic drain)."""
        if not self._queue:
            return None
        if not self._fair_share:
            return self._queue.head()
        heads = self._queue.tenant_heads()
        tenant = self._drr.select(
            {t: self._drr_cost(r) for t, r in heads.items()})
        return heads[tenant]

    @staticmethod
    def _drr_cost(req: Request) -> float:
        """Fair-share cost of admitting ``req``: its decode budget —
        except a preempted/requeued stream, whose first admission
        already charged the full budget (review finding: re-charging
        on resume billed a preempted tenant twice for the same tokens,
        dragging its admitted share below its weight)."""
        if req._resume is not None or req._requeued:
            return 0.0
        return float(req.max_new_tokens)

    def _dequeue(self, req: Request) -> None:
        """Remove ``req`` from the queue by IDENTITY (deque.remove
        would deep-compare whole Request dataclasses — prompt lists
        included — and quietly relies on request_id uniqueness to make
        equality mean identity; review finding). An admission
        candidate is always its tenant's deque head, so this is O(1)
        (ISSUE 15 satellite)."""
        self._queue.remove(req)

    def _admit_one(self) -> bool:
        """Try to admit the next candidate (:meth:`_next_candidate` —
        the arrival head, or the fair-share pick). Chunked
        engines admit through ``chunked_join`` (slot + block
        reservation only; the prompt KV is written by later mixed
        ticks); a parked ``_resume`` state makes the join re-prefill
        the preempted stream instead of the original prompt."""
        req = self._next_candidate()
        if req is None:
            return False
        t0 = time.perf_counter()
        resume = req._resume
        first_admission = resume is None and not req._requeued
        join_prompt = resume["stream"] if resume is not None else req.prompt
        # The engine-side tenant plumbing (adapter row + trie
        # namespace); omitted for tenantless requests so schedulers
        # over minimal/fake engines keep their pre-tenant signature.
        join_kw = ({"tenant_id": req.tenant_id}
                   if req.tenant_id is not None else {})
        # The request's counter-key stream seed — only for SAMPLED
        # engines (greedy ones ignore seeds, and fake/minimal engines
        # in tests keep their pre-seed join signature). A resume join
        # passes the SAME stored seed: the re-prefill's first sample
        # uses counter = stream-so-far length, exactly the counter the
        # uninterrupted stream would have used there, so the resumed
        # stream is bit-identical.
        if getattr(self.engine, "temperature", 0.0) > 0.0:
            join_kw["seed"] = req.seed
        if getattr(self.engine, "prefill_chunk", 0) > 0:
            slot = self.engine.chunked_join(join_prompt, **join_kw)
            if slot is None:
                return False
            self._dequeue(req)
            if self._fair_share:
                self._drr.charge(req.tenant_id, self._drr_cost(req))
            if first_admission:
                self._event(phase="queue_wait", request=req.request_id,
                            dur_s=round(t0 - req._arrival, 9),
                            **self._tenant_field(req),
                            **_journey.fields(req))
            info = getattr(self.engine, "last_prefix_info", None)
            if info is not None:
                self._event("prefix_cache", request=req.request_id,
                            slot=slot, **info,
                            **self._tenant_field(req),
                            **_journey.fields(req))
            self._filling[slot] = _Filling(req, slot, t_admit=t0,
                                           resume=resume)
            self._publish_gauges()
            return True
        res = self.engine.prefill_join(join_prompt, **join_kw)
        if res is None:
            return False
        self._dequeue(req)
        if self._fair_share:
            self._drr.charge(req.tenant_id, self._drr_cost(req))
        slot, tok, bucket = res
        if first_admission:
            self._event(phase="queue_wait", request=req.request_id,
                        dur_s=round(t0 - req._arrival, 9),
                        **self._tenant_field(req),
                        **_journey.fields(req))
        # Prefix-sharing accounting (ISSUE 7): the engine fills
        # last_prefix_info on every cache-on paged join — hit/miss,
        # adopted vs prefilled token counts, COW copies. Emitted here
        # (not in the engine) so it rides the scheduler's event window:
        # summary(), bench rows and trace_report all see it.
        info = getattr(self.engine, "last_prefix_info", None)
        if info is not None:
            self._event("prefix_cache", request=req.request_id,
                        slot=slot, **info, **self._tenant_field(req),
                        **_journey.fields(req))
        # ttft_s: submit -> first token. The prefill samples the
        # request's first token, so TTFT = queue wait + prefill — kept
        # as its own field (not derived downstream) because the two
        # phase events may be split across truncated traces.
        self._begin_stream(req, slot, tok, bucket=bucket,
                           t_admit=t0, resume=resume)
        return True

    def step(self) -> None:
        """One decode round. Plain engines advance every in-flight
        request by one token; speculative engines
        (``engine.spec_tokens > 0``) advance each by its accepted span
        (1..K+1 tokens — same stream, fewer rounds). Tokens past a
        request's ``max_new_tokens`` or EOS are truncated here (the
        engine may legitimately overshoot: its committed span is a
        property of acceptance, not of any one request's remaining
        budget)."""
        # Chunked engines ride the mixed program only while a fill is
        # actually in progress: a steady-state tick with no fill rows
        # would pay the T-wide grid for nothing — the plain decode /
        # verify step costs exactly what a monolithic engine's does.
        # (Both programs stay compiled-once; the pin is per-program.)
        if getattr(self.engine, "prefill_chunk", 0) > 0 and self._filling:
            self._mixed_tick()
            return
        if getattr(self.engine, "spec_tokens", 0) > 0:
            self._spec_step()
            return
        toks, dur = self.engine.decode_step()
        n_active = len(self._inflight)
        self._event(phase="decode_step", n_active=n_active,
                    n_slots=self.engine.num_slots, tokens=n_active,
                    dur_s=round(dur, 9))
        for slot, fl in list(self._inflight.items()):
            tok = int(toks[slot])
            fl.stream.append(tok)
            fl.generated += 1
            req = fl.request
            if fl.generated >= req.max_new_tokens or (
                req.eos_id is not None and tok == req.eos_id
            ):
                self._finish(fl)

    def _spec_step(self) -> None:
        """One draft→verify→accept tick (see ``ServingEngine
        .verify_step``); emits the same ``decode_step`` event (with the
        REAL multi-token count) plus one ``speculate`` event."""
        committed, dur, stats = self.engine.verify_step()
        n_active = len(self._inflight)
        # Per-request take (truncated at the request's remaining budget
        # / EOS) computed ONCE — the decode_step event's token count and
        # the committed streams come from the same pass, so they cannot
        # diverge. `done` records whether the LAST taken token finished
        # the request (the same predicate that cut the take).
        takes = self._takes(committed)
        self._event(phase="decode_step", n_active=n_active,
                    n_slots=self.engine.num_slots,
                    tokens=sum(len(t) for t, _ in takes.values()),
                    dur_s=round(dur, 9))
        self._event("speculate", drafted=stats["drafted"],
                    accepted=stats["accepted"],
                    accept_lens=list(stats["accept_lens"]),
                    mode=stats.get("mode", "greedy"),
                    dur_s=round(dur, 9))
        for slot, fl in list(self._inflight.items()):
            take, done = takes[slot]
            fl.stream.extend(take)
            fl.generated += len(take)
            if done:
                self._finish(fl)

    def _takes(self, committed: dict) -> dict:
        """Per-request take from an engine-committed span, truncated at
        the request's remaining budget / EOS (the one pass both the
        speculative and mixed ticks share — token counts and streams
        come from the same loop, so they cannot diverge)."""
        takes: dict[int, tuple[list[int], bool]] = {}
        for slot, fl in self._inflight.items():
            req = fl.request
            take: list[int] = []
            done = False
            for tok in committed.get(slot, ()):
                take.append(int(tok))
                done = (fl.generated + len(take) >= req.max_new_tokens
                        or (req.eos_id is not None
                            and int(tok) == req.eos_id))
                if done:
                    break
            takes[slot] = (take, done)
        return takes

    def _mixed_tick(self) -> None:
        """One chunk+decode tick (ISSUE 11): drive the engine's mixed
        step — SLO policy caps the chunk rows while TPOT debt is
        outstanding — then commit decode takes, emit one
        ``prefill_chunk`` event per advanced fill row, and promote
        completed fills to in-flight streams (their ``prefill`` event
        carries the TTFT sample, exactly like a monolithic
        admission)."""
        cap = self._chunk_row_cap() if self.policy == "slo" else None
        committed, fills, dur, stats = self.engine.mixed_step(
            max_fill_rows=cap)
        n_active = len(self._inflight)
        takes = self._takes(committed)
        self._event(phase="decode_step", n_active=n_active,
                    n_slots=self.engine.num_slots,
                    tokens=sum(len(t) for t, _ in takes.values()),
                    dur_s=round(dur, 9))
        if stats is not None:
            self._event("speculate", drafted=stats["drafted"],
                        accepted=stats["accepted"],
                        accept_lens=list(stats["accept_lens"]),
                        mode=stats.get("mode", "greedy"),
                        dur_s=round(dur, 9))
        for f in fills:
            fill = self._filling.get(f["slot"])
            self._event("prefill_chunk",
                        request=(fill.request.request_id
                                 if fill is not None else None),
                        slot=f["slot"], chunk=f["chunk"],
                        tokens=f["tokens"], dur_s=round(dur, 9),
                        **(_journey.fields(fill.request)
                           if fill is not None else {}))
        from chainermn_tpu.observability import metrics

        reg = metrics.active_registry()
        if reg is not None:
            reg.gauge("serving_chunk_rows",
                      "fill rows advanced by the last mixed tick").set(
                len(fills))
        for f in fills:
            if not f["done"]:
                continue
            fill = self._filling.pop(f["slot"])
            self._begin_stream(fill.request, f["slot"], f["first_tok"],
                               bucket=None, t_admit=fill.t_admit,
                               resume=fill.resume, chunks=f["chunk"] + 1)
        # Commit over the TICK-START in-flight set (takes' keys): a
        # fill promoted above joined after the forward ran and has no
        # decode take this tick.
        for slot, (take, done) in takes.items():
            fl = self._inflight[slot]
            fl.stream.extend(take)
            fl.generated += len(take)
            if done:
                self._finish(fl)

    # ------------------------------------------------------------------
    # SLO policy (ISSUE 11)

    def _tpot_ratio(self, fl: _InFlight, now: float) -> Optional[float]:
        """Measured-over-target TPOT for one in-flight request; None
        when it has no target or too few tokens to measure."""
        t = fl.request.tpot_target_ms
        if t is None or fl.generated < 2 or not fl.first_token_t:
            return None
        tpot_ms = (now - fl.first_token_t) / (fl.generated - 1) * 1e3
        return tpot_ms / t

    def _chunk_row_cap(self) -> Optional[int]:
        """Decode-interference bound: while ANY in-flight request is
        over its TPOT budget, only one fill row advances per tick —
        prefill keeps progressing (never starves) but chunk
        interference shrinks until the debt clears. None = no cap."""
        now = time.perf_counter()
        for fl in self._inflight.values():
            r = self._tpot_ratio(fl, now)
            if r is not None and r > 1.0:
                return 1
        return None

    def _maybe_preempt(self) -> bool:
        """SLO preemption rule: the queue head could not be admitted
        and has burned half its TTFT budget waiting — preempt the ONE
        in-flight request DEEPEST over its own TPOT budget (its SLO is
        already lost; the head's is still winnable). Requests without
        targets are never preempted; at most one preemption per round
        bounds the thrash; no over-budget victim = no preemption (a
        healthy set is never sacrificed). The gate reads the request
        admission actually TRIED — under fair share that is the DRR
        pick, not necessarily the arrival head (review finding: gating
        on the head let a targetless head mask the blocked candidate's
        at-risk TTFT; re-calling the picker is idempotent — no charge
        happened, so the same tenant is named again)."""
        blocked = self._next_candidate()
        if blocked is None:
            return False
        tt = blocked.ttft_target_ms
        if tt is None:
            return False
        if (time.perf_counter() - blocked._arrival) * 1e3 < 0.5 * tt:
            return False
        now = time.perf_counter()
        worst, worst_ratio = None, 1.0
        for slot, fl in self._inflight.items():
            r = self._tpot_ratio(fl, now)
            if r is not None and r > worst_ratio:
                worst, worst_ratio = slot, r
        if worst is None:
            return False
        self.preempt(worst)
        return True

    def preempt(self, slot: int, requeue: bool = True) -> Request:
        """Preempt the request on ``slot`` (in flight or mid-fill,
        ISSUE 11): the engine releases the slot — publishing its
        written blocks into the prefix trie first, so the resume
        re-adopts its OWN KV — the partial stream parks on the Request
        as resume state, and the request re-enters the BACK of the
        queue with its ORIGINAL arrival stamp (whole-journey TTFT; the
        back, not arrival order, or the freed slot would re-admit the
        victim forever). ``requeue=False`` returns the Request
        un-queued instead — the cluster router's re-route path: resume
        state travels ON the request, so a second replica resumes the
        stream identically (bit-identical: greedy determinism at
        temperature 0; at temperature > 0 the stored ``seed`` rides the
        Request and the counter keys re-derive at absolute positions)."""
        fl = self._inflight.pop(slot, None)
        if fl is not None:
            req = fl.request
            req._resume = {"stream": list(fl.stream),
                           "generated": fl.generated,
                           "first_token_t": fl.first_token_t}
            generated = fl.generated
        else:
            fill = self._filling.pop(slot, None)
            if fill is None:
                raise ValueError(
                    f"slot {slot} holds no preemptible request")
            # Mid-fill: no new tokens were sampled — any EARLIER resume
            # state on the request stays authoritative.
            req = fill.request
            if fill.resume is not None:
                req._resume = fill.resume
            generated = (int(fill.resume["generated"])
                         if fill.resume is not None else 0)
        req._requeued = True
        self.engine.preempt(slot)
        self.preemptions += 1
        self._event(phase="preempt", request=req.request_id,
                    generated=generated,
                    dur_s=round(time.perf_counter() - req._arrival, 9),
                    **self._tenant_field(req),
                    **_journey.fields(req))
        if requeue:
            keep_arrival(req)  # the unified stamp rule: no-op, by design
            self._queue.append(req)
        self._publish_gauges()
        return req

    def start_window(self) -> None:
        """Begin a fresh accounting window: :meth:`summary` covers the
        events from here to :meth:`close_window`. :meth:`run` calls
        both; the cluster router (ISSUE 8) drives replicas through
        :meth:`tick` and manages the windows itself."""
        self._events = []
        self.events_dropped = 0
        self._window_t0 = time.perf_counter()

    def close_window(self) -> None:
        self._wall = time.perf_counter() - getattr(
            self, "_window_t0", time.perf_counter())

    @property
    def event_window(self) -> list:
        """The current window's locally-kept events (read-only use:
        the cluster router aggregates cross-replica TTFT from them)."""
        return self._events

    @property
    def drained(self) -> bool:
        return not (self._queue or self._inflight or self._filling)

    def _admit_round(self) -> bool:
        """One policy-shaped admission pass (the ONE implementation
        :meth:`run` and :meth:`tick` share): prefill_priority — and the
        slo policy, whose extra discipline lives in the tick, not the
        admission order — drains every admissible queued request, fcfs
        admits at most one. Under slo, a blocked head whose TTFT target
        is at risk may preempt an over-budget in-flight stream and
        retry (:meth:`_maybe_preempt`)."""
        if self.policy in ("prefill_priority", "slo"):
            progressed = False
            while self._admit_one():
                progressed = True
            if (self.policy == "slo" and not progressed and self._queue
                    and self._maybe_preempt()):
                while self._admit_one():
                    progressed = True
            return progressed
        return self._admit_one()

    def tick(self) -> bool:
        """One admission round + (when anything is in flight or
        mid-fill) one decode/mixed step — the body of :meth:`run`'s
        loop, exposed so the cluster router can interleave N replicas'
        progress in one host loop. Returns whether anything progressed
        (an admission or a step); a False on a non-drained scheduler
        means the queue head is blocked on slots/pool — the caller
        decides whether that is a deferral (other replicas will free
        capacity) or a dead end."""
        progressed = self._admit_round()
        if self._inflight or self._filling:
            self.step()
            progressed = True
        return progressed

    def admit_prefilled(self, request: Request, slot: int, first_tok: int,
                        *, dur_s: Optional[float] = None) -> None:
        """Register an in-flight entry for a slot the engine ALREADY
        holds — the disaggregated-serving adoption path (ISSUE 8): a
        prefill replica ran the bucketed prefill, its KV blocks were
        streamed over the host plane, and this scheduler's engine
        adopted them via ``import_kv``. Emits the same ``queue_wait`` /
        ``prefill`` events an ordinary admission would (``ttft_s`` from
        the request's original submit stamp, so the transfer cost is
        inside the TTFT — honest disaggregation accounting; ``bucket``
        is None: no prefill ran HERE), and finishes immediately when
        the first token already satisfies the request."""
        if not self.engine._active[slot]:
            raise ValueError(f"slot {slot} is not active on this engine")
        if slot in self._inflight:
            raise ValueError(f"slot {slot} already tracked in flight")
        if request.request_id is None:
            request.request_id = f"r{next(self._ids)}"
        # Continue the journey the prefill side carried this far (the
        # in-process router hands the SAME Request object over; a
        # multi-process worker restores it from the payload via
        # journey.adopt_payload before calling here) — ensure() inside
        # fields() mints a fresh chain only for journey-less callers.
        now = time.perf_counter()
        arrival = request._arrival or now
        self._event(phase="queue_wait", request=request.request_id,
                    dur_s=round(max(0.0, (now - arrival)
                                    - (dur_s or 0.0)), 9),
                    **self._tenant_field(request),
                    **_journey.fields(request))
        self._event(phase="prefill", request=request.request_id,
                    slot=slot, bucket=None,
                    prompt_len=len(request.prompt),
                    dur_s=round(dur_s or 0.0, 9),
                    ttft_s=round(now - arrival, 9),
                    **self._tenant_field(request),
                    **_journey.fields(request))
        fl = _InFlight(request, slot,
                       list(request.prompt) + [int(first_tok)], 1,
                       first_token_t=now)
        self._inflight[slot] = fl
        self._publish_gauges()
        if fl.generated >= request.max_new_tokens or (
            request.eos_id is not None
            and int(first_tok) == request.eos_id
        ):
            self._finish(fl)

    def evacuate(self) -> list[Request]:
        """Strip every queued AND in-flight request out of this
        scheduler WITHOUT touching the engine (which may be dead — the
        replica-loss path, ISSUE 8): returns the orphans in arrival
        order so the router can re-route them. In-flight requests lose
        their partial streams (streams are deterministic — greedy, or
        counter-key sampled under the request's stored ``seed`` — so a
        re-prefill elsewhere reproduces the identical stream); mid-fill
        chunked admissions (ISSUE 11) are orphaned the same way —
        their arrival stamps travel, the unified keep_arrival rule."""
        orphans = list(self._queue)
        self._queue.clear()
        live = sorted(
            [fl.request for fl in self._inflight.values()]
            + [f.request for f in self._filling.values()],
            key=lambda r: r._arrival,
        )
        self._inflight.clear()
        self._filling.clear()
        orphans.extend(live)
        self._publish_gauges()
        return orphans

    def run(self, max_steps: int = 100_000,
            max_seconds: Optional[float] = None) -> dict:
        """Drive admissions + decode until queue and slots drain;
        returns :attr:`results` (request_id -> token streams). The
        local accounting (:meth:`summary`) covers THIS run — each call
        starts a fresh event window.

        ``max_seconds`` bounds the run by WALL CLOCK (checked once per
        admission/decode round): on expiry the loop stops cleanly with
        whatever is unfinished still queued/in flight — the open-loop
        bench/dryrun bound (ISSUE 8 satellite), where ``max_steps``
        stays the runaway guard and still raises."""
        from chainermn_tpu.observability import flight as _flight

        self.start_window()
        t0 = self._window_t0
        steps = 0
        try:
            while self._queue or self._inflight or self._filling:
                # Hang-watchdog heartbeat: one per admission/decode
                # round — the serving analog of the trainer's per-step
                # beat.
                _flight.beat(steps)
                if max_seconds is not None and (
                    time.perf_counter() - t0 >= max_seconds
                ):
                    break
                progressed = self._admit_round()
                if not (self._inflight or self._filling):
                    if self._queue and not progressed:
                        # nothing running AND the tried candidate (the
                        # DRR pick under fair share, else the head)
                        # cannot be admitted: it can never fit
                        # (slot/pool shortage)
                        head = self._next_candidate() or self._queue[0]
                        raise RuntimeError(
                            f"request {head.request_id!r} cannot be "
                            f"admitted on an idle engine (prompt_len="
                            f"{len(head.prompt)}, free_slots="
                            f"{self.engine.free_slot_count})"
                        )
                    continue
                self.step()
                steps += 1
                if steps > max_steps:
                    raise RuntimeError(
                        f"exceeded max_steps={max_steps} with "
                        f"{len(self._inflight)} in flight")
        finally:
            # Drained OR raised (max_steps, admission failure — both
            # catchable): stand the heartbeat down. A replica idling
            # for the next burst, or a driver that caught the error,
            # must not read as a hang — and must not waste the
            # fire-once dump on a non-hang (review finding).
            _flight.quiesce()
        self.close_window()
        return self.results

    # ------------------------------------------------------------------

    def summary(self) -> dict:
        """Tokens/s + latency accounting for the last :meth:`run` — the
        locally-kept serving events rolled up by
        :func:`chainermn_tpu.observability.trace.summarize_serving`,
        the ONE owner of these definitions, so this summary, bench's
        ``serving`` rows, and ``tools/trace_report.py``'s serving
        section can never disagree. Adds ``wall_s`` (queue idle time
        included; the rollup's ``tokens_per_sec`` is device-busy)."""
        from chainermn_tpu.observability.trace import summarize_serving

        out = summarize_serving(self._events) or {}
        if self._wall is not None:
            out["wall_s"] = round(self._wall, 4)
        if self.events_dropped:
            out["events_dropped"] = self.events_dropped
        return out
