"""Synchronized (multi-node) batch normalization.

Reference: ``chainermn/links/batch_normalization.py`` (dagger) (SURVEY.md
sections 2.2, 2.5): ``MultiNodeBatchNormalization`` allreduces the batch
mean/variance across ranks inside forward so statistics reflect the *global*
batch; a ``communication_backend`` argument picked MPI vs NCCL.

TPU-native, implemented from the mechanism up (not a flax subclass): the
local shard contributes ``(sum, sum-of-squares, count)``; ONE fused ``psum``
over the data-parallel mesh axis (or axes tuple — hierarchical meshes)
produces the global-batch moments; normalization and the running-statistics
EMA follow. This is exactly the reference's allreduce-of-partial-moments
design with the backend choice gone — XLA lowers the psum to the right
ICI/DCN collective.

Invariant (tested in ``tests/test_links.py``): sync-BN over shards equals
plain BN over the concatenated global batch, bit-for-bit in f32.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators.base import CommunicatorBase


class MultiNodeBatchNormalization(nn.Module):
    """BatchNorm whose batch statistics are computed over the GLOBAL batch.

    Use inside a ``shard_map``-based train step::

        MultiNodeBatchNormalization(use_running_average=not train,
                                    axis_name='data')(x)

    or derive the axis from a communicator with :meth:`for_communicator`.
    ``axis_name=None`` degenerates to local (single-device) semantics.

    Statistics are accumulated in float32 regardless of ``dtype`` (the same
    master-precision discipline as the gradient allreduce path); running
    mean/var live in the ``batch_stats`` collection under the flax-standard
    ``mean``/``var`` names, so checkpoints and ``AllreducePersistent``
    treat them like any flax BN state.
    """

    use_running_average: bool
    axis_name: Optional[Any] = None
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Any = None
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Callable = nn.initializers.zeros_init()
    scale_init: Callable = nn.initializers.ones_init()

    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feat,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((feat,), jnp.float32)
        )

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            # Local partial moments; ONE psum carries all three terms
            # (reference: the allreduce of packed (sum, sumsq) buffers).
            total = jnp.float32(x.size // feat)
            s = xf.sum(axis=reduce_axes)
            ss = (xf * xf).sum(axis=reduce_axes)
            # During init there is no axis context (flax inits modules
            # outside shard_map); local moments are fine for shape tracing.
            if self.axis_name is not None and not self.is_initializing():
                s, ss, total = lax.psum((s, ss, total), self.axis_name)
            mean = s / total
            var = jnp.maximum(ss / total - mean * mean, 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
                ra_var.value = m * ra_var.value + (1.0 - m) * var

        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            scale = self.param("scale", self.scale_init, (feat,), self.param_dtype)
            y = y * scale.astype(jnp.float32)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (feat,), self.param_dtype)
            y = y + bias.astype(jnp.float32)
        return y.astype(self.dtype or x.dtype)

    @classmethod
    def for_communicator(
        cls, comm: CommunicatorBase, *, use_running_average: bool, **kwargs
    ) -> "MultiNodeBatchNormalization":
        return cls(
            use_running_average=use_running_average,
            axis_name=comm.bn_axis_name,
            **kwargs,
        )
