"""Synchronized (multi-node) batch normalization.

Reference: ``chainermn/links/batch_normalization.py`` (dagger) (SURVEY.md
sections 2.2, 2.5): ``MultiNodeBatchNormalization`` allreduces the batch
mean/variance across ranks inside forward so statistics reflect the *global*
batch; a ``communication_backend`` argument picked MPI vs NCCL.

TPU-native: batch statistics are ``lax.pmean``-ed over the data-parallel mesh
axis inside the jitted step — one fused collective on the (sum, sumsq) pair,
no backend selection needed. Implemented on flax's BatchNorm, whose ``axis_name``
machinery performs exactly this psum; the subclass exists to (a) give the
reference's name/shape to the API, (b) default the axis from a communicator,
and (c) document the invariant tested in ``tests/test_links.py``: sync-BN
over shards == plain BN over the concatenated batch.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from chainermn_tpu.communicators.base import CommunicatorBase


class MultiNodeBatchNormalization(nn.BatchNorm):
    """``nn.BatchNorm`` whose batch statistics are averaged over the
    data-parallel mesh axis (``axis_name``).

    Use inside a ``shard_map``-based train step::

        MultiNodeBatchNormalization(use_running_average=not train,
                                    axis_name='data')(x)

    or derive the axis from a communicator with :meth:`for_communicator`.
    """

    @classmethod
    def for_communicator(
        cls, comm: CommunicatorBase, *, use_running_average: bool, **kwargs
    ) -> "MultiNodeBatchNormalization":
        return cls(
            use_running_average=use_running_average,
            axis_name=comm.bn_axis_name,
            **kwargs,
        )
