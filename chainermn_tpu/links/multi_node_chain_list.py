"""Cross-rank model composition.

Reference: ``chainermn/links/multi_node_chain_list.py`` (dagger) (SURVEY.md
sections 2.5, 3.4): ``MultiNodeChainList(comm).add_link(chain, rank_in,
rank_out)`` registers components on ranks; ``__call__`` walks them in
registration order — participating ranks run their chain and ``send`` the
output to ``rank_out`` (a list means multicast), ranks expecting input
``recv`` from ``rank_in`` (a list means merge); delegate variables keep the
cross-rank backward connected and ordered.

TPU-native execution model: the whole multi-stage model is ONE program under
``shard_map`` over a ``'stage'`` mesh axis. Per component:

  * the transfer is an unconditional ``lax.ppermute`` executed by *all*
    shards (collectives may not hide inside divergent control flow — the
    SPMD analog of the reference's deadlock-ordering rule, enforced here by
    construction);
  * the compute is a ``lax.cond`` on ``axis_index == rank``: the owning
    shard runs the chain, others produce zeros of the same (statically
    inferred) shape. At runtime each shard executes only its branch — the
    compute really is distributed, like the reference's per-rank processes.
    Verified at the HLO level: the compiled SPMD module retains one true
    ``conditional`` (with separate branch computations) per gated stage, not
    a both-branches ``select`` (regression-tested in
    ``tests/test_links.py::test_chain_list_compute_gating_is_true_conditional``).

Because one traced program contains every stage, XLA schedules transfers and
compute together; the delegate-variable ordering discipline of the reference
is unnecessary (and cycles are structurally impossible: a component may only
consume wires produced by earlier components — checked at trace time).

Chains must be *local* computations (no collectives inside — same as the
reference, where a chain was ordinary single-rank Chainer code).

Training discipline: compute the loss inside the shard_map (psum the terminal
logits so the scalar is genuinely replicated) but differentiate the whole
sharded function from *outside* — ``jax.grad(shard_map(...))``. Taking the
gradient per-shard of a replicated loss multiplies stage cotangents by the
axis size (each shard re-derives the same cotangent and the psum transpose
sums them); see ``examples/mnist/train_mnist_model_parallel.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any
Ranks = Union[int, Sequence[int], None]


def _as_list(r: Ranks) -> list[int]:
    if r is None:
        return []
    if isinstance(r, int):
        return [r]
    return list(r)


class _Component:
    def __init__(self, fn, init_fn, rank, rank_in, rank_out, name):
        self.fn = fn
        self.init_fn = init_fn
        self.rank = rank
        self.rank_in = _as_list(rank_in)
        self.rank_out = _as_list(rank_out)
        self.name = name


class MultiNodeChainList:
    """Registry of ``(chain, rank, rank_in, rank_out)`` components executed
    as one SPMD program over a stage axis.

    ``add_link(fn, rank, rank_in=None, rank_out=None, init_fn=None)``:
      - ``fn(params, x)`` — the chain; ``x`` is the local input (for the
        entry component) or the received activation (tuple when ``rank_in``
        is a list — a merge);
      - ``rank`` — which stage-axis index owns the compute (the reference
        inferred this from the MPI rank running the code; SPMD needs it
        explicit);
      - ``rank_in`` / ``rank_out`` — where activations come from / go to,
        matching the reference's signature;
      - ``init_fn(rng, x) -> params`` — optional, enables ``init()``.

    The final component (``rank_out=None``) yields the model output on its
    owning shard (zeros elsewhere; reduce or fetch as needed).
    """

    def __init__(self, comm: CommunicatorBase, *, axis_name: str = "stage") -> None:
        self.comm = comm
        self.axis_name = axis_name
        self.components: list[_Component] = []

    def add_link(
        self,
        fn: Callable[[PyTree, Any], Any],
        *,
        rank: int,
        rank_in: Ranks = None,
        rank_out: Ranks = None,
        init_fn: Optional[Callable] = None,
        name: Optional[str] = None,
    ) -> "MultiNodeChainList":
        self.components.append(
            _Component(fn, init_fn, rank, rank_in, rank_out,
                       name or f"component_{len(self.components)}")
        )
        return self

    # ------------------------------------------------------------------

    def _forward_local(self, params_list: Sequence[PyTree], x: Any):
        """Per-shard body. Must run inside shard_map over ``axis_name``."""
        ax = self.axis_name
        n = lax.axis_size(ax)
        max_rank = max(
            [c.rank for c in self.components]
            + [r for c in self.components for r in c.rank_in + c.rank_out]
        )
        if max_rank >= n:
            raise ValueError(
                f"model uses stage rank {max_rank} but mesh axis {ax!r} has "
                f"only {n} slot(s) — run with a mesh of >= {max_rank + 1} "
                f"devices on that axis"
            )
        idx = lax.axis_index(ax)
        wires: dict[tuple[int, int], Any] = {}
        output = None

        for ci, comp in enumerate(self.components):
            params = params_list[ci]
            # ---- assemble input (zeros on non-owner shards is fine: the
            # owner is the only shard whose branch consumes it) ----
            if comp.rank_in:
                received = []
                for src in comp.rank_in:
                    key = (src, comp.rank)
                    if key not in wires:
                        raise ValueError(
                            f"{comp.name} on stage {comp.rank} expects input "
                            f"from stage {src}, but no earlier component sent "
                            f"one (forward references/cycles are rejected — "
                            f"reference parity: cycle detection)"
                        )
                    received.append(wires.pop(key))
                inp = received[0] if len(received) == 1 else tuple(received)
            else:
                inp = x

            # ---- compute under cond: only the owner executes the chain ----
            out_shape = jax.eval_shape(comp.fn, params, inp)
            zeros = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), out_shape
            )
            out = lax.cond(
                idx == comp.rank,
                lambda p, v: comp.fn(p, v),
                lambda p, v: zeros,
                params, inp,
            )

            # ---- transfer: unconditional collectives (one ppermute per
            # destination — ppermute sources must be unique, so a multicast
            # is a sequence of pairwise sends, like the reference's
            # send-to-list loop) ----
            if comp.rank_out:
                for dst in comp.rank_out:
                    key = (comp.rank, dst)
                    if key in wires:
                        raise ValueError(
                            f"{comp.name} sends stage {comp.rank} -> {dst}, "
                            f"but an earlier unconsumed transfer on that "
                            f"edge exists — insert the consumer between "
                            f"them (transfers on one edge are ordered, "
                            f"reference parity: delegate-variable ordering)"
                        )
                    wires[key] = lax.ppermute(out, ax, [(comp.rank, dst)])
            else:
                output = out

        if output is None:
            raise ValueError("no terminal component (one needs rank_out=None)")
        return output

    def apply(self, params_list: Sequence[PyTree], x: Any):
        """Call inside an existing shard_map context over ``axis_name``."""
        return self._forward_local(params_list, x)

    def build(self, *, in_spec: P = P(), replicate_output: bool = True):
        """A jitted whole-model forward over the communicator's mesh: input
        replicated (or sharded per ``in_spec``). The terminal activation is
        non-zero only on its owning shard; with ``replicate_output`` it is
        psum-broadcast to every shard (all other shards contribute zeros)."""
        mesh = self.comm.mesh
        ax = self.axis_name

        def body(p, v):
            out = self._forward_local(p, v)
            if replicate_output:
                out = jax.tree.map(lambda o: lax.psum(o, ax), out)
            return out

        def fwd(params_list, x):
            return shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), in_spec),
                out_specs=P(None) if replicate_output else P(ax),
                check_vma=False,
            )(params_list, x)

        return jax.jit(fwd)

    # ------------------------------------------------------------------

    def init(self, rng: jax.Array, x: Any) -> list[PyTree]:
        """Host-side parameter init: walks components in order, propagating
        activation shapes (via the chains themselves on dummy zeros), calling
        each ``init_fn``. All shards/processes derive identical params from
        the same rng — the functional form of the reference's first-update
        ``bcast_data``."""
        rngs = jax.random.split(rng, len(self.components))
        params_list: list[PyTree] = []
        acts: dict[tuple[int, int], Any] = {}
        for ci, comp in enumerate(self.components):
            if comp.init_fn is None:
                raise ValueError(f"{comp.name} registered without init_fn")
            if comp.rank_in:
                for s in comp.rank_in:
                    if (s, comp.rank) not in acts:
                        raise ValueError(
                            f"{comp.name} (rank {comp.rank}) expects an input "
                            f"from rank {s}, but no earlier component sent "
                            f"one — components must be registered in "
                            f"dependency order (reference parity: "
                            f"MultiNodeChainList rejects forward references)"
                        )
                received = [acts[(s, comp.rank)] for s in comp.rank_in]
                inp = received[0] if len(received) == 1 else tuple(received)
            else:
                inp = x
            params = comp.init_fn(rngs[ci], inp)
            params_list.append(params)
            out = jax.eval_shape(comp.fn, params, inp)
            dummy = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), out)
            for dst in comp.rank_out:
                acts[(comp.rank, dst)] = dummy
        return params_list
