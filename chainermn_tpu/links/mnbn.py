"""``create_mnbn_model`` — convert every BatchNorm in a model to
synchronized (multi-node) batch normalization.

Reference: ``chainermn/links/create_mnbn_model.py`` (dagger) (SURVEY.md
section 2.5 family): upstream walks a Chainer link tree and rebuilds it
with every ``L.BatchNormalization`` replaced by
``MultiNodeBatchNormalization`` so an existing single-node model becomes
global-batch-correct without edits.

TPU-native design: flax modules are built inside ``setup``/``@nn.compact``,
so there is no static link tree to rewrite. Instead of reconstructing the
model, the conversion intercepts module calls (``nn.intercept_methods``)
and gives every batch-norm layer whose ``axis_name`` is unset the
communicator's data axis for the duration of the call — flax's own
``nn.BatchNorm`` (and ours) already compute global statistics when an
``axis_name`` is present, so "replacement" reduces to axis injection. The
wrapper shares its scope with the wrapped model (``nn.share_scope``), so
parameters, collections, and checkpoints keep the exact same tree paths as
the unconverted model: it is a drop-in, both ways.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Optional

import flax
import flax.linen as nn

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.links.batch_normalization import MultiNodeBatchNormalization
from chainermn_tpu.parallel.collectives import axes_bound

_BN_TYPES = (nn.BatchNorm, MultiNodeBatchNormalization)

# ``_MnbnModel.__getattr__`` leans on flax-internal behaviors (string
# ``method=`` resolution on the unbound template, ``_try_setup``,
# ``share_scope`` semantics) that are validated by the test suite against
# the versions below. On a NEWER flax those could shift silently — the
# symptom would be un-synchronized BN, not an error — so warn loudly once.
_FLAX_VALIDATED_MAX = (0, 12)


def _warn_if_flax_untested() -> None:
    try:
        major, minor = (int(p) for p in flax.__version__.split(".")[:2])
    except (AttributeError, ValueError):
        return
    if (major, minor) > _FLAX_VALIDATED_MAX:
        warnings.warn(
            f"create_mnbn_model's method delegation was validated against "
            f"flax <= {_FLAX_VALIDATED_MAX[0]}.{_FLAX_VALIDATED_MAX[1]}.x "
            f"but flax {flax.__version__} is installed; run the "
            "chainermn_tpu mnbn test suite before trusting synchronized-BN "
            "conversion on this version.",
            stacklevel=3,
        )


def _bn_sync_interceptor(axis_name):
    """Give BN layers with no ``axis_name`` the data axis for one call.

    The attribute is restored afterwards — module instances are reused
    across calls and transforms, so the override must not leak outside the
    converted model's forward.

    Not thread-safe: the override briefly mutates the SHARED module
    instance (``object.__setattr__`` in a try/finally), so two threads
    tracing the same bound module concurrently could observe each other's
    injected ``axis_name`` (or the restored ``None``) mid-call. Typical
    JAX tracing is single-threaded; key the override in a thread-local if
    you trace converted models from multiple threads.
    """

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        # axes_bound: run OUTSIDE shard_map (local debugging, single-device
        # eval) the converted model degrades to plain-BN behavior instead
        # of raising an unbound-axis NameError.
        if (
            context.method_name == "__call__"
            and isinstance(mod, _BN_TYPES)
            and mod.axis_name is None
            and axes_bound(axis_name)
        ):
            object.__setattr__(mod, "axis_name", axis_name)
            try:
                return next_fun(*args, **kwargs)
            finally:
                object.__setattr__(mod, "axis_name", None)
        return next_fun(*args, **kwargs)

    return interceptor


class _MnbnModel(nn.Module):
    """The converted model. Transparent: same call signature, same
    parameter/collection tree paths as ``inner`` (scope is shared), and
    auxiliary methods pass through — ``apply(..., method='encode')`` works
    on the converted model with BN layers inside ``encode`` synchronized
    (upstream converted the whole link tree, so every entry point stayed
    synchronized; the delegation below preserves that contract)."""

    inner: nn.Module
    sync_axis: Any

    def setup(self):
        nn.share_scope(self, self.inner)

    def __call__(self, *args, **kwargs):
        with nn.intercept_methods(_bn_sync_interceptor(self.sync_axis)):
            return self.inner(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            pass
        # Guard the delegation base itself: during unpickling/deepcopy the
        # stdlib probes dunders on a __new__-created instance whose fields
        # aren't set yet — falling through to self.inner would re-enter
        # this __getattr__ forever.
        if name in ("inner", "sync_axis") or "inner" not in vars(self):
            raise AttributeError(name)
        # Dataclass fields pass through as VALUES even when the value is
        # callable (dtype classes, initializer functions): only genuine
        # methods get the interception delegate.
        if name in {f.name for f in dataclasses.fields(type(self.inner))}:
            return getattr(self.inner, name)
        if not callable(getattr(type(self.inner), name, None)):
            return getattr(self.inner, name)

        # flax resolves string `method=` names on the UNBOUND template and
        # calls the result with the BOUND module prepended — re-resolve
        # `inner` from that bound instance. A direct `bound.method(x)` call
        # happens on an already-bound instance (scope set) and prepends
        # nothing, so there the instance looked up on IS the receiver —
        # even when the method's first real argument happens to be another
        # converted model.
        looked_up_on_bound = getattr(self, "scope", None) is not None

        def _delegated(*args, **kwargs):
            if (
                not looked_up_on_bound
                and args
                and isinstance(args[0], _MnbnModel)
            ):
                mod_self, args = args[0], args[1:]
            else:
                mod_self = self
            # flax only runs setup() when one of the module's OWN wrapped
            # methods executes; this delegate bypasses that, so trigger it
            # here — share_scope must be in effect before inner runs, or
            # parameters resolve under an '/inner/...' scope that init
            # never populated.
            mod_self._try_setup()
            with nn.intercept_methods(_bn_sync_interceptor(mod_self.sync_axis)):
                return getattr(mod_self.inner, name)(*args, **kwargs)

        return _delegated


def create_mnbn_model(
    model: nn.Module,
    comm: Optional[CommunicatorBase] = None,
    *,
    axis_name: Any = None,
) -> nn.Module:
    """Return ``model`` with every batch-norm layer synchronized over the
    communicator's data-parallel axis (or an explicit ``axis_name``).

    Matches the reference's contract (``create_mnbn_model(link, comm)``
    (dagger)): the returned model is used exactly like the original —
    same ``init``/``apply`` signature, same parameter tree — but batch
    statistics are computed over the GLOBAL batch when the forward runs
    inside a ``shard_map``/mesh context carrying that axis. Layers that
    already have an ``axis_name`` are left untouched.
    """
    if (comm is None) == (axis_name is None):
        raise ValueError("pass exactly one of comm or axis_name")
    _warn_if_flax_untested()
    if comm is not None:
        axis_name = comm.bn_axis_name
    return _MnbnModel(inner=model, sync_axis=axis_name)


__all__ = ["create_mnbn_model"]
