"""Model-parallel composition links.

Reference: ``chainermn/links/`` (dagger) (SURVEY.md section 2.5).
"""

from chainermn_tpu.links.multi_node_chain_list import MultiNodeChainList
from chainermn_tpu.links.batch_normalization import MultiNodeBatchNormalization

__all__ = ["MultiNodeChainList", "MultiNodeBatchNormalization"]
