"""Model-parallel composition links.

Reference: ``chainermn/links/`` (dagger) (SURVEY.md section 2.5).
"""

from chainermn_tpu.links.multi_node_chain_list import MultiNodeChainList
from chainermn_tpu.links.batch_normalization import MultiNodeBatchNormalization
from chainermn_tpu.links.mnbn import create_mnbn_model

__all__ = [
    "MultiNodeChainList",
    "MultiNodeBatchNormalization",
    "create_mnbn_model",
]
