"""Differentiable point-to-point communication.

Reference: ``chainermn/functions/point_to_point_communication.py`` (dagger)
(SURVEY.md sections 2.4, 3.4): Chainer ``Send``/``Recv`` Functions whose
backward passes are each other (``Send.backward`` receives the upstream
gradient over MPI; ``Recv.backward`` sends it), plus *delegate variables* and
``pseudo_connect`` imposing a total order on transfers so bidirectional
graphs cannot deadlock MPI.

TPU-native: inside a ``shard_map`` over a stage/model axis, a matched
send+recv pair is ONE ``lax.ppermute`` — XLA compiles and schedules the
transfer, and its transpose (the backward) is the inverse permutation,
automatically. Two whole classes of reference machinery therefore vanish:
  * deadlock ordering (XLA schedules all collectives in one program — the
    hazard the delegate-variable discipline existed for);
  * explicit backward implementations (ppermute is linear; AD transposes it).
``pseudo_connect`` survives as a graph-shaping helper: grafting a delegate
onto real variables so a stage with no local loss still contributes its
communication edges to the backward program.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def send_recv(x: PyTree, src: int, dst: int, axis_name: str) -> PyTree:
    """Transfer ``x`` from shard ``src`` to shard ``dst`` along ``axis_name``.

    Every shard participates (SPMD); the return value is ``src``'s ``x`` on
    shard ``dst`` and **zeros elsewhere**. Differentiable: the cotangent
    flows from ``dst`` back to ``src`` — exactly the reference's
    ``Send.backward == recv`` / ``Recv.backward == send`` duality, for free.
    """
    return lax.ppermute(x, axis_name, [(src, dst)])


def send(x: PyTree, dst: int, axis_name: str, *, src: Optional[int] = None):
    """Reference-shaped ``send``: returns a zero-size *delegate* tying the
    transfer into the caller's graph (thread it into a later
    :func:`pseudo_connect` or ``recv`` just like the reference's delegate
    variables — here it shapes the autodiff graph rather than preventing
    MPI deadlock).

    ``src`` is required: SPMD traces ONE program for every shard, so there is
    no implicit "my rank" at trace time — the (src, dst) pair must be static.
    (The reference inferred src from the calling process's MPI rank; that
    notion does not exist under a single controller.)
    """
    if src is None:
        raise ValueError(
            "SPMD send needs the static source index: send(x, dst, axis, src=i) "
            "(one program runs on every shard; there is no implicit 'my rank' "
            "at trace time)"
        )
    received = send_recv(x, src, dst, axis_name)
    delegate = jax.tree.map(lambda r: jnp.sum(r) * 0.0, received)
    return received, delegate


def recv(received: PyTree, *, delegate: Optional[PyTree] = None) -> PyTree:
    """Reference-shaped ``recv``: unwraps a transfer produced by
    :func:`send`/:func:`send_recv`, optionally grafting a ``delegate`` from a
    previous transfer (the reference's ``recv(..., delegate_variable=phi)``
    ordering idiom)."""
    if delegate is not None:
        received = pseudo_connect(delegate, received)
    return received


def stream_blocks(blocks: PyTree, src: int, dst: int,
                  axis_name: str) -> PyTree:
    """Move a whole KV-block pytree from shard ``src`` to shard ``dst``
    — one :func:`send_recv` (``lax.ppermute``) per leaf, scheduled by
    XLA as one program.

    The in-mesh rehearsal of the cluster serving plane's KV handoff
    (:mod:`chainermn_tpu.serving.cluster.kv_transfer`): when prefill
    and decode replicas live on one mesh, the block payload can ride
    ICI instead of the host TCP plane. The production handoff is
    host-plane by contract (replicas own independent compiled
    programs; a device collective would couple them) — this helper
    exists so the device path is exercised and measured
    (``tests/test_cluster.py``), not asserted in prose. Result: the
    payload on shard ``dst``, zeros elsewhere (SPMD), differentiable
    like every transfer here.
    """
    return jax.tree.map(lambda x: send_recv(x, src, dst, axis_name),
                        blocks)


def pseudo_connect(delegate: PyTree, actual: PyTree) -> PyTree:
    """Graft ``delegate``'s graph edges onto ``actual``.

    Reference (``pseudo_connect`` (dagger)): ensures backward on a rank whose
    loss does not depend on a transfer still executes that transfer's
    backward, and in order. Here: adds a zero term built from ``delegate`` to
    every leaf of ``actual`` so autodiff keeps the delegate's communication
    edges in the backward program (value is unchanged).
    """
    zeros = [jnp.sum(leaf) * 0.0 for leaf in jax.tree.leaves(delegate)]
    if not zeros:
        return actual
    z = sum(zeros)

    def graft(a):
        return a + z.astype(a.dtype)

    return jax.tree.map(graft, actual)
