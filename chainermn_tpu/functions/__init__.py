"""Differentiable cross-rank communication functions.

Reference: ``chainermn/functions/`` (dagger) (SURVEY.md section 2.4) — the
layer that lets the autograd graph span ranks, enabling model/pipeline
parallelism.
"""

from chainermn_tpu.functions.point_to_point import (
    send_recv,
    send,
    recv,
    pseudo_connect,
)
from chainermn_tpu.functions.collective import (
    allgather,
    alltoall,
    bcast,
    gather,
    scatter,
    allreduce,
)

__all__ = [
    "send_recv",
    "send",
    "recv",
    "pseudo_connect",
    "allgather",
    "alltoall",
    "bcast",
    "gather",
    "scatter",
    "allreduce",
]
