"""Differentiable collective communication functions.

Reference: ``chainermn/functions/collective_communication.py`` (dagger)
(SURVEY.md section 2.4): Chainer Functions pairing each collective with its
transpose — allgather/bwd:alltoall-sum, alltoall/bwd:alltoall, bcast/bwd:
gather+sum-on-root, gather/bwd:scatter, scatter/bwd:gather.

TPU-native: each is a thin wrapper over the named-axis primitives in
:mod:`chainermn_tpu.parallel.collectives`; JAX's AD already knows the
transpose of every XLA collective, so the reference's hand-written backward
pairs hold here *by construction* (and are asserted in
``tests/test_functions.py`` numerically).

All functions must be called inside a ``shard_map``/named-axis context over
``axis_name``. They accept either an axis name or a communicator.
"""

from __future__ import annotations

from typing import Union

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.parallel import collectives as C


def _axis(comm_or_axis: Union[str, CommunicatorBase]) -> str:
    if isinstance(comm_or_axis, str):
        return comm_or_axis
    return comm_or_axis.axis_name


def allgather(x, comm_or_axis, *, axis: int = 0, tiled: bool = False):
    """Differentiable allgather (backward: reduce-scatter of cotangents —
    the reference's alltoall-sum)."""
    return C.allgather(x, _axis(comm_or_axis), axis=axis, tiled=tiled)


def alltoall(x, comm_or_axis, *, split_axis: int = 0, concat_axis: int = 0,
             tiled: bool = True):
    """Differentiable all-to-all (self-transpose under AD)."""
    return C.alltoall(
        x, _axis(comm_or_axis), split_axis=split_axis,
        concat_axis=concat_axis, tiled=tiled,
    )


def bcast(x, comm_or_axis, root: int = 0):
    """Differentiable broadcast from ``root`` (backward: cotangents sum onto
    root — the reference's gather+sum)."""
    return C.bcast(x, _axis(comm_or_axis), root=root)


def gather(x, comm_or_axis, root: int = 0, *, axis: int = 0):
    """Differentiable gather to ``root`` (backward: scatter)."""
    return C.gather(x, _axis(comm_or_axis), root=root, axis=axis)


def scatter(x, comm_or_axis, root: int = 0, *, axis: int = 0):
    """Differentiable scatter from ``root`` (backward: gather)."""
    return C.scatter(x, _axis(comm_or_axis), root=root, axis=axis)


def allreduce(x, comm_or_axis, *, op: str = "sum"):
    """Differentiable allreduce (psum's transpose is psum)."""
    return C.allreduce(x, _axis(comm_or_axis), op=op)
