"""Multi-node optimizer wrappers.

Reference: ``chainermn/optimizers.py`` (dagger) (SURVEY.md sections 2.3, 3.2):
``create_multi_node_optimizer(opt, comm, double_buffering=False)`` wraps any
Chainer optimizer so that ``update()`` broadcasts weights on the first
iteration and allreduces gradients on every iteration;
``_DoubleBufferingOptimizer`` overlaps the allreduce with backward on a side
CUDA stream at the cost of one step of gradient staleness.

TPU-native design: the wrapped object is an :class:`optax.GradientTransformation`
meant to be used *inside the jitted train step*. ``allreduce_grad`` is a
``lax.pmean`` over the communicator's mesh axes — XLA fuses the reference's
pack / fp16-cast / ncclAllReduce / scale / unpack pipeline
(``pure_nccl_communicator.py`` (dagger)) into its collective schedule, and its
latency-hiding scheduler overlaps the collective with remaining backward
computation, which is what double buffering bought on GPU. The
``double_buffering=True`` flag is still honoured with *faithful semantics*
(updates apply the previous step's reduced gradients, staleness 1) so
convergence behaviour matches the reference feature; on TPU it additionally
lets XLA start the psum of step *t* while step *t*'s weights update with
*t-1*'s gradients.

Weight broadcast on first iteration: in the functional JAX world parameters
are created once and replicated by :meth:`CommunicatorBase.bcast_data`; call
``optimizer.broadcast(params)`` (or rely on identical PRNG keys) instead of a
hidden first-update hook.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any


def _pmean_if_in_axis(tree: PyTree, axis_names) -> PyTree:
    """pmean over ``axis_names`` when tracing inside that named-axis context
    (shard_map/pmap); identity otherwise (pjit auto-parallel mode, where XLA
    inserts the reduction from sharding propagation, or single-device)."""
    from chainermn_tpu.parallel.collectives import axes_bound

    if not axes_bound(axis_names):
        return tree
    return lax.pmean(tree, axis_names)


def allreduce_gradients(
    grads: PyTree,
    comm: Optional[CommunicatorBase] = None,
    *,
    axis_names=None,
    compress_dtype=None,
) -> PyTree:
    """In-jit gradient averaging — the hot collective of the framework.

    With ``compress_dtype`` (e.g. ``jnp.bfloat16``) gradients are cast before
    the collective and restored after: the reference's
    ``allreduce_grad_dtype='float16'`` compressed allreduce
    (``pure_nccl_communicator.py`` (dagger), shu65's v1.3 feature) — halves
    bytes on ICI/DCN; master accumulation stays f32.

    ``compress_dtype=jnp.int8`` selects the QUANTIZED wire (beyond the
    reference): max-abs-scaled int8 over a two-phase
    all_to_all/all_gather scheme
    (:func:`chainermn_tpu.parallel.collectives.int8_allreduce_mean`) —
    ~2 bytes/element on the wire vs bf16's 4, at ~1/127-relative
    rounding noise per stage. Outside a named-axis context int8 is an
    identity (no pointless quantization round-trip).
    """
    if axis_names is None:
        if comm is None:
            raise ValueError("pass a communicator or axis_names")
        # Strategy dispatch: the communicator owns its in-jit reduction
        # algorithm (base: fused pmean; two_dimensional: explicit
        # reduce-scatter -> inter-allreduce -> all-gather).
        return comm.reduce_gradients_in_jit(grads, compress_dtype=compress_dtype)

    int8_wire = (compress_dtype is not None
                 and jnp.dtype(compress_dtype) == jnp.dtype(jnp.int8))

    def reduce_leaf(g):
        if int8_wire and jnp.issubdtype(g.dtype, jnp.floating):
            from chainermn_tpu.parallel.collectives import (
                axes_bound,
                int8_allreduce_mean,
            )

            if not axes_bound(axis_names):
                return g
            return int8_allreduce_mean(g, axis_names)
        if compress_dtype is not None and not int8_wire and jnp.issubdtype(
            g.dtype, jnp.floating
        ):
            return _pmean_if_in_axis(g.astype(compress_dtype), axis_names).astype(
                g.dtype
            )
        # pmean promotes integer leaves to float; keep the leaf dtype
        # (reference parity: allreduce_grad returned grads in-place/dtype).
        return _pmean_if_in_axis(g, axis_names).astype(g.dtype)

    return jax.tree.map(reduce_leaf, grads)


def allreduce_grads_transform(
    comm: CommunicatorBase, *, compress_dtype=None
) -> optax.GradientTransformation:
    """Standalone optax transform performing the gradient allreduce; compose
    it manually as ``optax.chain(allreduce_grads_transform(comm), inner)`` if
    you don't want the full wrapper."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        return (
            allreduce_gradients(updates, comm, compress_dtype=compress_dtype),
            state,
        )

    return optax.GradientTransformation(init_fn, update_fn)


class _DoubleBufferState(NamedTuple):
    inner: Any
    #: gradients reduced at step t-1, applied at step t (staleness 1)
    communicated_grads: PyTree
    step: jax.Array


class _ErrorFeedbackState(NamedTuple):
    inner: Any
    #: per-rank residual of the int8 wire's quantization, added into the
    #: next step's message (EF-SGD). Flat wire: mirrors the params tree
    #: (full-param f32). Topology-aware wire: a tuple of SHARD-shaped f32
    #: buffers, one per ~64 MB bucket — the error arises only at the
    #: inter stage, on the intra-summed shard, and is stored there.
    residual: PyTree


class _ZeroShardState(NamedTuple):
    """Optimizer state of the ``'zero'`` reduction schedule: EVERY inner
    leaf is stacked ``[n_shards, ...]`` along a leading shard dim
    (scalar counters tiled), so one prefix ``PartitionSpec`` shards the
    whole subtree over the scatter axis — ZeRO-1 state sharding fused
    into the gradient-reduction schedule (reduce-scatter -> sharded
    update -> allgather, arXiv:2004.13336; the chunk layout of
    :mod:`chainermn_tpu.parallel.zero`, optimizer-wrapped)."""

    inner: Any


_EF_BUCKET_BYTES = 64 << 20


def _float_bucket_partition(float_idx, sizes, bucket_bytes=None):
    """Deterministic ~64 MB (f32) bucket partition of the float leaves
    — ONE function used by ``MultiNodeOptimizer.init`` (residual
    allocation), ``_reduce_with_feedback`` (the EF reduction), and the
    schedule layer, so no two consumers can disagree about the layout.
    Thin f32 wrapper over
    :func:`chainermn_tpu.parallel.reduction_schedule.bucket_partition`,
    which owns the edge contract: zero-size leaves are skipped (they
    ride the exact per-leaf path), a payload smaller than one bucket
    yields exactly one bucket, a single leaf larger than the bucket
    gets its own bucket unsplit, and no bucket is ever empty.
    ``bucket_bytes`` comes from the optimizer's autotuned resolution
    (decision ``allreduce_bucket_mb``, resolved ONCE per optimizer
    instance so init and update always see the same layout)."""
    from chainermn_tpu.parallel.reduction_schedule import bucket_partition

    if bucket_bytes is None:
        bucket_bytes = _EF_BUCKET_BYTES
    return bucket_partition(float_idx, sizes, 4, bucket_bytes)


class MultiNodeOptimizer:
    """optax-compatible wrapper: ``init``/``update`` plus communicator-aware
    gradient reduction. Duck-types :class:`optax.GradientTransformation`.

    Reference behaviours preserved (``optimizers.py`` (dagger)):
      - every update averages gradients across all ranks before applying;
      - ``double_buffering=True`` applies the *previous* iteration's averaged
        gradients (staleness-1) — tested for exactly that semantic;
      - attribute delegation: unknown attributes forward to the wrapped
        optimizer (the reference delegated via ``__getattr__``).

    ``reduction_schedule`` selects the gradient-reduction ALGORITHM
    (:mod:`chainermn_tpu.parallel.reduction_schedule`; see
    docs/parallelism.md "Gradient-reduction schedules"):

    - ``None`` (default): the communicator's own strategy — base: fused
      pmean; two_dimensional: its packed two-level pipeline. Exactly
      the pre-schedule behaviour.
    - ``'flat'``: the packed flat allreduce, pinned (the reference's
      ``_memory_utility.pack_params`` (dagger) discipline).
    - ``'two_level'``: intra reduce-scatter -> inter allreduce on the
      shard -> allgather, per ~64 MB bucket (HiCCL-style composition,
      arXiv:2408.05962).
    - ``'zero'``: reduce-scatter + SHARDED update + allgather — the
      inner optimizer runs on 1/n of the parameters with 1/n of its
      state (arXiv:2004.13336), fused with
      :mod:`chainermn_tpu.parallel.zero`'s chunk layout. The inner
      transform must be elementwise (adam/sgd/...); carry the state
      through ``shard_map`` with :meth:`opt_state_spec`
      (``make_train_step`` does this automatically). Incompatible with
      ``double_buffering``, ``error_feedback`` and the int8 wire.
    - ``'auto'``: resolved once per optimizer instance through the
      autotune registry (decision ``'reduction_schedule'``, keyed
      device_kind x world-shape x payload-MB bucket), seedable offline
      from bench's ``overlap`` phase rows.

    ``double_buffering=True`` is the OVERLAPPED mode: the update
    consumes the PREVIOUS step's banked buckets while this step's
    reduction is dispatched with no data path into the current update
    (certified structurally in tests/test_optimizer.py) — with an
    explicit schedule (or the default's bucketed overlap form) each
    bucket's trace-time ``wire`` event carries ``overlapped=True`` so
    ``tools/trace_report.py`` reports the comm-hidden fraction.
    """

    #: protocol marker for make_train_step: this wrapper performs its own
    #: cross-rank synchronisation, so the step must NOT pre-reduce grads
    #: (an isinstance special-case would silently miss sibling wrappers —
    #: it did: LocalSGDOptimizer kept the per-step wire until review).
    handles_cross_rank_sync = True

    def __init__(
        self,
        actual_optimizer: optax.GradientTransformation,
        communicator: CommunicatorBase,
        *,
        double_buffering: bool = False,
        compress_dtype=None,
        error_feedback: bool = False,
        reduction_schedule: str | None = None,
    ) -> None:
        self.actual_optimizer = actual_optimizer
        self.communicator = communicator
        self.double_buffering = double_buffering
        if isinstance(compress_dtype, str) and compress_dtype == "auto":
            # Same device-aware wire resolution the communicator's
            # allreduce_grad_dtype="auto" takes (chainermn_tpu.tuning).
            # A resolved f32 wire is None — deliberately NOT falling
            # through to the communicator's configured dtype.
            from chainermn_tpu.parallel.collectives import (
                resolve_allreduce_wire,
            )

            self.compress_dtype = resolve_allreduce_wire(
                communicator.device_kind, communicator.size
            )
        else:
            self.compress_dtype = (
                compress_dtype
                if compress_dtype is not None
                else communicator.allreduce_grad_dtype
            )
        self.error_feedback = error_feedback
        if error_feedback and not self._int8_wire():
            raise ValueError(
                "error_feedback requires the int8 quantized wire "
                "(allreduce_grad_dtype=jnp.int8) — other dtypes lose "
                "nothing systematic to feed back"
            )
        from chainermn_tpu.parallel.composition import (
            Composition,
            CompositionError,
            compile_schedule,
        )
        from chainermn_tpu.parallel.reduction_schedule import SCHEDULES

        if reduction_schedule not in (None, "auto") + SCHEDULES:
            # Beyond the menu: a composition signature string or a
            # Composition instance (ISSUE 12) — validated against this
            # communicator's mesh axes NOW, so a broken pipeline fails
            # at construction, not inside the compiled step.
            try:
                comp = compile_schedule(
                    reduction_schedule, communicator.grad_axes
                )
            except CompositionError as e:
                raise ValueError(
                    f"reduction_schedule must be one of "
                    f"{(None, 'auto') + SCHEDULES}, a composition "
                    f"signature, or a Composition; got "
                    f"{reduction_schedule!r} ({e})"
                ) from None
            if comp.has_update:
                raise ValueError(
                    f"reduction_schedule composition "
                    f"{comp.signature()!r} carries a sharded_update "
                    "stage — spell the structural form as "
                    "reduction_schedule='zero'"
                )
            if isinstance(reduction_schedule, Composition):
                reduction_schedule = comp  # normalized+validated
        if error_feedback and reduction_schedule not in (None, "flat"):
            raise ValueError(
                "error_feedback owns its reduction (the flat or the "
                "communicator's topology-aware quantized wire) — "
                f"reduction_schedule={reduction_schedule!r} cannot compose"
            )
        if reduction_schedule == "zero":
            if double_buffering:
                raise ValueError(
                    "reduction_schedule='zero' cannot compose with "
                    "double_buffering: the sharded update replaces the "
                    "grads the staleness bank would carry"
                )
            if self._int8_wire():
                raise ValueError(
                    "reduction_schedule='zero' cannot ride the int8 wire "
                    "(its reduce-scatter sums raw chunks; the two-phase "
                    "quantized scheme has no scatter form) — use bf16 "
                    "compression or the flat/two_level schedules"
                )
        self.reduction_schedule = reduction_schedule
        #: candidates an ``'auto'`` resolution may pick: the DERIVED
        #: choice set for this mesh's axis count (menu names + the
        #: compositions the menu cannot express, by signature —
        #: chainermn_tpu.parallel.composition.schedule_candidates).
        #: ``'zero'`` is eligible only when nothing structurally
        #: incompatible is on; beyond-menu compositions only on a
        #: lossless/bf16 wire (the int8 two-phase wire has flat and
        #: two-level renderings only).
        from chainermn_tpu.parallel.composition import schedule_candidates

        self._auto_candidates = tuple(
            s for s in schedule_candidates(len(communicator.grad_axes))
            if not (s == "zero" and (double_buffering or error_feedback
                                     or self._int8_wire()))
            and not (s not in SCHEDULES and self._int8_wire())
        )
        #: the one-shot 'auto' resolution (first need wins — init and
        #: update must agree on the state layout) + its registry record.
        self._auto_resolved: str | None = None
        self._schedule_provenance: dict | None = None
        # One resolution per optimizer instance: init's residual
        # allocation and update's reduction must see the same bucket
        # layout even if the autotune cache changes mid-process. The
        # table-default 64 MB resolves to None — _float_bucket_partition
        # then reads the module's _EF_BUCKET_BYTES at call time, keeping
        # that constant the single default (and test seam); only a
        # non-default cache/forced decision pins an explicit size here.
        from chainermn_tpu import tuning

        mb = tuning.choice(
            "allreduce_bucket_mb", ("16", "64", "256", "none"),
            tuning.decision_key(communicator.device_kind,
                                shape=(communicator.size,), dtype="grad"),
        )
        self._bucket_bytes = (
            None if mb == "64"
            else (1 << 62) if mb == "none"
            else int(mb) << 20
        )
        if double_buffering:
            self._advise_double_buffering()

    def _advise_double_buffering(self) -> None:
        """Warn-and-record when the autotune cache says the
        double-buffering flag LOSES on this backend (measured 0.752x on
        the CPU proxy, 0.85x on a single chip — the grad-sized bank is
        pure cost with no collective to overlap). The flag stays
        honoured with faithful staleness-1 semantics — this is an
        advisory, not an override — and the decision is recorded either
        way so bench/dryrun artifacts show the provenance. The blanket
        table fallback does NOT warn: on an unmeasured topology (e.g. a
        real multi-chip pod, exactly where the flag is designed to pay)
        there is no evidence to cite, and a warning claiming a
        measurement would be false."""
        import warnings

        from chainermn_tpu import tuning

        comm = self.communicator
        key = tuning.decision_key(comm.device_kind, shape=(comm.size,),
                                  dtype="step")
        verdict = tuning.choice("double_buffering", ("on", "off"), key)
        rec = next((d for d in tuning.decisions_taken()
                    if d["name"] == "double_buffering"
                    and d["key"] == key), {})
        evidenced = rec.get("source", "").startswith(("cache", "measured"))
        if verdict == "off" and evidenced:
            warnings.warn(
                "double_buffering=True, but the autotune record for "
                f"this backend (key {key!r}, {rec.get('source')}) says "
                "the flag loses here — with no collective to overlap "
                "the grad-sized bank is pure cost (measured 0.85x "
                "on-chip, 0.752x CPU proxy; see docs/benchmarks.md). "
                "Keeping the requested staleness-1 semantics; enable "
                "it where a real inter-chip allreduce sits on the "
                "critical path.",
                stacklevel=4,
            )

    def _int8_wire(self) -> bool:
        return (self.compress_dtype is not None
                and jnp.dtype(self.compress_dtype) == jnp.dtype(jnp.int8))

    # -- reduction-schedule plumbing ---------------------------------------

    def _zero_axis(self) -> str:
        """The scatter axis of the 'zero' schedule: the LAST grad axis
        (mesh convention puts the fast/intra axis last — state shards
        where the gather is cheapest)."""
        return self.communicator.grad_axes[-1]

    def _zero_n(self) -> int:
        return int(self.communicator.mesh.shape[self._zero_axis()])

    def _effective_schedule(self, tree: PyTree | None = None) -> str | None:
        """The schedule this update runs: the explicit choice, the
        one-shot ``'auto'`` resolution (payload taken from ``tree``),
        or — for the default ``None`` — the communicator's own strategy,
        EXCEPT under double buffering, where the overlapped mode runs
        the bucketed pipeline so each in-flight bucket is a separately
        schedulable (and separately traced) collective."""
        s = self.reduction_schedule
        if s == "auto":
            if self._auto_resolved is None:
                from chainermn_tpu.parallel.reduction_schedule import (
                    resolve_schedule,
                )

                payload = sum(
                    leaf.size * jnp.dtype(leaf.dtype).itemsize
                    for leaf in jax.tree.leaves(tree)
                ) if tree is not None else 0
                comm = self.communicator
                winner, rec = resolve_schedule(
                    comm.device_kind, payload,
                    tuple(int(v) for v in comm.mesh.shape.values()),
                    candidates=self._auto_candidates,
                    # comp_slices (ISSUE 15): slice the winner where a
                    # measured capture adopted an interleave — except
                    # on the int8 wire, whose two-phase scheme has no
                    # sliced rendering.
                    slices=(None if self._int8_wire() else "auto"),
                )
                self._auto_resolved = winner
                self._schedule_provenance = rec
            return self._auto_resolved
        if s is None and self.double_buffering:
            return ("two_level"
                    if getattr(self.communicator, "two_level_axes", None)
                    is not None else "flat")
        return s

    def _reduce_scheduled(self, grads: PyTree, schedule: str | None) -> PyTree:
        """Reduce ``grads`` under ``schedule`` (never 'zero' — that is
        structural, see ``_zero_update``). ``None`` and any
        outside-axis-context call take the legacy communicator path, so
        the degrade semantics (identity + compress-dtype roundtrip)
        stay byte-identical to the pre-schedule behaviour."""
        from chainermn_tpu.parallel.collectives import axes_bound
        from chainermn_tpu.parallel.reduction_schedule import reduce_tree

        comm = self.communicator
        if schedule is None or not axes_bound(comm.grad_axes):
            return allreduce_gradients(
                grads, comm, compress_dtype=self.compress_dtype
            )
        return reduce_tree(
            grads,
            schedule=schedule,
            axes=comm.grad_axes,
            compress_dtype=self.compress_dtype,
            bucket_bytes=self._bucket_bytes,
            overlapped=self.double_buffering,
            provenance=self._schedule_provenance,
            size=comm.size,
        )

    def opt_state_spec(self):
        """``PartitionSpec`` (prefix pytree) for carrying this
        optimizer's state through ``shard_map``: the 'zero' schedule
        shards every (stacked) state leaf over the scatter axis;
        everything else is replicated. ``make_train_step`` consumes
        this automatically; hand-rolled steps pass it as the state's
        ``in_specs``/``out_specs`` entry.

        An unresolved ``'auto'`` is resolved HERE (payload unknown —
        the 1 MB key bucket) rather than silently reported replicated:
        the resolution is one-shot, so whichever of init()/this runs
        first fixes the schedule and the other agrees — never a spec
        that contradicts the state layout. Call ``init`` (or
        ``create_train_state``) first when the payload-keyed cache
        entry should decide."""
        from jax.sharding import PartitionSpec as P

        sched = self.reduction_schedule
        if sched == "auto":
            sched = self._effective_schedule(None)
        if sched == "zero":
            return _ZeroShardState(inner=P(self._zero_axis()))
        return P()

    # -- the 'zero' schedule: reduce-scatter + sharded update + allgather --

    def _zero_update(self, grads: PyTree, state, params: PyTree | None):
        """Xu et al.'s reduce-scatter sharded update (arXiv:2004.13336),
        fused with parallel/zero.py's chunk layout: each shard receives
        the MEAN of its 1/n gradient chunk (half an allreduce's wire
        bytes), updates 1/n of the optimizer state, and allgathers the
        1/n parameter updates back (the other half). Outside any
        named-axis context it degrades to a vectorised per-chunk update
        over the full stacked state — elementwise inner transforms make
        that exactly the full-parameter update, so eager/pjit callers
        see identical numerics with zero collectives."""
        from chainermn_tpu.parallel.collectives import axes_bound, axes_size
        from chainermn_tpu.parallel.zero import _chunk_rows, _unchunk

        inner = self.actual_optimizer
        comm = self.communicator
        names = comm.grad_axes
        ax = names[-1]
        n = self._zero_n()
        compress = self.compress_dtype

        if not axes_bound(names):
            grows = jax.tree.map(lambda g: _chunk_rows(g, n), grads)
            prows = (jax.tree.map(lambda p: _chunk_rows(p, n), params)
                     if params is not None else None)
            if prows is None:
                urows, inner_state = jax.vmap(
                    lambda g, s: inner.update(g, s)
                )(grows, state.inner)
            else:
                urows, inner_state = jax.vmap(inner.update)(
                    grows, state.inner, prows
                )
            updates = jax.tree.map(
                lambda u, g: _unchunk(u, g.shape, g.dtype), urows, grads
            )
            return updates, _ZeroShardState(inner=inner_state)

        lead = {int(jnp.shape(e)[0]) for e in jax.tree.leaves(state.inner)
                if jnp.ndim(e) >= 1}
        if lead and lead != {1}:
            raise ValueError(
                "the 'zero' schedule's opt_state reached update without "
                f"being sharded (leading dims {sorted(lead)}, expected 1 "
                "per shard) — carry it through shard_map with "
                "optimizer.opt_state_spec() (make_train_step does this), "
                "never closed over or replicated"
            )
        n_tot = axes_size(names)
        idx = lax.axis_index(ax)

        # The 'zero' schedule IS a composition instance (ISSUE 12):
        # rs(fast) > [ar(rest)] > sharded_update > ag(fast) — the
        # reduce prefix and gather suffix run through the one staged
        # executor, with the inner optimizer fused between them.
        from chainermn_tpu.parallel.composition import (
            run_gather_suffix,
            run_reduce_prefix,
            zero_composition,
        )

        pre, post = zero_composition(names).split_update()
        gchunks = jax.tree.map(
            lambda g: run_reduce_prefix(
                g, pre, total=n_tot, wire_dtype=compress
            ),
            grads,
        )
        pchunks = (jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(
                _chunk_rows(p, n), idx, keepdims=False
            ), params,
        ) if params is not None else None)
        schunk = jax.tree.map(lambda e: e[0], state.inner)
        uchunks, schunk = inner.update(gchunks, schunk, pchunks)
        inner_state = jax.tree.map(lambda e: e[None], schunk)

        updates = jax.tree.map(
            lambda u, g: run_gather_suffix(u, g, post, pre),
            uchunks, grads,
        )
        return updates, _ZeroShardState(inner=inner_state)

    # -- optax protocol ----------------------------------------------------

    def init(self, params: PyTree):
        if self._effective_schedule(params) == "zero":
            # 1/n state per shard, stacked [n, ...] (scalar counters
            # tiled) so ONE prefix spec shards the whole subtree — the
            # layout _zero_update and opt_state_spec() both key on.
            # Works eagerly (create_train_state) and in-trace alike.
            from chainermn_tpu.parallel.zero import _chunk_rows

            n = self._zero_n()
            rows = jax.tree.map(
                lambda p: _chunk_rows(jnp.asarray(p), n), params
            )
            return _ZeroShardState(
                inner=jax.vmap(self.actual_optimizer.init)(rows)
            )
        state = self.actual_optimizer.init(params)
        if self.double_buffering:
            state = _DoubleBufferState(
                inner=state,
                communicated_grads=jax.tree.map(jnp.zeros_like, params),
                step=jnp.zeros((), jnp.int32),
            )
        if self.error_feedback:
            # Residual lives in float32 regardless of param dtype: with
            # bf16 params a bf16 residual would itself drop ~2/3 of the
            # quantization error being fed back each step, weakening the
            # cumulative-bias-removal guarantee EF exists for.
            axes2 = getattr(self.communicator, "two_level_axes", None)
            if axes2 is not None:
                # Topology-aware wire: the only lossy stage quantizes
                # the intra-summed SHARD per bucket, so the residual is
                # one shard-shaped f32 buffer per bucket — 1/n_intra
                # the flat-wire residual's footprint. Bucket layout is
                # static (param sizes + mesh shape), shared with the
                # update path via _float_bucket_partition.
                from chainermn_tpu.parallel.collectives import (
                    two_level_shard_len,
                )

                intra_ax, _ = axes2
                n_intra = self.communicator.mesh.shape[intra_ax]
                leaves = jax.tree.leaves(params)
                sizes = [leaf.size for leaf in leaves]
                float_idx = [
                    i for i, leaf in enumerate(leaves)
                    if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
                ]
                residual = tuple(
                    jnp.zeros(
                        (two_level_shard_len(
                            sum(sizes[i] for i in bidx), n_intra),),
                        jnp.float32,
                    )
                    for bidx in _float_bucket_partition(
                        float_idx, sizes, self._bucket_bytes)
                )
            else:
                # Flat wire: one params-sized f32 buffer.
                residual = jax.tree.map(
                    lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
                )
            state = _ErrorFeedbackState(inner=state, residual=residual)
        return state

    def _reduce_with_feedback(self, grads: PyTree, residual: PyTree):
        """EF-SGD over the int8 wire: the NEW residual is exactly what
        quantization dropped this step — deterministic rounding bias is
        fed back instead of lost.

        Float leaves ride ~64 MB flat f32 buckets (the same packing
        discipline as the two-dimensional communicator's pipeline —
        tiny bias/scale leaves must not each pay their own collective;
        layout shared with ``init`` via ``_float_bucket_partition``);
        non-float leaves take the exact pmean, matching the non-EF
        path's reference-parity behaviour.

        Two forms, keyed on the communicator's ``two_level_axes``
        capability:

        - flat wire (any communicator): message = grads + residual at
          full param shape; residual mirrors the params tree.
        - TOPOLOGY-AWARE wire (``TwoDimensionalCommunicator``, round 5):
          the intra reduction is exact, so feedback happens at the ONLY
          lossy stage — the int8 wire on the intra-summed shard crossing
          inter/DCN. The residual is shard-shaped per bucket (1/n_intra
          the flat footprint), see
          :func:`chainermn_tpu.parallel.collectives.int8_two_level_allreduce_mean_with_feedback`.
        """
        from chainermn_tpu.parallel.collectives import (
            axes_bound,
            int8_allreduce_mean_with_feedback,
            int8_two_level_allreduce_mean_with_feedback,
        )

        axes = self.communicator.grad_axes
        if not axes_bound(axes):
            return grads, residual  # pjit/eager: identity, residual kept

        axes2 = getattr(self.communicator, "two_level_axes", None)
        leaves, treedef = jax.tree.flatten(grads)
        out: list = [None] * len(leaves)

        # Zero-size float leaves ride the exact per-leaf path with the
        # non-floats: an empty buffer has no max-abs for the int8 scale
        # (and bucket_partition skips them — see its edge contract).
        float_idx = [i for i, g in enumerate(leaves)
                     if jnp.issubdtype(g.dtype, jnp.floating) and g.size > 0]
        for i, g in enumerate(leaves):
            if i not in float_idx:
                out[i] = _pmean_if_in_axis(g, axes).astype(g.dtype)

        sizes = [g.size for g in leaves]
        buckets = _float_bucket_partition(float_idx, sizes,
                                          self._bucket_bytes)

        if axes2 is not None:
            # Shard-level EF: residual is a tuple of per-bucket shard
            # buffers (the layout init allocated).
            intra_ax, inter_ax = axes2
            e_shards = jax.tree.leaves(residual)
            if len(e_shards) != len(buckets):
                raise ValueError(
                    f"shard-level EF residual has {len(e_shards)} "
                    f"buckets but these gradients need {len(buckets)} — "
                    "the opt_state was built for different params "
                    "(restore mismatch?); rebuild it with "
                    "optimizer.init(params) / create_train_state(...)"
                )
            new_shards = []
            for bidx, e_shard in zip(buckets, e_shards):
                m = jnp.concatenate([
                    leaves[i].astype(jnp.float32).ravel() for i in bidx
                ])
                mean, new_shard = int8_two_level_allreduce_mean_with_feedback(
                    m, e_shard, intra_ax, inter_ax
                )
                new_shards.append(new_shard)
                off = 0
                for i in bidx:
                    n = leaves[i].size
                    out[i] = (mean[off:off + n]
                              .reshape(leaves[i].shape)
                              .astype(leaves[i].dtype))
                    off += n
            return jax.tree.unflatten(treedef, out), tuple(new_shards)

        e_leaves = jax.tree.leaves(residual)
        new_e: list = list(e_leaves)
        for bidx in buckets:
            m = jnp.concatenate([
                (leaves[i].astype(jnp.float32)
                 + e_leaves[i].astype(jnp.float32)).ravel()
                for i in bidx
            ])
            mean, local_rt = int8_allreduce_mean_with_feedback(m, axes)
            err = m - local_rt
            off = 0
            for i in bidx:
                n = leaves[i].size
                out[i] = (mean[off:off + n]
                          .reshape(leaves[i].shape)
                          .astype(leaves[i].dtype))
                new_e[i] = (err[off:off + n]
                            .reshape(e_leaves[i].shape)
                            .astype(e_leaves[i].dtype))
                off += n

        return (jax.tree.unflatten(treedef, out),
                jax.tree.unflatten(treedef, new_e))

    def update(self, grads: PyTree, state, params: PyTree | None = None):
        ef_state = None
        reduced = None
        if self.error_feedback:
            ef_state, state = state, state.inner
            reduced, new_residual = self._reduce_with_feedback(
                grads, ef_state.residual
            )
        else:
            schedule = self._effective_schedule(grads)
            if schedule == "zero":
                return self._zero_update(grads, state, params)

        if not self.double_buffering:
            if reduced is None:
                reduced = self._reduce_scheduled(grads, schedule)
            updates, inner = self.actual_optimizer.update(
                reduced, state, params
            )
        else:
            # OVERLAPPED mode (reference staleness-1, made explicit):
            # apply last step's BANKED buckets first, then dispatch this
            # step's reduction — the update has no data path into the
            # same step's collective (certified in tests/test_optimizer
            # .py), so XLA's async scheduler (and, across a scan, step
            # t+1's backward) runs the wire concurrently with compute;
            # with donation (make_train_step's default) the bank buffer
            # is reused in place. Per-bucket wire events carry
            # overlapped=True for trace_report's comm-hidden fraction.
            updates, inner_inner = self.actual_optimizer.update(
                state.communicated_grads, state.inner, params
            )
            if reduced is None:
                reduced = self._reduce_scheduled(grads, schedule)
            inner = _DoubleBufferState(
                inner=inner_inner, communicated_grads=reduced,
                step=state.step + 1,
            )
        if self.error_feedback:
            return updates, _ErrorFeedbackState(
                inner=inner, residual=new_residual
            )
        return updates, inner

    # -- reference-parity conveniences ------------------------------------

    def broadcast(self, params: PyTree, root: int = 0) -> PyTree:
        """The reference's first-update ``bcast_data(model)``, made explicit."""
        return self.communicator.bcast_data(params, root)

    def __getattr__(self, item):
        # Guard against re-entry during unpickling/copy, when __dict__ is
        # not yet populated and 'actual_optimizer' itself is being looked up.
        if item.startswith("__") or "actual_optimizer" not in self.__dict__:
            raise AttributeError(item)
        return getattr(self.actual_optimizer, item)


class _LocalSGDState(NamedTuple):
    inner: Any
    #: replicated step counter driving the sync cadence
    step: jax.Array
    #: params at the last sync — the outer optimizer's reference point
    anchor: PyTree
    #: outer heavy-ball velocity (DiLoCo's outer momentum)
    outer_velocity: PyTree


class LocalSGDOptimizer:
    """Local SGD / DiLoCo-style periodic parameter averaging.

    The per-step allreduce of :class:`MultiNodeOptimizer` is the right
    default on ICI, but on a DCN-dominated topology the gradient wire is
    the bottleneck even at int8 (docs/parallelism.md's scaling model).
    This wrapper removes it entirely: each member applies ``inner``
    updates computed from its LOCAL gradients, and only every
    ``sync_every``-th step do the members communicate — one global
    parameter average, folded through an outer heavy-ball step from the
    last sync's ``anchor`` (``outer_momentum=0, outer_lr=1`` is plain
    FedAvg-style averaging; DiLoCo uses outer momentum ≈0.9).
    Communication volume drops ``sync_every``× with the usual local-SGD
    convergence trade-off.

    TPU shape: the sync is a single ``pmean`` under a ``lax.cond`` whose
    predicate (``step % sync_every == 0``) is replicated — every member
    takes the same branch, so the collective stays matched across the
    mesh. Outside any named-axis context (single device / pjit
    auto-parallel) the mean is the identity and the wrapper degrades to
    exactly ``inner``.

    Beyond the reference: ChainerMN's only communication-reduction
    levers were fp16 compression and double buffering
    (``pure_nccl_communicator.py`` †, ``optimizers.py`` †); periodic
    averaging composes with this package's int8 wire era as the third
    axis (frequency, alongside width and overlap).
    """

    #: see MultiNodeOptimizer: the sync is the periodic parameter mean;
    #: gradients must reach ``inner`` UN-reduced.
    handles_cross_rank_sync = True

    def __init__(self, inner, communicator, *, sync_every: int,
                 outer_lr: float = 1.0, outer_momentum: float = 0.0):
        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        self.inner = inner
        self.comm = communicator
        self.sync_every = sync_every
        self.outer_lr = outer_lr
        self.outer_momentum = outer_momentum

    def init(self, params: PyTree):
        return _LocalSGDState(
            inner=self.inner.init(params),
            step=jnp.zeros((), jnp.int32),
            # A COPY, not the params themselves: a donating train step
            # (make_train_step(donate=True)) would otherwise hand XLA
            # the same buffer twice (params leaf + anchor leaf) and die
            # with 'Attempt to donate the same buffer twice'.
            anchor=jax.tree.map(lambda p: jnp.array(p, copy=True), params),
            outer_velocity=jax.tree.map(jnp.zeros_like, params),
        )

    def update(self, grads: PyTree, state, params: PyTree | None = None):
        if params is None:
            raise ValueError("LocalSGDOptimizer.update requires params")
        iu, inner_state = self.inner.update(grads, state.inner, params)
        candidate = optax.apply_updates(params, iu)
        step = state.step + 1
        do_sync = (step % self.sync_every) == 0
        axes = self.comm.grad_axes

        def sync(_):
            mean_cand = _pmean_if_in_axis(candidate, axes)
            # Outer step from the anchor along the averaged local
            # progress: delta is what the flock moved since last sync.
            delta = jax.tree.map(
                lambda a, c: a - c, state.anchor, mean_cand
            )
            vel = jax.tree.map(
                lambda v, d: self.outer_momentum * v + d,
                state.outer_velocity, delta,
            )
            target = jax.tree.map(
                lambda a, v: a - self.outer_lr * v, state.anchor, vel
            )
            return target, vel, target

        def no_sync(_):
            return candidate, state.outer_velocity, state.anchor

        target, vel, anchor = lax.cond(do_sync, sync, no_sync, None)
        updates = jax.tree.map(lambda t, p: t - p, target, params)
        return updates, _LocalSGDState(
            inner=inner_state, step=step, anchor=anchor,
            outer_velocity=vel,
        )

    def __getattr__(self, item):
        # Same re-entry guard as MultiNodeOptimizer: during unpickling /
        # copy, __dict__ is empty and looking up 'inner' would recurse.
        if item.startswith("__") or "inner" not in self.__dict__:
            raise AttributeError(item)
        return getattr(self.inner, item)


def create_local_sgd(
    inner: optax.GradientTransformation,
    communicator: CommunicatorBase,
    *,
    sync_every: int,
    outer_lr: float = 1.0,
    outer_momentum: float = 0.0,
) -> LocalSGDOptimizer:
    """Factory for :class:`LocalSGDOptimizer` (periodic parameter
    averaging; see the class docstring for semantics and when it beats
    the per-step wire)."""
    return LocalSGDOptimizer(
        inner, communicator, sync_every=sync_every,
        outer_lr=outer_lr, outer_momentum=outer_momentum,
    )


def inner_transform(optimizer) -> optax.GradientTransformation:
    """The plain optax transform a :class:`~chainermn_tpu.parallel.plan.
    ParallelPlan` composes, unwrapped from a communicator-style wrapper.

    A plan owns the whole reduction (its spec providers say which
    collective each axis owes the step), so a
    :class:`MultiNodeOptimizer`'s own wire features cannot ride along:
    the plain inner transform is extracted, and wrappers whose semantics
    live in the wrapper itself (double buffering's staleness bank, the
    EF residual, local-SGD's sync cadence) are refused loudly rather
    than silently dropped. Plain optax transforms pass through.
    """
    if isinstance(optimizer, MultiNodeOptimizer):
        if optimizer.double_buffering or optimizer.error_feedback:
            raise ValueError(
                "a ParallelPlan composes its own reduction; "
                "double_buffering/error_feedback live in the wrapper's "
                "wire and cannot ride a plan-compiled step — pass the "
                "plain inner optimizer"
            )
        if optimizer.compress_dtype is not None:
            raise ValueError(
                "a ParallelPlan reduces in full precision; the wrapper's "
                f"compressed wire (allreduce_grad_dtype="
                f"{jnp.dtype(optimizer.compress_dtype).name}) would be "
                "silently dropped — pass the plain inner optimizer, or "
                "keep this call site on the communicator path"
            )
        return optimizer.actual_optimizer
    if isinstance(optimizer, LocalSGDOptimizer):
        raise ValueError(
            "LocalSGDOptimizer's sync cadence is wrapper state; a "
            "ParallelPlan cannot carry it — pass the plain inner "
            "optimizer"
        )
    return optimizer


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    *,
    double_buffering: bool = False,
    allreduce_grad_dtype=None,
    error_feedback: bool = False,
    reduction_schedule: str | None = None,
) -> MultiNodeOptimizer:
    """Factory mirroring the reference signature
    (``create_multi_node_optimizer(opt, comm, double_buffering)``,
    ``optimizers.py`` (dagger)). ``error_feedback=True`` (with
    ``allreduce_grad_dtype=jnp.int8``) enables EF-SGD over the quantized
    wire: each rank's stage-1 quantization error is carried in the
    optimizer state and added to the next step's message, removing the
    systematic rounding bias (the cumulative applied gradient tracks the
    exact mean to one-step noise instead of drifting linearly).
    ``reduction_schedule`` picks the reduction algorithm
    ('flat'/'two_level'/'zero'/'auto'; see
    :class:`MultiNodeOptimizer` and docs/parallelism.md)."""
    return MultiNodeOptimizer(
        actual_optimizer,
        communicator,
        double_buffering=double_buffering,
        compress_dtype=allreduce_grad_dtype,
        error_feedback=error_feedback,
        reduction_schedule=reduction_schedule,
    )


__all__ = [
    "LocalSGDOptimizer",
    "MultiNodeOptimizer",
    "allreduce_gradients",
    "allreduce_grads_transform",
    "create_local_sgd",
    "create_multi_node_optimizer",
    "inner_transform",
]
