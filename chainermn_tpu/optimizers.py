"""Multi-node optimizer wrappers.

Reference: ``chainermn/optimizers.py`` (dagger) (SURVEY.md sections 2.3, 3.2):
``create_multi_node_optimizer(opt, comm, double_buffering=False)`` wraps any
Chainer optimizer so that ``update()`` broadcasts weights on the first
iteration and allreduces gradients on every iteration;
``_DoubleBufferingOptimizer`` overlaps the allreduce with backward on a side
CUDA stream at the cost of one step of gradient staleness.

TPU-native design: the wrapped object is an :class:`optax.GradientTransformation`
meant to be used *inside the jitted train step*. ``allreduce_grad`` is a
``lax.pmean`` over the communicator's mesh axes — XLA fuses the reference's
pack / fp16-cast / ncclAllReduce / scale / unpack pipeline
(``pure_nccl_communicator.py`` (dagger)) into its collective schedule, and its
latency-hiding scheduler overlaps the collective with remaining backward
computation, which is what double buffering bought on GPU. The
``double_buffering=True`` flag is still honoured with *faithful semantics*
(updates apply the previous step's reduced gradients, staleness 1) so
convergence behaviour matches the reference feature; on TPU it additionally
lets XLA start the psum of step *t* while step *t*'s weights update with
*t-1*'s gradients.

Weight broadcast on first iteration: in the functional JAX world parameters
are created once and replicated by :meth:`CommunicatorBase.bcast_data`; call
``optimizer.broadcast(params)`` (or rely on identical PRNG keys) instead of a
hidden first-update hook.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any


def _pmean_if_in_axis(tree: PyTree, axis_names) -> PyTree:
    """pmean over ``axis_names`` when tracing inside that named-axis context
    (shard_map/pmap); identity otherwise (pjit auto-parallel mode, where XLA
    inserts the reduction from sharding propagation, or single-device)."""
    from chainermn_tpu.parallel.collectives import axes_bound

    if not axes_bound(axis_names):
        return tree
    return lax.pmean(tree, axis_names)


def allreduce_gradients(
    grads: PyTree,
    comm: Optional[CommunicatorBase] = None,
    *,
    axis_names=None,
    compress_dtype=None,
) -> PyTree:
    """In-jit gradient averaging — the hot collective of the framework.

    With ``compress_dtype`` (e.g. ``jnp.bfloat16``) gradients are cast before
    the collective and restored after: the reference's
    ``allreduce_grad_dtype='float16'`` compressed allreduce
    (``pure_nccl_communicator.py`` (dagger), shu65's v1.3 feature) — halves
    bytes on ICI/DCN; master accumulation stays f32.

    ``compress_dtype=jnp.int8`` selects the QUANTIZED wire (beyond the
    reference): max-abs-scaled int8 over a two-phase
    all_to_all/all_gather scheme
    (:func:`chainermn_tpu.parallel.collectives.int8_allreduce_mean`) —
    ~2 bytes/element on the wire vs bf16's 4, at ~1/127-relative
    rounding noise per stage. Outside a named-axis context int8 is an
    identity (no pointless quantization round-trip).
    """
    if axis_names is None:
        if comm is None:
            raise ValueError("pass a communicator or axis_names")
        # Strategy dispatch: the communicator owns its in-jit reduction
        # algorithm (base: fused pmean; two_dimensional: explicit
        # reduce-scatter -> inter-allreduce -> all-gather).
        return comm.reduce_gradients_in_jit(grads, compress_dtype=compress_dtype)

    int8_wire = (compress_dtype is not None
                 and jnp.dtype(compress_dtype) == jnp.dtype(jnp.int8))

    def reduce_leaf(g):
        if int8_wire and jnp.issubdtype(g.dtype, jnp.floating):
            from chainermn_tpu.parallel.collectives import (
                axes_bound,
                int8_allreduce_mean,
            )

            if not axes_bound(axis_names):
                return g
            return int8_allreduce_mean(g, axis_names)
        if compress_dtype is not None and not int8_wire and jnp.issubdtype(
            g.dtype, jnp.floating
        ):
            return _pmean_if_in_axis(g.astype(compress_dtype), axis_names).astype(
                g.dtype
            )
        # pmean promotes integer leaves to float; keep the leaf dtype
        # (reference parity: allreduce_grad returned grads in-place/dtype).
        return _pmean_if_in_axis(g, axis_names).astype(g.dtype)

    return jax.tree.map(reduce_leaf, grads)


def allreduce_grads_transform(
    comm: CommunicatorBase, *, compress_dtype=None
) -> optax.GradientTransformation:
    """Standalone optax transform performing the gradient allreduce; compose
    it manually as ``optax.chain(allreduce_grads_transform(comm), inner)`` if
    you don't want the full wrapper."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        return (
            allreduce_gradients(updates, comm, compress_dtype=compress_dtype),
            state,
        )

    return optax.GradientTransformation(init_fn, update_fn)


class _DoubleBufferState(NamedTuple):
    inner: Any
    #: gradients reduced at step t-1, applied at step t (staleness 1)
    communicated_grads: PyTree
    step: jax.Array


class MultiNodeOptimizer:
    """optax-compatible wrapper: ``init``/``update`` plus communicator-aware
    gradient reduction. Duck-types :class:`optax.GradientTransformation`.

    Reference behaviours preserved (``optimizers.py`` (dagger)):
      - every update averages gradients across all ranks before applying;
      - ``double_buffering=True`` applies the *previous* iteration's averaged
        gradients (staleness-1) — tested for exactly that semantic;
      - attribute delegation: unknown attributes forward to the wrapped
        optimizer (the reference delegated via ``__getattr__``).
    """

    def __init__(
        self,
        actual_optimizer: optax.GradientTransformation,
        communicator: CommunicatorBase,
        *,
        double_buffering: bool = False,
        compress_dtype=None,
    ) -> None:
        self.actual_optimizer = actual_optimizer
        self.communicator = communicator
        self.double_buffering = double_buffering
        self.compress_dtype = (
            compress_dtype
            if compress_dtype is not None
            else communicator.allreduce_grad_dtype
        )

    # -- optax protocol ----------------------------------------------------

    def init(self, params: PyTree):
        inner = self.actual_optimizer.init(params)
        if not self.double_buffering:
            return inner
        zeros = jax.tree.map(jnp.zeros_like, params)
        return _DoubleBufferState(
            inner=inner, communicated_grads=zeros, step=jnp.zeros((), jnp.int32)
        )

    def update(self, grads: PyTree, state, params: PyTree | None = None):
        reduced = allreduce_gradients(
            grads, self.communicator, compress_dtype=self.compress_dtype
        )
        if not self.double_buffering:
            return self.actual_optimizer.update(reduced, state, params)

        # Apply last step's reduced grads; bank this step's. XLA is free to
        # overlap the psum producing `reduced` with the inner-optimizer math
        # consuming `state.communicated_grads` — the dependency graph is
        # exactly the reference's two-buffer/side-stream overlap.
        updates, inner = self.actual_optimizer.update(
            state.communicated_grads, state.inner, params
        )
        new_state = _DoubleBufferState(
            inner=inner, communicated_grads=reduced, step=state.step + 1
        )
        return updates, new_state

    # -- reference-parity conveniences ------------------------------------

    def broadcast(self, params: PyTree, root: int = 0) -> PyTree:
        """The reference's first-update ``bcast_data(model)``, made explicit."""
        return self.communicator.bcast_data(params, root)

    def __getattr__(self, item):
        # Guard against re-entry during unpickling/copy, when __dict__ is
        # not yet populated and 'actual_optimizer' itself is being looked up.
        if item.startswith("__") or "actual_optimizer" not in self.__dict__:
            raise AttributeError(item)
        return getattr(self.actual_optimizer, item)


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    *,
    double_buffering: bool = False,
    allreduce_grad_dtype=None,
) -> MultiNodeOptimizer:
    """Factory mirroring the reference signature
    (``create_multi_node_optimizer(opt, comm, double_buffering)``,
    ``optimizers.py`` (dagger))."""
    return MultiNodeOptimizer(
        actual_optimizer,
        communicator,
        double_buffering=double_buffering,
        compress_dtype=allreduce_grad_dtype,
    )


__all__ = [
    "MultiNodeOptimizer",
    "allreduce_gradients",
    "allreduce_grads_transform",
    "create_multi_node_optimizer",
]
