"""Persistent autotune cache + offline seeding.

Deliberately jax-free: the cache is plain JSON so the bench parent
process (which never imports jax — bench.py's robustness contract) and
the ``python -m chainermn_tpu.tuning`` CLI can read/seed it cheaply.

File format (``.autotune_cache.json``)::

    {"version": 1,
     "decisions": {
       "moe_dispatch|TPU v5 lite|16384x16x512|bfloat16": {
         "winner": "sort",
         "source": "seeded:BENCH_DETAILS.json",
         "candidates_ms": {"einsum": 11.362, "sort": 6.981},
         "spread_pct": 0.0,
         "measured_at": "2026-08-01T08:46:00Z"}}}

Keys are ``name|decision_key`` (see :func:`registry.decision_key`).
Every entry carries its evidence (``candidates_ms`` or a free-form
``evidence``) and provenance (``source`` + ``measured_at``) — a cache
the next session can audit, not just obey.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

CACHE_ENV = "CHAINERMN_TPU_AUTOTUNE_CACHE"
VERSION = 1

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_LOCK = threading.Lock()


def default_cache_path() -> str:
    """Cache file path: ``CHAINERMN_TPU_AUTOTUNE_CACHE`` or
    ``<repo>/.autotune_cache.json``."""
    return os.environ.get(CACHE_ENV) or os.path.join(
        _REPO_ROOT, ".autotune_cache.json"
    )


#: path -> (mtime_ns, size, parsed doc) — choice() resolves on every
#: auto-dispatched library call, so repeated full read+parse of the
#: JSON would be per-call I/O; one stat per lookup keeps cross-process
#: freshness (a bench child rewriting the file bumps the mtime).
_LOAD_MEMO: dict = {}


def load_cache(path: str | None = None) -> dict:
    """Load the cache document (mtime-memoized); a missing or corrupt
    file is an empty cache, never an error (the cache is an
    accelerator, not a dependency)."""
    path = path or default_cache_path()
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        _LOAD_MEMO.pop(path, None)
        return {"version": VERSION, "decisions": {}}
    memo = _LOAD_MEMO.get(path)
    if memo is not None and memo[0] == stamp:
        return memo[1]
    try:
        with open(path) as f:
            doc = json.load(f)
        if not (isinstance(doc, dict)
                and isinstance(doc.get("decisions"), dict)):
            doc = {"version": VERSION, "decisions": {}}
    except (OSError, json.JSONDecodeError):
        doc = {"version": VERSION, "decisions": {}}
    _LOAD_MEMO[path] = (stamp, doc)
    return doc


def lookup_entry(name: str, key: str, path: str | None = None):
    """The cached entry for ``name|key``, or None."""
    entry = load_cache(path)["decisions"].get(f"{name}|{key}")
    return entry if isinstance(entry, dict) else None


def store_entry(
    name: str, key: str, entry: dict, path: str | None = None
) -> bool:
    """Read-modify-write one decision entry. Best-effort: an unwritable
    location (read-only checkout, scrubbed env) loses the persistence,
    never the decision. Returns whether the write landed."""
    path = path or default_cache_path()
    with _LOCK:
        doc = load_cache(path)
        # copy before mutating: load_cache memoizes the parsed doc and
        # hands the same object to concurrent readers
        doc = {**doc, "decisions": dict(doc["decisions"])}
        doc["version"] = VERSION
        entry = dict(entry)
        entry.setdefault(
            "measured_at", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        )
        doc["decisions"][f"{name}|{key}"] = entry
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
            return True
        except OSError:
            return False


# ---------------------------------------------------------------------------
# Offline seeding from bench artifacts
# ---------------------------------------------------------------------------

_MOE_SHAPE = re.compile(r"T(\d+)xE(\d+)xD(\d+)")
_ATTN_SHAPE = re.compile(r"B(\d+)xT(\d+)xH(\d+)xD(\d+)_(\w+?)_")
_SERVING_SHAPE = re.compile(r"D(\d+)xH(\d+)xL(\d+)")
_SEQATTN_SHAPE = re.compile(r"S(\d+)xH(\d+)xT(\d+)")


def _bucketed_key(device_kind: str, dims, dtype_name: str) -> str:
    # The ONE key builder (registry.decision_key), imported lazily to
    # break the module cycle (registry imports this module at top).
    # With an explicit device_kind and a string dtype the registry path
    # is jax-free, so seeding stays usable without a backend.
    from chainermn_tpu.tuning.registry import decision_key

    return decision_key(device_kind, shape=[int(d) for d in dims],
                        dtype=dtype_name)


def _seed_one_result(result: dict, source: str, out: list,
                     path: str | None) -> None:
    kind = result.get("device_kind")
    if not kind:
        return
    stamp = result.get("measured_at")

    def put(name, key, winner, evidence):
        entry = {"winner": winner, "source": source, **evidence}
        if stamp:
            entry["measured_at"] = stamp
        if store_entry(name, key, entry, path):
            out.append(f"{name}|{key} -> {winner}")

    # MoE dispatch: einsum vs sort medians at the measured shape.
    m = _MOE_SHAPE.search(result.get("moe_dispatch_shape", ""))
    e_ms = result.get("moe_dispatch_einsum_ms")
    s_ms = result.get("moe_dispatch_sort_ms")
    if m and e_ms and s_ms:
        key = _bucketed_key(kind, m.groups(), "bfloat16")
        put("moe_dispatch", key,
            "sort" if s_ms <= e_ms else "einsum",
            {"candidates_ms": {"einsum": e_ms, "sort": s_ms},
             "spread_pct": result.get("moe_dispatch_spread_pct", 0.0)})

    # Expert axis (ISSUE 20): the bench ``moe`` phase's expert-plan vs
    # replicated-experts step pair, spread-gated like the LIVE adoption
    # path (record_measurement) — and under the SAME key derivation
    # (shape=(T, E, D), dtype float32), so offline seed and in-run
    # adoption land on one cache entry.
    m = _MOE_SHAPE.search(result.get("moe_plan_shape", ""))
    on_ms = result.get("moe_step_ms")
    off_ms = result.get("moe_off_step_ms")
    if m and on_ms and off_ms:
        from chainermn_tpu.tuning.measure import decide

        # absent spread = single-sample on-chip row: the 10% noise
        # floor record_measurement would apply
        spread = float(result.get("moe_spread_pct", 10.0))
        pair = {"on": float(on_ms), "off": float(off_ms)}
        winner = decide(pair, {k: spread for k in pair})
        if winner is not None:
            key = _bucketed_key(kind, m.groups(), "float32")
            put("expert_parallel", key, winner,
                {"candidates_ms": pair, "spread_pct": spread})

    # Attention variant: fwd+bwd medians (the training-relevant row).
    m = _ATTN_SHAPE.search(result.get("attn_shape", ""))
    f_ms = result.get("flash_fwdbwd_ms")
    x_ms = result.get("xla_fwdbwd_ms")
    if m and f_ms and x_ms:
        _, t, h, d, dt = m.groups()
        # normalise to numpy dtype names — the spelling runtime keys use
        dt = {"bf16": "bfloat16", "f32": "float32",
              "f16": "float16"}.get(dt, dt)
        key = _bucketed_key(kind, (t, h, d), dt)
        put("attention", key,
            "flash" if f_ms <= x_ms else "xla",
            {"candidates_ms": {"flash": f_ms, "xla": x_ms},
             "spread_pct": result.get("attn_proxy_spread_pct", 0.0)})

    # Allreduce wire: best busbw mode among the curve's rows. Only on a
    # REAL multi-member axis — at n=1 there is no wire, and the dtype
    # "comparison" would just adopt loopback memory-bandwidth noise.
    curve = result.get("allreduce_curve")
    n = result.get("n_devices", 1)
    if isinstance(curve, list) and n > 1:
        best: dict[str, float] = {}
        for row in curve:
            if not isinstance(row, dict) or "busbw_gbps" not in row:
                continue
            wire = ("int8" if row.get("mode") == "int8"
                    else {"bfloat16": "bf16", "float32": "f32"}.get(
                        row.get("dtype")))
            if wire:
                best[wire] = max(best.get(wire, 0.0), row["busbw_gbps"])
        if best:
            key = _bucketed_key(kind, (n,), "grad")
            put("allreduce_wire", key,
                max(best, key=best.get),
                {"busbw_gbps": best})
    if isinstance(curve, list):
        # Bucket size: the ~64 MB packing discipline is adopted unless
        # the curve shows the fused single buffer decisively faster.
        # Only rows big enough to actually CARRY >= 64 MiB buckets count
        # — the CPU proxy's shrunken-bucket rows measure per-collective
        # latency at micro sizes, not the packing discipline.
        by_mode = {
            row.get("mode"): row["busbw_gbps"]
            for row in curve
            if isinstance(row, dict) and "busbw_gbps" in row
            and row.get("dtype") == "bfloat16"
            and row.get("mib", 0) >= 64
        }
        if "fused" in by_mode and "bucketed" in by_mode:
            key = _bucketed_key(kind, (n,), "grad")
            put("allreduce_bucket_mb", key,
                "64" if by_mode["bucketed"] >= 0.9 * by_mode["fused"]
                else "none",
                {"busbw_gbps": by_mode})

    # Reduction schedule: the overlap phase's per-schedule step-time
    # medians (ISSUE 3 — bench's ``overlap`` rows, carried TPU blob
    # included, become the 'auto' schedule's evidence). The key must
    # reproduce resolve_schedule's exactly: world-shape + payload-MB
    # bucket, dtype tag 'sched' — bench records both alongside the rows.
    sched_ms = result.get("overlap_schedule_ms")
    if isinstance(sched_ms, dict) and len(sched_ms) >= 2 and all(
        isinstance(v, (int, float)) for v in sched_ms.values()
    ):
        # Spread-gated like the LIVE adoption path (measure.decide): a
        # schedule "winner" inside the run's own noise band must not be
        # pinned into the cache — the in-run record_measurement refused
        # it, and the offline seeder must not resurrect it.
        from chainermn_tpu.tuning.measure import decide

        spread = float(result.get("overlap_schedule_spread_pct", 0.0))
        winner = decide(sched_ms, {k: spread for k in sched_ms})
        if winner is not None:
            world = result.get("overlap_world_shape") or [
                result.get("n_devices", 1)
            ]
            payload_mb = result.get("overlap_payload_mb", 1)
            key = _bucketed_key(
                kind, tuple(world) + (payload_mb,), "sched"
            )
            put("reduction_schedule", key, winner,
                {"candidates_ms": {k: round(float(v), 4)
                                   for k, v in sched_ms.items()},
                 "spread_pct": spread})

    # Composed schedules (ISSUE 12): bench's ``composed`` phase sweeps
    # the DERIVED composition list on the multi-level factoring of the
    # mesh (rows keyed by composition signature string) — same decision
    # name, its own world-shape key (e.g. (2,2,2) vs the flat (8,)), so
    # the flat-mesh 'overlap' entry and the 3-level one coexist. Spread-
    # gated through measure.decide like every adoption.
    comp_ms = result.get("composed_schedule_ms")
    if isinstance(comp_ms, dict) and len(comp_ms) >= 2 and all(
        isinstance(v, (int, float)) for v in comp_ms.values()
    ):
        from chainermn_tpu.parallel.composition import (
            normalize_schedule_name,
        )
        from chainermn_tpu.tuning.measure import decide

        n_axes = len(result.get("composed_world_shape") or (1, 1, 1))
        # The registry's candidate spelling: menu-instance signatures
        # (the derived flat/two_level) adopt by MENU NAME — a signature
        # winner the candidate list excludes would be silently
        # discarded at choice() time and the table default would win.
        comp_ms = {normalize_schedule_name(k, n_axes): v
                   for k, v in comp_ms.items()}
        spread = float(result.get("composed_spread_pct", 0.0))
        winner = decide(comp_ms, {k: spread for k in comp_ms})
        if winner is not None:
            world = result.get("composed_world_shape") or [
                result.get("n_devices", 1)
            ]
            payload_mb = result.get("composed_payload_mb", 1)
            key = _bucketed_key(
                kind, tuple(world) + (payload_mb,), "sched"
            )
            put("reduction_schedule", key, winner,
                {"candidates_ms": {k: round(float(v), 4)
                                   for k, v in comp_ms.items()},
                 "spread_pct": spread})

    # Bucket-slice count (ISSUE 15): bench's ``composed`` sliced arms
    # time the hierarchical pipeline at comp_slices ∈ {1,2,4,8} — rows
    # keyed by slice count, adopted under the SAME world-shape x
    # payload-MB key resolve_comp_slices reads (dtype tag 'slices').
    # Spread-gated through measure.decide exactly like the live
    # record_measurement adoption, so offline seed and in-run adoption
    # agree on identical rows (the PR 14 adapter_impl lesson).
    sl_ms = result.get("composed_sliced_ms")
    if isinstance(sl_ms, dict) and len(sl_ms) >= 2 and all(
        isinstance(v, (int, float)) for v in sl_ms.values()
    ):
        from chainermn_tpu.tuning.measure import decide

        if "composed_sliced_spread_pct" in result:
            spread = float(result["composed_sliced_spread_pct"])
        else:
            spread = 10.0  # on-accel single sample: the noise floor
        winner = decide(sl_ms, {k: spread for k in sl_ms})
        if winner is not None:
            world = result.get("composed_world_shape") or [
                result.get("n_devices", 1)
            ]
            payload_mb = result.get("composed_payload_mb", 1)
            key = _bucketed_key(
                kind, tuple(world) + (payload_mb,), "slices"
            )
            put("comp_slices", key, str(winner),
                {"candidates_ms": {k: round(float(v), 4)
                                   for k, v in sl_ms.items()},
                 "spread_pct": spread})

    # Cost-model schedule search (ISSUE 16): the composed phase now
    # ranks arms with the fitted α–β model and measures only top-k; the
    # predicted-vs-measured max error over the arms it DID time is the
    # model audit. Seed the sched_search decision from that audit:
    # error inside the measurement spread keeps the ranked top-k path,
    # disagreement past the gate seeds 'exhaustive' so the next run
    # restores full coverage — loud provenance either way, and the
    # predicted rows ride along as evidence (never trusted blind).
    cm_err = result.get("cost_model_err_pct")
    if isinstance(cm_err, (int, float)) and result.get(
            "sched_search_selected"):
        spread = float(result.get("composed_spread_pct", 0.0)) or 10.0
        world = result.get("composed_world_shape") or [
            result.get("n_devices", 1)
        ]
        payload_mb = result.get("composed_payload_mb", 1)
        key = _bucketed_key(
            kind, tuple(world) + (payload_mb,), "search"
        )
        winner = "topk" if float(cm_err) <= spread else "exhaustive"
        evidence: dict = {
            "cost_model_err_pct": round(float(cm_err), 3),
            "spread_pct": spread,
            "selected": str(result["sched_search_selected"]),
        }
        pred = result.get("sched_search_predicted_ms")
        if isinstance(pred, dict):
            evidence["predicted_ms"] = {
                k: round(float(v), 4) for k, v in pred.items()
                if isinstance(v, (int, float))
            }
        skipped = result.get("sched_search_skipped")
        if isinstance(skipped, (list, tuple)):
            evidence["skipped"] = [str(s) for s in skipped]
        put("sched_search", key, winner, evidence)

    # Sequence-axis attention impl (ISSUE 13): bench's ``seq_parallel``
    # phase times the ONE plan-compiled step per candidate (ring's n-1
    # ppermutes/layer vs Ulysses' all_to_all reshard), keyed
    # shards x heads x LOCAL-T — the same key
    # ParallelPlan.seq_attention resolves under. Spread-gated like
    # every adoption.
    m_sa = _SEQATTN_SHAPE.search(result.get("seq_parallel_attn_shape", ""))
    sa_ms = result.get("seq_parallel_attn_ms")
    if m_sa and isinstance(sa_ms, dict) and len(sa_ms) >= 2 and all(
        isinstance(v, (int, float)) for v in sa_ms.values()
    ):
        from chainermn_tpu.tuning.measure import decide

        if "seq_parallel_attn_spread_pct" in result:
            spread = float(result["seq_parallel_attn_spread_pct"])
        else:
            spread = 10.0  # on-accel single sample: the noise floor
        winner = decide(sa_ms, {k: spread for k in sa_ms})
        if winner is not None:
            key = _bucketed_key(kind, m_sa.groups(), "seqattn")
            put("seq_attn_impl", key, winner,
                {"candidates_ms": {k: round(float(v), 4)
                                   for k, v in sa_ms.items()},
                 "spread_pct": spread})

    # Serving decode decisions (ISSUE 4/5/7): bench's ``serving`` and
    # ``serving_prefix`` phases record per-candidate medians keyed by
    # the engine's own decision key material (``serving_model_shape``
    # D..xH..xL..) — decode impl, paged block size, the speculative
    # length K (``serving_spec_ms``: ms per GENERATED token per K, so
    # the acceptance rate is priced in), the prefix cache on/off
    # (``serving_prefix_ttft_ms``: median TTFT under duplicate-prefix
    # load — the metric sharing exists to move) and its adoption
    # threshold (``serving_prefix_msb_ttft_ms``). All adoptions are
    # spread-gated through measure.decide, same as the overlap schedule
    # rows above.
    m = _SERVING_SHAPE.search(result.get("serving_model_shape", ""))
    # The prefix rows carry their OWN shape key: the two phases share a
    # model today, but last-writer-wins on one merged key would silently
    # re-key the other phase's decisions if either shape ever diverges.
    m_px = (_SERVING_SHAPE.search(
        result.get("serving_prefix_model_shape", "")) or m)
    m_cl = (_SERVING_SHAPE.search(
        result.get("serving_cluster_model_shape", "")) or m)
    m_bu = (_SERVING_SHAPE.search(
        result.get("serving_burst_model_shape", "")) or m)
    m_sp = (_SERVING_SHAPE.search(
        result.get("seq_parallel_model_shape", "")) or m)
    m_te = (_SERVING_SHAPE.search(
        result.get("serving_tenants_model_shape", "")) or m)
    m_dk = (_SERVING_SHAPE.search(
        result.get("serving_decode_kernel_model_shape", "")) or m)
    if m or m_px or m_cl or m_bu or m_sp or m_te or m_dk:
        from chainermn_tpu.tuning.measure import decide

        for row_key, spread_key, name in (
            ("serving_decode_impl_ms", "serving_decode_spread_pct",
             "decode_impl"),
            ("serving_kv_block_ms", "serving_kv_block_spread_pct",
             "kv_block_size"),
            ("serving_spec_ms", "serving_spec_spread_pct",
             "spec_tokens"),
            ("serving_prefix_ttft_ms", "serving_prefix_spread_pct",
             "prefix_cache"),
            ("serving_prefix_msb_ttft_ms",
             "serving_prefix_msb_spread_pct", "min_shared_blocks"),
            ("serving_cluster_disagg_ttft_ms",
             "serving_cluster_disagg_spread_pct", "cluster_disagg"),
            ("serving_burst_chunk_ms",
             "serving_burst_spread_pct", "prefill_chunk"),
            ("seq_parallel_ttft_ms",
             "seq_parallel_spread_pct", "prefill_seq_parallel"),
            ("serving_tenants_adapter_ms",
             "serving_tenants_adapter_spread_pct", "adapter_impl"),
            ("serving_decode_kernel_ms",
             "serving_decode_kernel_spread_pct", "decode_attend_impl"),
        ):
            rows = result.get(row_key)
            if not (isinstance(rows, dict) and len(rows) >= 2 and all(
                isinstance(v, (int, float)) for v in rows.values()
            )):
                continue
            # A PRESENT spread key is a real multi-sample estimate and
            # is used verbatim (0.0 = genuinely tied medians adopts,
            # matching the in-run path); an ABSENT key marks an
            # on-accel single-sample row, which takes the same 10%
            # noise floor the live adoption applies (spreads=None in
            # registry.record_measurement) — neither path can pin a
            # margin the other would have refused.
            if spread_key in result:
                spread = float(result[spread_key])
            else:
                spread = 10.0
            winner = decide(rows, {k: spread for k in rows})
            if winner is not None:
                if name in ("prefix_cache", "min_shared_blocks"):
                    m_row = m_px
                elif name == "cluster_disagg":
                    m_row = m_cl
                elif name == "prefill_chunk":
                    m_row = m_bu
                elif name == "prefill_seq_parallel":
                    m_row = m_sp
                elif name == "adapter_impl":
                    m_row = m_te
                elif name == "decode_attend_impl":
                    m_row = m_dk
                else:
                    m_row = m
                if m_row is None:
                    continue
                key = _bucketed_key(kind, m_row.groups(), "decode")
                evidence = {"candidates_ms": {k: round(float(v), 4)
                                              for k, v in rows.items()},
                            "spread_pct": spread}
                if name == "spec_tokens":
                    # acceptance rate rides as evidence: a cache entry
                    # the next session can audit for WHY K won (high
                    # accept rate) or lost (drafts were junk).
                    rates = result.get("serving_spec_accept_rates")
                    if isinstance(rates, dict):
                        evidence["accept_rates"] = rates
                if name == "prefix_cache":
                    # the hit rate behind the TTFT comparison: 'on'
                    # winning at 0% hits would be noise, not sharing.
                    hr = result.get("serving_prefix_hit_rate")
                    if hr is not None:
                        evidence["hit_rate"] = hr
                if name == "cluster_disagg":
                    # the handoff's measured wire cost + the replica
                    # scaling behind it — a 'disaggregated' entry the
                    # next session can audit.
                    for ev_key, row in (
                        ("transfers", "serving_cluster_transfers"),
                        ("transfer_bytes",
                         "serving_cluster_transfer_bytes"),
                        ("scaling", "serving_cluster_scaling"),
                    ):
                        v = result.get(row)
                        if v is not None:
                            evidence[ev_key] = v
                if name == "prefill_chunk":
                    # the bursty goodput-under-SLO and p99 TTFT behind
                    # the ms ranking — WHY chunking won (or lost) on
                    # this shape, auditable next session.
                    for ev_key, row in (
                        ("goodput", "serving_burst_goodput"),
                        ("ttft_p99_ms", "serving_burst_ttft_p99_ms"),
                    ):
                        v = result.get(row)
                        if v is not None:
                            evidence[ev_key] = v
                if name == "prefill_seq_parallel":
                    # the per-shard-count TTFT curve behind the off/on
                    # ranking (ISSUE 13) — auditable evidence for the
                    # wide-prefill adoption.
                    v = result.get("seq_parallel_ttft_shards_ms")
                    if v is not None:
                        evidence["ttft_shards_ms"] = v
                if name == "decode_attend_impl":
                    # the kernel-vs-gather speedup behind the ranking
                    # (ISSUE 19) — on a CPU proxy the fused arm timed
                    # the interpret-mode EMULATOR, so an 'xla' entry
                    # here is expected and only an on-chip row should
                    # ever seed 'fused'.
                    v = result.get("serving_decode_kernel_fused_speedup")
                    if v is not None:
                        evidence["fused_speedup"] = v
                if name == "adapter_impl":
                    # the multi-tenant goodput + fairness behind the
                    # gather/merged ranking (ISSUE 14) — a 'merged'
                    # entry the next session can audit for WHY the
                    # fold won (single-tenant-dominant traffic).
                    for ev_key, row in (
                        ("goodput", "serving_tenants_goodput"),
                        ("fairness", "serving_tenants_fairness"),
                    ):
                        v = result.get(row)
                        if v is not None:
                            evidence[ev_key] = v
                put(name, key, winner, evidence)

    # Double buffering: the measured on/off step-time ratio.
    speedup = result.get("double_buffer_speedup")
    if speedup:
        n = result.get("n_devices", 1)
        key = _bucketed_key(kind, (n,), "step")
        put("double_buffering", key,
            "on" if speedup > 1.02 else "off",
            {"double_buffer_speedup": speedup,
             "spread_pct": result.get("double_buffer_spread_pct", 0.0)})


def seed_from_bench_details(
    details_path: str | None = None, cache_path: str | None = None
) -> list[str]:
    """Seed the cache from a bench artifact (``BENCH_DETAILS.json`` by
    default, or the carried ``.bench_last_tpu.json`` blob directly).

    Seeds decisions from the artifact's top level (whatever backend that
    run measured — often the CPU proxy) AND from its ``last_good_tpu``
    carried blob, each under its own ``device_kind``, so on-chip sweep
    winners are adopted for the chip without re-measuring while the CPU
    entries keep describing the CPU. Returns the list of seeded
    ``name|key -> winner`` strings."""
    details_path = details_path or os.path.join(
        _REPO_ROOT, "BENCH_DETAILS.json"
    )
    with open(details_path) as f:
        result = json.load(f)
    seeded: list[str] = []
    _seed_one_result(result, f"seeded:{os.path.basename(details_path)}",
                     seeded, cache_path)
    carried = result.get("last_good_tpu")
    if isinstance(carried, dict):
        _seed_one_result(
            carried,
            f"seeded:{os.path.basename(details_path)}:last_good_tpu",
            seeded, cache_path,
        )
    return seeded
