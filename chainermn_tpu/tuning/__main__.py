"""CLI for the autotune cache.

- ``python -m chainermn_tpu.tuning seed [DETAILS.json]`` — seed the
  persistent cache offline from a bench artifact (default:
  ``BENCH_DETAILS.json``; the ``last_good_tpu`` carried blob inside it
  is seeded too, under its own device kind) — on-chip sweep winners get
  adopted without re-measuring.
- ``python -m chainermn_tpu.tuning show`` — print the cache.

Both are jax-free (cache + seeding are plain JSON).
"""

from __future__ import annotations

import json
import sys

from chainermn_tpu.tuning.cache import (
    default_cache_path,
    load_cache,
    seed_from_bench_details,
)


def main(argv: list[str]) -> int:
    cmd = argv[0] if argv else "show"
    if cmd == "seed":
        details = argv[1] if len(argv) > 1 else None
        seeded = seed_from_bench_details(details)
        for line in seeded:
            print(f"seeded {line}")
        print(f"{len(seeded)} decisions -> {default_cache_path()}")
        return 0
    if cmd == "show":
        print(json.dumps(load_cache(), indent=1, sort_keys=True))
        return 0
    print(f"usage: python -m chainermn_tpu.tuning [seed [DETAILS]|show]; "
          f"got {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
