"""Device-aware dispatch + persistent autotune cache.

The codebase used to hard-code path choices that INVERT across backends
(round-5 review): sort-based MoE dispatch is 167.8x the einsum path on
the CPU proxy but only 1.63x on TPU v5e at the production shape; the
flash kernel is 3.0x XLA attention on the chip but 0.56x under CPU
interpret mode; double buffering measures 0.752x on the proxy. A static
flag cannot be right on both backends — collective-algorithm and kernel
choice must be composed per device/topology (HiCCL, arxiv 2408.05962;
cross-replica update sharding, arxiv 2004.13336), so this package gives
every such choice one mechanism:

- :func:`choice` — the decision registry. A call site names its decision
  (``"moe_dispatch"``), its candidates, and a key built by
  :func:`decision_key` from ``(device_kind, shape-bucket, dtype)``;
  resolution order is forced-override -> persistent cache -> one-shot
  measurement (when callables are supplied and tracing is not active)
  -> deterministic per-device table.
- :mod:`~chainermn_tpu.tuning.measure` — the one-shot autotuner, using
  bench.py's median-of-n>=3 + spread discipline; a spread-dominated
  comparison falls back to the table instead of adopting noise.
- :mod:`~chainermn_tpu.tuning.cache` — the persistent JSON cache
  (``.autotune_cache.json``), seedable OFFLINE from
  ``BENCH_DETAILS.json`` / the carried TPU blob
  (``python -m chainermn_tpu.tuning seed``) so on-chip sweep winners
  are adopted without re-measuring.

Call sites wired through the registry: MoE sort-vs-einsum dispatch
(:mod:`chainermn_tpu.parallel.moe`), attention variant selection
(:func:`chainermn_tpu.ops.attention.attention`), the allreduce wire
variant + bucket size (:mod:`chainermn_tpu.communicators`,
:mod:`chainermn_tpu.parallel.collectives`), and the double-buffering
advisory (:mod:`chainermn_tpu.optimizers`). ``bench.py`` and
``__graft_entry__.dryrun_multichip`` report which decision each site
took, so every capture shows its dispatch provenance.

Env knobs (documented in docs/benchmarks.md):

- ``CHAINERMN_TPU_AUTOTUNE`` — ``auto`` (default: cache, then measure
  when possible, then table), ``measure`` (same), ``table`` (never
  measure), ``off`` (ignore the cache too; pure table).
- ``CHAINERMN_TPU_AUTOTUNE_CACHE`` — cache file path (default:
  ``<repo>/.autotune_cache.json``).
- ``CHAINERMN_TPU_AUTOTUNE_FORCE`` — comma-separated hard overrides,
  e.g. ``moe_dispatch=einsum,attention=xla``.
"""

from chainermn_tpu.tuning.cache import (
    default_cache_path,
    load_cache,
    seed_from_bench_details,
    store_entry,
)
from chainermn_tpu.tuning.measure import measure_candidates, repeat_median
from chainermn_tpu.tuning.registry import (
    DEFAULT_TABLE,
    choice,
    current_device_kind,
    decision_key,
    decisions_summary,
    decisions_taken,
    device_class,
    record_measurement,
    reset_decisions,
    shape_bucket,
)

__all__ = [
    "DEFAULT_TABLE",
    "choice",
    "current_device_kind",
    "decision_key",
    "decisions_summary",
    "decisions_taken",
    "default_cache_path",
    "device_class",
    "load_cache",
    "measure_candidates",
    "record_measurement",
    "repeat_median",
    "reset_decisions",
    "seed_from_bench_details",
    "shape_bucket",
    "store_entry",
]
