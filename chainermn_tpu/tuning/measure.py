"""One-shot measured autotuning — bench.py's measurement discipline.

CPU-proxy rows drifted round-to-round until bench.py adopted
median-of-n>=3 with an explicit spread (round-5 VERDICT ask #8); a
measured autotuner inherits exactly that rule, plus one more: when the
spread SWALLOWS the gap between the two best candidates, the
measurement cannot pick a winner and the deterministic table must
(adopting noise as a cached "winner" would pin a coin flip for every
future run).
"""

from __future__ import annotations

from typing import Callable, Mapping


def repeat_median(sample: Callable[[], float], repeats: int = 3):
    """Median + spread of ``repeats`` samples of a zero-arg measurement
    returning a float (ms). ``spread = 100*(max-min)/median`` — the same
    discipline as bench.py's ``_repeat_median``."""
    vals = sorted(sample() for _ in range(max(1, repeats)))
    n = len(vals)
    med = (vals[n // 2] if n % 2
           else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    spread = 100.0 * (vals[-1] - vals[0]) / med if med else 0.0
    return med, round(spread, 1)


def decide(medians: Mapping[str, float], spreads: Mapping[str, float],
           *, higher_is_better: bool = False):
    """Pick a winner from per-candidate medians, or None when the
    comparison is spread-dominated: the best two medians differ by less
    than the larger of their spreads, so the difference is
    indistinguishable from measurement noise."""
    if not medians:
        return None
    ranked = sorted(medians, key=medians.get, reverse=higher_is_better)
    best = ranked[0]
    if len(ranked) > 1:
        second = ranked[1]
        gap = abs(medians[second] - medians[best])
        noise = max(spreads.get(best, 0.0), spreads.get(second, 0.0))
        if gap <= abs(medians[best]) * noise / 100.0:
            return None
    return best


def measure_candidates(
    measure_fns: Mapping[str, Callable[[], float]], repeats: int = 3
):
    """Run each candidate's zero-arg measurement ``repeats`` times
    (n>=3 enforced) and return ``(winner_or_None, evidence)`` where
    evidence is ``{"candidates_ms": ..., "spread_pct": worst}``.
    Winner is None when spread-dominated (see :func:`decide`)."""
    repeats = max(3, repeats)
    medians: dict[str, float] = {}
    spreads: dict[str, float] = {}
    for cand, fn in measure_fns.items():
        medians[cand], spreads[cand] = repeat_median(fn, repeats)
    evidence = {
        "candidates_ms": {k: round(v, 4) for k, v in medians.items()},
        "spread_pct": max(spreads.values(), default=0.0),
    }
    return decide(medians, spreads), evidence
