"""The decision registry: ``choice(name, candidates, key)``.

Resolution order (each step records its provenance):

1. ``CHAINERMN_TPU_AUTOTUNE_FORCE`` override (``name=winner,...``);
2. the persistent cache (measured on this machine, or seeded offline
   from on-chip bench artifacts — :mod:`chainermn_tpu.tuning.cache`);
3. one-shot measurement, when the call site supplies per-candidate
   measurement callables, tracing is not active, and the mode allows it
   (:mod:`chainermn_tpu.tuning.measure`); the winner is persisted;
4. the deterministic per-device-class table below.

Every resolution is appended to a process-local decision log so
``bench.py`` / ``dryrun_multichip`` can report exactly which path each
site took (dispatch provenance in every capture artifact).
"""

from __future__ import annotations

import os
from typing import Callable, Mapping, Optional, Sequence

from chainermn_tpu.tuning import cache as _cache
from chainermn_tpu.tuning import measure as _measure

#: Deterministic fallbacks, keyed ``decision -> device class -> winner``
#: (``*`` = any). Each winner cites the measurement it rests on
#: (BENCH_DETAILS.json r5 + the carried v5e blob), so the table is the
#: documented crossover, not an opinion:
#:
#: - ``moe_dispatch``: sort won BOTH measured points — 167.8x on the CPU
#:   proxy (T2048xE8xD64) and 1.63x on TPU v5e at the production shape
#:   (T16384xE16xD512, where the dense path is einsum-competitive); the
#:   dense [T,E,C] einsum only ties at tiny shapes, so ``sort``
#:   everywhere and let a cache entry flip shapes where a sweep shows
#:   otherwise.
#: - ``attention``: flash is 3.0x fwd+bwd on the chip but 0.56x under
#:   CPU interpret mode — the inversion that motivated this package.
#: - ``allreduce_wire``: bf16 is the measured default (halved bytes,
#:   zero rounding risk); int8's two rounding stages pay only where DCN
#:   bandwidth is scarce, which a cache entry (seeded from a multi-slice
#:   curve) must demonstrate before it is chosen.
#: - ``allreduce_bucket_mb``: ~64 MB keeps the inter level
#:   bandwidth-bound while bounding the transient flat-copy in HBM
#:   (docs/benchmarks.md curve); ``none`` = single fused buffer.
#: - ``double_buffering``: measured 0.752x on the CPU proxy and 0.85x on
#:   a single chip (no collective to overlap) — ``off`` until a
#:   multi-slice capture shows the overlap paying.
#: - ``reduction_schedule``: ``flat`` everywhere until measured — XLA
#:   already derives a topology-aware schedule from the fused pmean,
#:   so the pinned ``two_level``/``zero`` pipelines must EARN their
#:   extra program structure with a bench ``overlap``-phase win
#:   (seeded from BENCH_DETAILS.json ``overlap_schedule_ms`` rows; see
#:   chainermn_tpu.parallel.reduction_schedule). The choice set is the
#:   DERIVED composition list for the world shape (ISSUE 12:
#:   composition.schedule_candidates — menu names + signature-keyed
#:   derived pipelines, swept by bench's ``composed`` phase and seeded
#:   from its ``composed_schedule_ms`` rows, spread-gated as always);
#:   the ``flat`` table default stays the no-evidence answer.
#: - ``decode_impl`` (serving steady-state step): ``paged`` everywhere
#:   — the idle-box CPU-proxy point measured paged 0.95 ms vs dense
#:   1.38 ms/step (D64xH4xL64, gap outside the 17.5% spread), and on
#:   chip paging additionally buys the HBM-capacity win that motivates
#:   the layout; later proxy runs on a loaded box were SPREAD-DOMINATED
#:   (impls within ~8%, noise ~16%) and correctly refused adoption, so
#:   the table — not a coin-flip cache entry — decides until a decisive
#:   per-shape capture (bench ``serving`` rows) seeds one.
#: - ``kv_block_size``: ``64`` — big enough that table/gather overhead
#:   amortises, small enough that a short request strands < 64 stale
#:   rows per slot; the proxy's 16-vs-64 sweep was SPREAD-DOMINATED
#:   (29% noise), so the table default stands until a decisive
#:   ``serving_kv_block_ms`` capture seeds a winner.
#: - ``spec_tokens`` (speculative decode length K): ``0`` (off) — the
#:   payoff is acceptance-dependent (draft hit rate is a property of
#:   the WORKLOAD, not the device), and a K that drafts junk pays K
#:   wasted verify columns plus draft overhead per tick, so speculation
#:   must EARN adoption through a bench ``serving`` capture
#:   (``serving_spec_ms`` rows + acceptance rate) before 'auto' turns
#:   it on for a shape. Since ISSUE 18 the knob covers SAMPLED traffic
#:   too (counter-based keys + rejection acceptance, docs/serving.md
#:   "Sampling"), so sampled captures (``serving_sampled`` rows,
#:   per-mode acceptance) feed the same decision.
#: - ``prefix_cache`` (cross-request KV prefix sharing): ``on`` — the
#:   miss path costs host metadata only (one trie walk + refcounts per
#:   join; the decode/verify programs are untouched and shared streams
#:   are bit-identical, both pinned in tests/test_prefix_cache.py),
#:   while a hit removes the shared prefix from prefill entirely —
#:   bench's ``serving_prefix`` phase measured the CPU-proxy TTFT win
#:   under duplicate-prefix load and unlike ``spec_tokens`` there is no
#:   workload that pays a device-plane penalty for a junk hit (COW
#:   copies one block, only ever on a full-prefix boundary). A cache
#:   entry can still turn it off where a sweep shows the host walk
#:   mattering.
#: - ``min_shared_blocks``: ``1`` — adopt every full-block hit; raise
#:   via a sweep only where table/refcount churn on tiny hits shows up
#:   (``serving_prefix_msb_ttft_ms`` rows).
DEFAULT_TABLE: dict = {
    "moe_dispatch": {"cpu": "sort", "tpu": "sort", "*": "sort"},
    # Expert-axis MoE (ISSUE 20): spread the experts over an 'expert'
    # mesh axis (2 all_to_alls/layer, 1/n experts resident per shard)
    # vs replicated-local (every shard hosts every expert, zero
    # collectives). 'off' everywhere — on one host the a2a pair is pure
    # overhead, and the HBM-per-expert capacity win that motivates
    # spreading only prices honestly on a real multi-chip mesh, so the
    # axis must EARN adoption through bench's ``moe`` phase rows
    # (``moe_step_ms``, spread-gated; the spec_tokens precedent).
    "expert_parallel": {"*": "off"},
    "attention": {"cpu": "xla", "tpu": "flash", "*": "flash"},
    "attention_windowed": {"cpu": "xla", "tpu": "windowed", "*": "windowed"},
    "allreduce_wire": {"*": "bf16"},
    "allreduce_bucket_mb": {"*": "64"},
    "double_buffering": {"*": "off"},
    "reduction_schedule": {"*": "flat"},
    # Bucket-sliced composed reduction (ISSUE 15): how many slices a
    # composed schedule's stages interleave over (slice i's slow inter-
    # level stage behind slice i+1's fast rs/ag). ``1`` everywhere —
    # slicing multiplies per-stage collective DISPATCHES S× at 1/S
    # payload (total wire bytes unchanged), so the latency/overlap
    # trade must EARN adoption through bench's ``composed`` sliced arms
    # (``composed_sliced_ms`` rows, spread-gated; the
    # spec_tokens/prefill_chunk precedent).
    "comp_slices": {"*": "1"},
    "decode_impl": {"*": "paged"},
    "kv_block_size": {"*": "64"},
    # Fused paged-decode Pallas kernel (ISSUE 19): 'xla' = scatter →
    # dense-view gather → einsum attend; 'fused' = one flash-decoding
    # HBM pass with the block table as a scalar-prefetch operand
    # (ops/paged_decode.py). 'xla' everywhere — the kernel must EARN
    # adoption through bench's ``serving_decode_kernel`` step-time rows
    # (spread-gated; the spec_tokens precedent), and interpret-mode CPU
    # emulation is slower than the XLA path by construction, so only a
    # live-chip capture can honestly flip this. byte_audit's decode
    # workload prices the HBM-bytes case the proxy can't.
    "decode_attend_impl": {"*": "xla"},
    "spec_tokens": {"*": "0"},
    "prefix_cache": {"*": "on"},
    "min_shared_blocks": {"*": "1"},
    # Cluster disaggregation (ISSUE 8): colocated until a bench capture
    # shows the prefill/decode split wins TTFT on this shape — the
    # transfer hop must EARN its place, like speculation.
    "cluster_disagg": {"*": "colocated"},
    # Chunked prefill (ISSUE 11): tokens of prompt prefilled per decode
    # tick inside the mixed step; 0 = monolithic prefill. Default 0 —
    # chunking trades peak prefill throughput for decode-tick latency
    # (every tick pays the chunk-width forward), so it must earn
    # adoption through the bench's bursty goodput-under-SLO rows
    # (spread-gated, the spec_tokens/cluster_disagg precedent). Applies
    # to sampled traffic too since ISSUE 18: counter-based keys make the
    # chunked schedule bit-identical to monolithic at temperature > 0
    # (docs/serving.md "Sampling"), so one decision covers both modes.
    "prefill_chunk": {"*": "0"},
    # Sequence-axis attention (ISSUE 13): ring (n-1 neighbour ppermutes
    # per layer, O(T_local) resident K/V, no divisibility constraint)
    # vs Ulysses (two all_to_alls in + one out per layer; cheaper when
    # heads >= seq size AND the full sequence fits a shard's HBM —
    # which is exactly when you need less sequence parallelism). Ring
    # everywhere until a bench ``seq_parallel`` capture shows Ulysses
    # winning a shape; heads-indivisible shapes force ring regardless.
    "seq_attn_impl": {"*": "ring"},
    # Cost-model schedule search (ISSUE 16): how the composed-schedule
    # sweep covers its candidate grid. 'topk' ranks the candidates with
    # the fitted alpha-beta model and MEASURES only the top-k (skipped
    # arms logged with their predicted costs — no silent coverage
    # loss); 'exhaustive' measures every arm. Topk everywhere — the
    # model is audited on every adoption (predicted-vs-measured error
    # recorded as cache evidence) and an uncalibrated or disagreeing
    # model FORCES exhaustive with loud provenance, so the cheap path
    # can never silently rank on a default-initialized model.
    "sched_search": {"*": "topk"},
    # Multi-tenant adapter application (ISSUE 14): 'gather' = the one
    # compiled program gathers each slot's A/B rows and adds the rank-r
    # delta in-forward — mixed-tenant traffic pays O(r(d_in+d_out)) per
    # projection and tenant churn stays host metadata; 'merged' folds
    # one tenant's delta into the base weights (zero per-step cost but
    # ONE tenant per engine). Gather everywhere until the bench's
    # ``serving_tenants`` rows show merging winning a single-tenant-
    # dominant shape (spread-gated, the spec_tokens precedent).
    "adapter_impl": {"*": "gather"},
    # Sequence-parallel long-prompt prefill over the replica's 'model'
    # partition (ISSUE 13): 'off' until the bench's long-prompt TTFT
    # rows (``seq_parallel_ttft_ms``) show the sharded forward beating
    # the TP prefill on this shape — the in-program param all-gather
    # and per-layer ring hops must EARN their place, the
    # spec_tokens/cluster_disagg precedent. No longer greedy-only
    # (ISSUE 18): every shard derives the same counter-based key from
    # the psum'd logits row, so the sampled sharded prefill emits the
    # token the monolithic path would (docs/serving.md "Sampling").
    "prefill_seq_parallel": {"*": "off"},
}

_MODE_ENV = "CHAINERMN_TPU_AUTOTUNE"
_FORCE_ENV = "CHAINERMN_TPU_AUTOTUNE_FORCE"

#: process-local decision log: (name, key) -> record, insertion-ordered
_DECISIONS: dict = {}


def _mode() -> str:
    mode = os.environ.get(_MODE_ENV, "auto").lower()
    return mode if mode in ("auto", "measure", "table", "off") else "auto"


def _forced() -> dict:
    out = {}
    for part in os.environ.get(_FORCE_ENV, "").split(","):
        if "=" in part:
            name, _, winner = part.partition("=")
            out[name.strip()] = winner.strip()
    return out


def current_device_kind() -> str:
    """``device_kind`` of the default backend's first device (``"cpu"``,
    ``"TPU v5 lite"``, ...); ``"unknown"`` when no backend is up. Call
    sites resolving at trace time always have a live backend."""
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def device_class(device_kind: str) -> str:
    """Coarse class for table lookup: ``cpu`` / ``tpu`` / ``*``."""
    kind = (device_kind or "").lower()
    if "cpu" in kind:
        return "cpu"
    if "tpu" in kind or kind.startswith("v"):
        return "tpu"
    return "*"


def shape_bucket(shape: Sequence[int]) -> str:
    """Bucket each dim up to the next power of two, joined with ``x`` —
    nearby shapes share one decision (and one measurement) instead of
    fragmenting the cache per exact shape."""

    def bucket(d: int) -> int:
        d = int(d)
        if d < 1:
            raise ValueError(f"shape dims must be >= 1, got {d}")
        b = 1
        while b < d:
            b <<= 1
        return b

    return "x".join(str(bucket(d)) for d in shape)


def decision_key(
    device_kind: Optional[str] = None,
    shape: Optional[Sequence[int]] = None,
    dtype=None,
) -> str:
    """``"<device_kind>|<shape-bucket>|<dtype>"`` — the cache key a call
    site's decision is stored under. ``device_kind`` defaults to the
    live backend's; ``dtype`` accepts anything ``jnp.dtype`` does (or a
    plain string tag for non-dtype keys)."""
    kind = device_kind if device_kind is not None else current_device_kind()
    shape_s = shape_bucket(shape) if shape else "-"
    if dtype is None:
        dtype_s = "-"
    elif isinstance(dtype, str):
        dtype_s = dtype
    else:
        import numpy as np

        dtype_s = np.dtype(dtype).name
    return f"{kind}|{shape_s}|{dtype_s}"


def _record(name: str, key: str, winner: str, source: str,
            evidence: Optional[dict] = None) -> None:
    _DECISIONS[(name, key)] = {
        "name": name, "key": key, "winner": winner, "source": source,
        **({"evidence": evidence} if evidence else {}),
    }
    # Every resolution also lands in the structured trace (when one is
    # active) as a ``dispatch`` event — the tuning-cache provenance the
    # observability layer attaches to 'auto' decisions.
    try:
        from chainermn_tpu.observability import trace as _trace

        rec = _trace.active()
        if rec is not None:
            rec.event("dispatch", **_DECISIONS[(name, key)])
    except Exception:
        pass


def decisions_taken() -> list:
    """The decisions this process resolved, in first-resolution order —
    what bench.py / dryrun_multichip fold into their artifacts."""
    return list(_DECISIONS.values())


def decisions_summary(max_len: int = 200) -> str:
    """Compact ``name=winner(source)`` summary for size-capped artifact
    lines (bench's compact JSON line has a 2000-char budget)."""
    parts = [
        f"{d['name']}={d['winner']}({d['source'].split(':')[0]})"
        for d in _DECISIONS.values()
    ]
    out = " ".join(parts)
    return out[:max_len]


def reset_decisions() -> None:
    """Clear the process-local decision log (test isolation)."""
    _DECISIONS.clear()


def _trace_clean() -> bool:
    """Whether we are OUTSIDE any jax trace — measurement runs real
    device work and must never fire mid-trace (inside shard_map/jit the
    table/cache answer is used instead)."""
    try:
        import jax.core

        return bool(jax.core.trace_state_clean())
    except Exception:
        return False


def _table_winner(name: str, key: str, candidates, table) -> str:
    tab = table if table is not None else DEFAULT_TABLE.get(name, {})
    cls = device_class(key.split("|", 1)[0])
    winner = tab.get(cls) or tab.get("*")
    if winner in candidates:
        return winner
    return candidates[0]


def choice(
    name: str,
    candidates: Sequence[str],
    key: str,
    *,
    measure: Optional[Mapping[str, Callable[[], float]]] = None,
    table: Optional[dict] = None,
    cache_path: Optional[str] = None,
) -> str:
    """Resolve decision ``name`` among ``candidates`` for ``key``.

    ``measure`` (optional): per-candidate zero-arg callables returning a
    cost in ms (lower wins) — supplied only by call sites that can
    afford a one-shot measurement (bench, tests, offline sweeps); plain
    library call sites omit it and get cache/table resolution, which is
    pure Python and safe inside a trace.
    """
    if not candidates:
        raise ValueError(f"decision {name!r}: no candidates")
    forced = _forced().get(name)
    if forced is not None:
        if forced not in candidates:
            raise ValueError(
                f"{_FORCE_ENV} forces {name}={forced!r}, not one of "
                f"{tuple(candidates)}"
            )
        _record(name, key, forced, "forced")
        return forced

    mode = _mode()
    if mode != "off":
        entry = _cache.lookup_entry(name, key, cache_path)
        if entry and entry.get("winner") in candidates:
            _record(name, key, entry["winner"],
                    f"cache:{entry.get('source', '?')}",
                    {k: entry[k] for k in ("candidates_ms", "spread_pct")
                     if k in entry})
            return entry["winner"]

    if (measure and mode in ("auto", "measure") and _trace_clean()):
        fns = {c: measure[c] for c in candidates if c in measure}
        if fns:
            winner, evidence = _measure.measure_candidates(fns)
            if winner is not None:
                _cache.store_entry(
                    name, key, {"winner": winner, "source": "measured",
                                **evidence}, cache_path,
                )
                _record(name, key, winner, "measured", evidence)
                return winner
            # spread-dominated: deterministic fallback, evidence kept
            winner = _table_winner(name, key, candidates, table)
            _record(name, key, winner, "table:spread-dominated", evidence)
            return winner

    winner = _table_winner(name, key, candidates, table)
    _record(name, key, winner, "table")
    return winner


def record_measurement(
    name: str,
    key: str,
    medians_ms: Mapping[str, float],
    *,
    spreads: Optional[Mapping[str, float]] = None,
    higher_is_better: bool = False,
    source: str = "measured:bench",
    cache_path: Optional[str] = None,
    extra_evidence: Optional[Mapping[str, object]] = None,
) -> Optional[str]:
    """Adopt an ALREADY-measured comparison into the cache (bench.py's
    phases measure the candidates anyway — this turns those rows into
    dispatch decisions without re-running them). Returns the winner, or
    None when spread-dominated (nothing stored).

    ``spreads=None`` means the caller has NO repeat-derived noise
    estimate (the on-chip bench runs one sample of many chained
    iterations instead of n>=3 samples): a conservative 10% noise floor
    is applied, so a single-sample comparison is adopted only when the
    winner's margin is decisive — never a coin flip recorded as
    spread_pct 0.

    ``extra_evidence`` (ISSUE 16): caller-supplied keys merged into the
    stored entry beside the medians — the cost-model schedule search
    records its predicted-vs-measured error here on every top-k
    adoption, so the model is audited in the cache, never trusted
    blind. Reserved entry keys (winner/source/medians/spread) win over
    a colliding extra key."""
    floored = spreads is None
    if floored:
        spreads = {k: 10.0 for k in medians_ms}
    winner = _measure.decide(medians_ms, spreads,
                             higher_is_better=higher_is_better)
    if winner is None:
        return None
    unit = "candidates_score" if higher_is_better else "candidates_ms"
    entry = {
        **(dict(extra_evidence) if extra_evidence else {}),
        "winner": winner, "source": source,
        unit: {k: round(float(v), 4) for k, v in medians_ms.items()},
        "spread_pct": max(spreads.values(), default=0.0),
    }
    if floored:
        entry["noise_floor_pct"] = 10.0  # single-sample caller
    _cache.store_entry(name, key, entry, cache_path)
    return winner
