"""Multi-node evaluator.

Reference: ``chainermn/evaluators.py`` (dagger) (SURVEY.md section 2.7):
wraps a Chainer Evaluator so each rank evaluates its dataset shard, then the
observation dict is ``allreduce_obj``-ed and divided by world size —
globally averaged metrics, identical to whole-dataset eval.

TPU-native: the evaluator wraps any callable returning a metrics dict
(values: scalars or 0-d arrays). Device-plane averaging happens inside the
caller's jitted eval step (psum over the mesh); this wrapper adds the
host-plane (cross-process) averaging and weighting by example count.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


def create_multi_node_evaluator(
    evaluator: Callable[..., Mapping[str, Any]],
    communicator: CommunicatorBase,
):
    """Wrap ``evaluator`` (any callable returning ``{name: scalar}``) so its
    results are averaged across processes.

    If the returned dict contains the key ``'n'`` (local example count), a
    weighted average is computed; otherwise a plain mean over ranks —
    matching the reference's divide-by-size behaviour.
    """

    def evaluate(*args, **kwargs) -> dict[str, float]:
        local = dict(evaluator(*args, **kwargs))
        n = float(local.pop("n", 1.0))
        weighted = {k: float(v) * n for k, v in local.items()}
        weighted["__n"] = n
        total = communicator.allreduce_obj(weighted)
        n_total = total.pop("__n")
        return {k: v / n_total for k, v in total.items()}

    return evaluate
