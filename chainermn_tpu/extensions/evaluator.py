"""Multi-node evaluator.

Reference: ``chainermn/evaluators.py`` (dagger) (SURVEY.md section 2.7):
wraps a Chainer Evaluator so each rank evaluates its dataset shard, then the
observation dict is ``allreduce_obj``-ed and divided by world size —
globally averaged metrics, identical to whole-dataset eval.

TPU-native: the evaluator wraps any callable returning a metrics dict
(values: scalars or 0-d arrays). Device-plane averaging happens inside the
caller's jitted eval step (psum over the mesh); this wrapper adds the
host-plane (cross-process) averaging and weighting by example count.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


def create_multi_node_evaluator(
    evaluator: Callable[..., Mapping[str, Any]],
    communicator: CommunicatorBase,
    *,
    reduce: str = "mean",
    finalize: Callable[[dict[str, float]], Mapping[str, Any]] | None = None,
):
    """Wrap ``evaluator`` (any callable returning ``{name: scalar}``) so its
    results are aggregated across processes.

    ``reduce='mean'`` (default, the reference's divide-by-size behaviour):
    if the returned dict contains the key ``'n'`` (local example count), a
    weighted average is computed; otherwise a plain mean over ranks.

    ``reduce='sum'``: plain element-wise sum — for metrics whose corpus
    value is a function of summed sufficient statistics rather than an
    average (corpus BLEU: :mod:`chainermn_tpu.utils.bleu`).

    ``finalize``: applied to the aggregated dict on every rank (e.g.
    ``bleu_from_stats`` turning summed n-gram counts into the score).
    """
    if reduce not in ("mean", "sum"):
        raise ValueError(f"reduce must be 'mean' or 'sum', got {reduce!r}")

    def evaluate(*args, **kwargs):
        local = dict(evaluator(*args, **kwargs))
        if reduce == "sum":
            total = communicator.allreduce_obj(
                {k: float(v) for k, v in local.items()}
            )
        else:
            n = float(local.pop("n", 1.0))
            weighted = {k: float(v) * n for k, v in local.items()}
            weighted["__n"] = n
            total = communicator.allreduce_obj(weighted)
            n_total = total.pop("__n")
            total = {k: v / n_total for k, v in total.items()}
        return dict(finalize(total)) if finalize is not None else total

    return evaluate
