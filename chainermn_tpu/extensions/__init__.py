"""Trainer extensions: evaluation, checkpointing, persistent-value sync.

Reference: ``chainermn/extensions/`` (dagger) + ``chainermn/evaluators.py``
(dagger) (SURVEY.md section 2.7).
"""

from chainermn_tpu.extensions.evaluator import create_multi_node_evaluator
from chainermn_tpu.extensions.checkpoint import (
    create_multi_node_checkpointer,
    MultiNodeCheckpointer,
)
from chainermn_tpu.extensions.allreduce_persistent import AllreducePersistent
from chainermn_tpu.extensions.observation_aggregator import ObservationAggregator


def __getattr__(name):
    # Lazy: orbax import is heavy and optional for users of the npz path.
    if name in ("OrbaxMultiNodeCheckpointer", "create_orbax_checkpointer"):
        from chainermn_tpu.extensions import orbax_adapter

        return getattr(orbax_adapter, name)
    raise AttributeError(name)


__all__ = [
    "create_multi_node_evaluator",
    "create_multi_node_checkpointer",
    "MultiNodeCheckpointer",
    "AllreducePersistent",
    "ObservationAggregator",
    "OrbaxMultiNodeCheckpointer",
    "create_orbax_checkpointer",
]
