"""Cross-rank aggregation of training observations (metrics).

Reference: upstream's ``ObservationAggregator`` extension (presence in the
fork uncertain — SURVEY.md section 5 "Metrics / logging"): every
``interval`` iterations, the observations accumulated over the window are
averaged over time AND across ranks, so rank-0 logs global statistics while
the host-plane collective runs once per window, not once per step.
"""

from __future__ import annotations

from typing import Mapping, Optional

from chainermn_tpu.communicators.base import CommunicatorBase


class ObservationAggregator:
    """Average numeric observations across processes and a time window.

    Device-plane metrics inside a jitted step should use ``lax.pmean``
    directly; this aggregator handles host-side dicts (loss running means,
    timing counters) before rank-0 logging.

    With ``interval == 1`` (default) every call aggregates immediately.
    With ``interval > 1`` calls buffer locally and return ``None`` until
    the window closes; then the window-mean is allreduced in one host
    collective and returned. Keys may vary between steps within a window
    (each key averages over the steps that reported it)."""

    def __init__(
        self, communicator: CommunicatorBase, *, interval: int = 1
    ) -> None:
        if interval < 1:
            raise ValueError(f"interval must be >= 1, got {interval}")
        self.comm = communicator
        self.interval = interval
        self._sums: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._calls = 0

    def __call__(
        self, observation: Mapping[str, float]
    ) -> Optional[dict[str, float]]:
        self.add(observation)
        if self._calls < self.interval:
            return None
        # Window mean per rank, then ONE cross-rank averaging collective.
        return self.flush()

    def add(self, observation: Mapping[str, float]) -> None:
        """Buffer one observation into the current window WITHOUT any
        collective — the accumulate half of ``__call__``, split out so
        consumers that exchange per-rank summaries (the straggler
        monitor) can share the window machinery."""
        for k, v in observation.items():
            self._sums[k] = self._sums.get(k, 0.0) + float(v)
            self._counts[k] = self._counts.get(k, 0) + 1
        self._calls += 1

    def flush(self) -> Optional[dict[str, float]]:
        """Aggregate whatever the current window holds (for end of training,
        where a partial window would otherwise be silently dropped). Returns
        ``None`` when the window is empty on EVERY rank.

        Collective when multi-process: every rank must call it at the same
        point, and the collective runs unconditionally — a rank whose window
        is empty contributes nothing but still participates (an early local
        return would deadlock the others). Keys union across ranks; each
        key averages over the ranks/steps that reported it."""
        local = {
            k: (self._sums[k], float(self._counts[k])) for k in self._sums
        }
        self._sums.clear()
        self._counts.clear()
        self._calls = 0

        def union_sum(a: dict, b: dict) -> dict:
            out = dict(a)
            for k, (s, c) in b.items():
                s0, c0 = out.get(k, (0.0, 0.0))
                out[k] = (s0 + s, c0 + c)
            return out

        total = self.comm.allreduce_obj(local, op=union_sum)
        if not total:
            return None
        return {k: s / c for k, (s, c) in total.items()}

    def flush_per_rank(self) -> list[dict[str, float]]:
        """Exchange the window and return EVERY process's window-mean
        dict, in host-plane rank order (``out[i]`` is process i's; an
        empty window contributes ``{}``). The cross-rank comparison the
        straggler monitor needs — a mean would hide exactly the
        divergence it looks for. Same collective contract as
        :meth:`flush`: one host-plane allgather, every process must
        call at the same point."""
        local = {
            k: self._sums[k] / self._counts[k]
            for k in self._sums if self._counts.get(k)
        }
        self._sums.clear()
        self._counts.clear()
        self._calls = 0
        return self.comm.allgather_obj(local)
