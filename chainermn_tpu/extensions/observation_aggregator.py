"""Cross-rank aggregation of training observations (metrics).

Reference: upstream's ``ObservationAggregator`` extension (presence in the
fork uncertain — SURVEY.md section 5 "Metrics / logging"): averages the
reporter's observation dict across ranks each reporting interval so rank-0
logs global, not local, statistics.
"""

from __future__ import annotations

from typing import Mapping

from chainermn_tpu.communicators.base import CommunicatorBase


class ObservationAggregator:
    """Average numeric observations across processes.

    Device-plane metrics inside a jitted step should use ``lax.pmean``
    directly; this aggregator handles host-side dicts (loss running means,
    timing counters) before rank-0 logging.
    """

    def __init__(self, communicator: CommunicatorBase) -> None:
        self.comm = communicator

    def __call__(self, observation: Mapping[str, float]) -> dict[str, float]:
        obs = {k: float(v) for k, v in observation.items()}
        total = self.comm.allreduce_obj(obs)
        return {k: v / self.comm.host.size for k, v in total.items()}
