"""Orbax-backed checkpointer with the reference's agreement semantics.

The framework's own :class:`~chainermn_tpu.extensions.checkpoint.MultiNodeCheckpointer`
(reference: ``extensions/checkpoint.py`` (dagger)) stores per-rank npz
snapshots. Teams already standardised on `orbax
<https://github.com/google/orbax>`_ — the JAX ecosystem's checkpoint
library (sharded array support, async, cloud storage) — shouldn't have to
leave it to get ChainerMN's fault-tolerance behaviour. This adapter keeps
the same two-method surface (``save`` / ``maybe_load``) and the same
cross-rank guarantees:

- retention of the last ``keep`` steps (orbax ``max_to_keep``);
- resume from the NEWEST step that EVERY process possesses, agreed via a
  host-plane object collective (the reference's ``maybe_load``
  max-common-iteration protocol, SURVEY.md section 3.5) — a rank that
  crashed mid-save can't drag the job onto a step others don't have.

Storage layout follows the runtime: single-process uses a per-rank
directory; multi-process uses ORBAX'S native collective model (one
shared directory, coordinated saves), whose contract is that state is
replicated across processes or globally sharded — per-rank-DIVERGENT
host-local state belongs to the npz backend (per-rank files by
design).

Storage format and everything below ``save``/``restore`` is pure orbax
(``StandardCheckpointer`` under a ``CheckpointManager``): checkpoints
taken here are readable by plain orbax tooling and vice versa.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any


def _to_host(leaf):
    """Fully-addressable jax.Arrays -> host numpy (shared by save's
    replicated-value handoff and maybe_load's npz-parity conversion);
    everything else passes through."""
    import jax
    import numpy as np

    if isinstance(leaf, jax.Array) and leaf.is_fully_addressable:
        return np.asarray(leaf)
    return leaf


class OrbaxMultiNodeCheckpointer:
    """``save(state, step)`` / ``maybe_load(template) -> (state, step)``
    on orbax storage, with cross-rank resume agreement."""

    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        *,
        path: str = "checkpoints",
        keep: int = 2,
    ) -> None:
        import orbax.checkpoint as ocp

        import jax as _jax

        self.name = name
        self.comm = comm
        self._multiprocess = _jax.process_count() > 1
        if self._multiprocess:
            # Multi-process runtimes follow ORBAX'S OWN model: one shared
            # checkpoint directory, collective saves coordinated by the
            # manager (primary-host metadata, cross-host barriers).
            # Contract: state leaves must be replicated-identical across
            # processes or globally sharded jax.Arrays — the standard
            # orbax semantics, ENFORCED at save time. Per-rank-DIVERGENT
            # host-local state is the npz backend's domain (per-rank
            # files by design). No migration concern vs earlier layouts:
            # no earlier multi-process layout ever functioned (orbax
            # rejected host-local arrays outright).
            self.path = os.path.abspath(
                os.path.join(path, f"{name}_orbax")
            )
        else:
            self.path = os.path.abspath(
                os.path.join(path, f"{name}_orbax_rank{comm.rank}")
            )
        self._mgr = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    # ------------------------------------------------------------------

    def save(self, state: PyTree, iteration: int, *, block: bool = True) -> str:
        import orbax.checkpoint as ocp

        # npz-backend parity: re-saving an iteration overwrites it (orbax's
        # ``force`` only bypasses the save-interval policy; an existing
        # step raises instead). Drain BEFORE the existence check — orbax
        # commits pending async saves inside save() and would then raise
        # on a step that wasn't in all_steps() moments earlier (TOCTOU:
        # async save of step N in flight + resave of N). Delete-then-save
        # is not atomic — a crash between the two loses this step locally
        # — which the cross-rank agreement absorbs: resume falls back to
        # the previous common step.
        self._mgr.wait_until_finished()
        if iteration in self._mgr.all_steps():
            self._mgr.delete(iteration)
        # Multi-process runtimes: host-local jax.Arrays (single-device
        # shardings) trip orbax's multihost safety check. Under this
        # backend's multiprocess contract the values are replicated
        # across processes, so hand them over as host numpy (orbax
        # writes replicated numpy from the primary). Non-fully-
        # addressable (globally sharded) leaves pass through for orbax's
        # sharded writer.
        import jax as _jax

        if self._multiprocess:
            state = _jax.tree.map(_to_host, state)
            # The contract is ENFORCED, not assumed: divergent values
            # would silently become the primary's on restore — raise
            # loudly and point at the npz backend instead.
            self._assert_replicated(state)
        self._mgr.save(
            iteration, args=ocp.args.StandardSave(state), force=True
        )
        if block:
            self._mgr.wait_until_finished()
        return os.path.join(self.path, str(iteration))

    def _assert_replicated(self, state: PyTree) -> None:
        import hashlib
        import pickle

        import jax as _jax
        import numpy as _np

        h = hashlib.sha256()
        for leaf in _jax.tree.leaves(state):
            if isinstance(leaf, _np.ndarray):
                h.update(_np.ascontiguousarray(leaf).tobytes())
            elif not isinstance(leaf, _jax.Array):
                h.update(pickle.dumps(leaf))
        digests = self.comm.allgather_obj(h.hexdigest())
        if len(set(digests)) != 1:
            raise ValueError(
                "orbax backend multiprocess contract violated: state "
                "differs across processes (digests "
                f"{sorted(set(digests))}); per-rank-divergent state needs "
                "create_multi_node_checkpointer (npz, per-rank files)"
            )

    def _local_iterations(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def maybe_load(
        self, state_template: PyTree
    ) -> tuple[PyTree, Optional[int]]:
        """Restore the newest step ALL processes have; ``(template, None)``
        when no common step exists. Call with the freshly initialised
        state so shapes/dtypes (and shardings) come from the template."""
        import orbax.checkpoint as ocp

        from chainermn_tpu.extensions.checkpoint import agree_max_common_step

        # Drain async saves BEFORE comparing steps — but never raise ahead
        # of the collective (that would leave the healthy ranks hanging in
        # allgather): the shared agreement helper carries each rank's
        # drain error through the collective and raises symmetrically.
        drain_err = None
        try:
            self._mgr.wait_until_finished()
        except Exception as e:
            drain_err = f"{type(e).__name__}: {e}"
        step = agree_max_common_step(
            self.comm, self._local_iterations(), drain_err
        )
        if step is None:
            return state_template, None
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_template)
        )
        # npz-backend parity: hand fully-addressable leaves back as HOST
        # arrays so the next jitted step (re-)places them under its own
        # shardings — orbax otherwise returns device-committed arrays
        # whose placement can disagree leaf-to-leaf with the template
        # (restored scalar on one device, replicated params on eight →
        # "incompatible devices" at the first step after resume).
        # Non-fully-addressable (multi-host sharded) leaves keep their
        # restored global shardings.
        import jax
        import numpy as np

        return jax.tree.map(_to_host, state), step

    def wait_async(self) -> None:
        """Drain pending async saves (surface parity with the npz
        backend's ``wait_async``)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def create_orbax_checkpointer(
    name: str, comm: CommunicatorBase, **kwargs
) -> OrbaxMultiNodeCheckpointer:
    """Factory mirroring :func:`create_multi_node_checkpointer`, on orbax
    storage."""
    return OrbaxMultiNodeCheckpointer(name, comm, **kwargs)


__all__ = ["OrbaxMultiNodeCheckpointer", "create_orbax_checkpointer"]
