"""Fault-tolerant multi-node checkpointing.

Reference: ``chainermn/extensions/checkpoint.py`` (dagger) (SURVEY.md
sections 2.7, 3.5): ``create_multi_node_checkpointer(name, comm)`` snapshots
per-rank files tagged ``(name, rank, iteration)``, garbage-collects stale
snapshots round-robin, and on restart ``maybe_load`` agrees — via an object
collective — on the newest iteration *every* rank possesses, giving
restart-based fault tolerance on preemptible clusters.

TPU-native: one snapshot file per *process* (a host checkpoints all its local
shards; arrays are fetched with their global view, so single-process restores
of multi-device state just work). Agreement on the resume iteration is a
host-plane ``allgather_obj`` + min/max-common computation, exactly the
reference's protocol. Orbax is the right answer for production multi-TB
checkpoints; this implementation is self-contained (npz) with the same
file-per-rank + agreement semantics so its behaviour is testable hermetically.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any

_FNAME_RE = re.compile(r"^snapshot_(?P<name>.+)_(?P<rank>\d+)_(?P<iter>\d+)\.npz$")


class MultiNodeCheckpointer:
    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        *,
        path: str = "checkpoints",
        keep: int = 2,
    ) -> None:
        self.name = name
        self.comm = comm
        self.path = path
        self.keep = keep
        os.makedirs(path, exist_ok=True)

    # ------------------------------------------------------------------

    def _fname(self, iteration: int, rank: Optional[int] = None) -> str:
        rank = self.comm.rank if rank is None else rank
        return os.path.join(
            self.path, f"snapshot_{self.name}_{rank}_{iteration}.npz"
        )

    def _local_iterations(self) -> list[int]:
        its = []
        for fn in os.listdir(self.path):
            m = _FNAME_RE.match(fn)
            if m and m.group("name") == self.name and int(m.group("rank")) == self.comm.rank:
                its.append(int(m.group("iter")))
        return sorted(its)

    # ------------------------------------------------------------------

    def save(self, state: PyTree, iteration: int) -> str:
        """Snapshot ``state`` (any pytree of arrays) for this process, then
        GC old local snapshots beyond ``keep`` (the reference's round-robin
        stale-file GC)."""
        leaves = jax.tree.leaves(state)
        arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
        fname = self._fname(iteration)
        tmp = fname + ".tmp.npz"
        np.savez(tmp, **arrays)
        os.replace(tmp, fname)

        for it in self._local_iterations()[: -self.keep] if self.keep else []:
            try:
                os.remove(self._fname(it))
            except OSError:
                pass
        return fname

    def maybe_load(self, state_template: PyTree) -> tuple[PyTree, Optional[int]]:
        """Resume from the newest iteration available on *all* processes
        (reference: gather available iters -> max common -> deserialize,
        SURVEY.md section 3.5). Returns ``(state, iteration)`` or
        ``(state_template, None)`` when no common snapshot exists."""
        local = set(self._local_iterations())
        everyone = self.comm.allgather_obj(sorted(local))
        common = set(everyone[0])
        for its in everyone[1:]:
            common &= set(its)
        if not common:
            return state_template, None
        it = max(common)
        data = np.load(self._fname(it))
        leaves, treedef = jax.tree.flatten(state_template)
        loaded = [
            np.asarray(data[f"leaf_{i}"]).astype(np.asarray(t).dtype)
            for i, t in enumerate(leaves)
        ]
        restored = [
            jax.numpy.asarray(x).reshape(np.shape(t))
            for x, t in zip(loaded, leaves)
        ]
        return jax.tree.unflatten(treedef, restored), it

    def cleanup(self) -> None:
        for it in self._local_iterations():
            try:
                os.remove(self._fname(it))
            except OSError:
                pass


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    *,
    path: str = "checkpoints",
    keep: int = 2,
) -> MultiNodeCheckpointer:
    """Factory mirroring the reference signature."""
    return MultiNodeCheckpointer(name, comm, path=path, keep=keep)
