"""Fault-tolerant multi-node checkpointing.

Reference: ``chainermn/extensions/checkpoint.py`` (dagger) (SURVEY.md
sections 2.7, 3.5): ``create_multi_node_checkpointer(name, comm)`` snapshots
per-rank files tagged ``(name, rank, iteration)``, garbage-collects stale
snapshots round-robin, and on restart ``maybe_load`` agrees — via an object
collective — on the newest iteration *every* rank possesses, giving
restart-based fault tolerance on preemptible clusters.

TPU-native: one snapshot file per *process* (a host checkpoints all its local
shards; arrays are fetched with their global view, so single-process restores
of multi-device state just work). Agreement on the resume iteration is a
host-plane ``allgather_obj`` + min/max-common computation, exactly the
reference's protocol. Orbax is the right answer for production multi-TB
checkpoints; this implementation is self-contained (npz) with the same
file-per-rank + agreement semantics so its behaviour is testable hermetically.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any

_FNAME_RE = re.compile(r"^snapshot_(?P<name>.+)_(?P<rank>\d+)_(?P<iter>\d+)\.npz$")


def _path_key(path) -> str:
    """Stable string key for a tree path (root leaf → ``'<root>'``)."""
    return jax.tree_util.keystr(path) or "<root>"


def _path_keyed_arrays(state: PyTree) -> dict[str, np.ndarray]:
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    arrays: dict[str, np.ndarray] = {}
    for path, leaf in flat:
        key = _path_key(path)
        if key in arrays:
            raise ValueError(f"duplicate tree-path key {key!r}")
        arrays[key] = np.asarray(leaf)
    return arrays


class MultiNodeCheckpointer:
    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        *,
        path: str = "checkpoints",
        keep: int = 2,
    ) -> None:
        self.name = name
        self.comm = comm
        self.path = path
        self.keep = keep
        os.makedirs(path, exist_ok=True)

    # ------------------------------------------------------------------

    def _fname(self, iteration: int, rank: Optional[int] = None) -> str:
        rank = self.comm.rank if rank is None else rank
        return os.path.join(
            self.path, f"snapshot_{self.name}_{rank}_{iteration}.npz"
        )

    def _local_iterations(self) -> list[int]:
        its = []
        for fn in os.listdir(self.path):
            m = _FNAME_RE.match(fn)
            if m and m.group("name") == self.name and int(m.group("rank")) == self.comm.rank:
                its.append(int(m.group("iter")))
        return sorted(its)

    # ------------------------------------------------------------------

    def save(self, state: PyTree, iteration: int) -> str:
        """Snapshot ``state`` (any pytree of arrays) for this process, then
        GC old local snapshots beyond ``keep`` (the reference's round-robin
        stale-file GC).

        Arrays are keyed by their *tree path* (``jax.tree_util.keystr``),
        not position: a pytree reordered between save and load restores
        correctly by name, and a renamed/missing/extra leaf fails loudly at
        load instead of silently mis-assigning a shape-compatible array."""
        arrays = _path_keyed_arrays(state)
        fname = self._fname(iteration)
        tmp = fname + ".tmp.npz"
        np.savez(tmp, **arrays)
        os.replace(tmp, fname)

        for it in self._local_iterations()[: -self.keep] if self.keep else []:
            try:
                os.remove(self._fname(it))
            except OSError:
                pass
        return fname

    def maybe_load(self, state_template: PyTree) -> tuple[PyTree, Optional[int]]:
        """Resume from the newest iteration available on *all* processes
        (reference: gather available iters -> max common -> deserialize,
        SURVEY.md section 3.5). Returns ``(state, iteration)`` or
        ``(state_template, None)`` when no common snapshot exists."""
        local = set(self._local_iterations())
        everyone = self.comm.allgather_obj(sorted(local))
        common = set(everyone[0])
        for its in everyone[1:]:
            common &= set(its)
        if not common:
            return state_template, None
        it = max(common)
        data = np.load(self._fname(it))
        flat, treedef = jax.tree_util.tree_flatten_with_path(state_template)
        keys = [_path_key(p) for p, _ in flat]
        saved, wanted = set(data.files), set(keys)
        if saved != wanted and all(
            re.fullmatch(r"leaf_\d+", k) for k in saved
        ):
            raise ValueError(
                f"checkpoint {self._fname(it)} uses the legacy positional "
                "'leaf_{i}' format (pre-tree-path snapshots); it cannot be "
                "restored safely by name — re-save from a live state or "
                "delete the stale snapshot files"
            )
        if saved != wanted:
            raise ValueError(
                f"checkpoint {self._fname(it)} key set does not match the "
                f"state template: missing={sorted(wanted - saved)[:8]} "
                f"unexpected={sorted(saved - wanted)[:8]}"
            )
        restored = []
        for key, (_, t) in zip(keys, flat):
            arr = np.asarray(data[key])
            tshape = np.shape(t)
            if arr.shape != tshape:
                raise ValueError(
                    f"checkpoint leaf {key!r} has shape {arr.shape}, "
                    f"template expects {tshape}"
                )
            restored.append(
                jax.numpy.asarray(arr.astype(np.asarray(t).dtype))
            )
        return jax.tree.unflatten(treedef, restored), it

    def cleanup(self) -> None:
        for it in self._local_iterations():
            try:
                os.remove(self._fname(it))
            except OSError:
                pass


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    *,
    path: str = "checkpoints",
    keep: int = 2,
) -> MultiNodeCheckpointer:
    """Factory mirroring the reference signature."""
    return MultiNodeCheckpointer(name, comm, path=path, keep=keep)
