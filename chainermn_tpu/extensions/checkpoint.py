"""Fault-tolerant multi-node checkpointing.

Reference: ``chainermn/extensions/checkpoint.py`` (dagger) (SURVEY.md
sections 2.7, 3.5): ``create_multi_node_checkpointer(name, comm)`` snapshots
per-rank files tagged ``(name, rank, iteration)``, garbage-collects stale
snapshots round-robin, and on restart ``maybe_load`` agrees — via an object
collective — on the newest iteration *every* rank possesses, giving
restart-based fault tolerance on preemptible clusters.

TPU-native: one snapshot file per *process* (a host checkpoints all its local
shards; arrays are fetched with their global view, so single-process restores
of multi-device state just work). Agreement on the resume iteration is a
host-plane ``allgather_obj`` + min/max-common computation, exactly the
reference's protocol. Orbax is the right answer for production multi-TB
checkpoints; this implementation is self-contained (npz) with the same
file-per-rank + agreement semantics so its behaviour is testable hermetically.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any

_FNAME_RE = re.compile(r"^snapshot_(?P<name>.+)_(?P<rank>\d+)_(?P<iter>\d+)\.npz$")


def _path_key(path) -> str:
    """Stable string key for a tree path (root leaf → ``'<root>'``)."""
    return jax.tree_util.keystr(path) or "<root>"


#: separates the tree-path key from a shard's global-index suffix
_SHARD_SEP = "@@"


def _index_str(index, shape) -> str:
    """Canonical string for a shard's global index: ``start:stop`` per dim,
    or ``start:stop:step`` for a STRIDED shard (some sharding layouts hand
    a device an interleaved slice — e.g. a transposed mesh axis over a
    stacked ``[n, ...]`` plan-ZeRO state). Slices are normalised against
    the global shape, so device numbering never enters the format —
    restarts with renumbered devices restore fine. The parse side
    (``slice(*map(int, part.split(':')))`` in ``_global_from_shards`` /
    the ``_assemble_sharded`` symmetric lookup) handles both forms."""
    parts = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step == 1:
            parts.append(f"{start}:{stop}")
        else:
            parts.append(f"{start}:{stop}:{step}")
    return "|".join(parts)


def _path_keyed_arrays(state: PyTree) -> dict[str, np.ndarray]:
    """Flatten ``state`` to ``{tree_path: np.ndarray}``.

    Fully-addressable leaves (replicated or single-process) are stored as
    their global view. Multi-process *sharded* leaves are stored as this
    process's addressable shards, keyed ``path@@start:stop|...`` by global
    index — each host writes only the bytes it owns (the sharded-params
    answer the npz whole-state format lacked; reference scale story:
    SURVEY.md section 5 checkpoint/resume, 'sharded per-host checkpoints
    with a manifest')."""
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    arrays: dict[str, np.ndarray] = {}
    for path, leaf in flat:
        key = _path_key(path)
        if _SHARD_SEP in key:
            raise ValueError(f"tree-path key {key!r} contains {_SHARD_SEP!r}")
        if key in arrays:
            raise ValueError(f"duplicate tree-path key {key!r}")
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            seen = set()
            for s in leaf.addressable_shards:
                ik = _index_str(s.index, leaf.shape)
                if ik in seen:  # replicated over several local devices
                    continue
                seen.add(ik)
                arrays[f"{key}{_SHARD_SEP}{ik}"] = np.asarray(s.data)
        else:
            arrays[key] = np.asarray(leaf)
    return arrays


def _assemble_sharded(key: str, data, template_leaf, tshape):
    """Rebuild a global sharded array from this process's saved shards,
    using the *template's* sharding to place them."""
    sharding = template_leaf.sharding
    imap = sharding.addressable_devices_indices_map(tshape)
    pieces = []
    for device, index in imap.items():
        skey = f"{key}{_SHARD_SEP}{_index_str(index, tshape)}"
        if skey not in data:
            raise ValueError(
                f"checkpoint misses shard {skey!r} required by the template "
                "sharding — was it saved under a different mesh layout?"
            )
        arr = np.asarray(data[skey]).astype(
            np.dtype(template_leaf.dtype), copy=False
        )
        pieces.append(jax.device_put(arr, device))
    return jax.make_array_from_single_device_arrays(
        tshape, sharding, pieces
    )


def agree_max_common_step(
    comm: CommunicatorBase,
    local_iterations,
    drain_err: Optional[str] = None,
) -> Optional[int]:
    """The cross-rank resume agreement, shared by every checkpoint backend
    (npz and orbax): allgather ``(iterations, drain-error)`` in ONE
    collective, raise SYMMETRICALLY on every rank if any rank's async
    writes failed (a raising preamble before the collective would hang the
    healthy ranks inside allgather), else return the newest iteration ALL
    ranks possess (``None`` when no common step exists). Reference
    protocol: SURVEY.md section 3.5."""
    everyone = comm.allgather_obj(
        {"its": sorted(local_iterations), "err": drain_err}
    )
    errs = [
        f"rank {r}: {e['err']}" for r, e in enumerate(everyone) if e["err"]
    ]
    if errs:
        raise RuntimeError(
            "async checkpoint write failures detected at restore: "
            + "; ".join(errs)
        )
    common = set(everyone[0]["its"])
    for entry in everyone[1:]:
        common &= set(entry["its"])
    return max(common) if common else None


class MultiNodeCheckpointer:
    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        *,
        path: str = "checkpoints",
        keep: int = 2,
    ) -> None:
        self.name = name
        self.comm = comm
        self.path = path
        self.keep = keep
        self._writer = None  # lazy native async writer (save(block=False))
        os.makedirs(path, exist_ok=True)

    # ------------------------------------------------------------------

    def _fname(self, iteration: int, rank: Optional[int] = None) -> str:
        rank = self.comm.rank if rank is None else rank
        return os.path.join(
            self.path, f"snapshot_{self.name}_{rank}_{iteration}.npz"
        )

    def _local_iterations(self) -> list[int]:
        its = []
        for fn in os.listdir(self.path):
            m = _FNAME_RE.match(fn)
            if m and m.group("name") == self.name and int(m.group("rank")) == self.comm.rank:
                its.append(int(m.group("iter")))
        return sorted(its)

    def _directory_iterations(self) -> list[int]:
        """Iterations present for ANY rank (world-resize restore: the
        saving world's rank numbering is irrelevant; completeness is
        verified leaf-by-leaf during the load)."""
        its = set()
        for fn in os.listdir(self.path):
            m = _FNAME_RE.match(fn)
            if m and m.group("name") == self.name:
                its.add(int(m.group("iter")))
        return sorted(its)

    def _merged_shard_data(self, iteration: int) -> dict:
        """Union of every rank's saved arrays for ``iteration`` —
        requires the snapshot directory to be SHARED storage (the
        world-resize contract). Duplicate keys (shards replicated
        across the old world) are verified identical."""
        merged: dict[str, np.ndarray] = {}
        for fn in sorted(os.listdir(self.path)):
            m = _FNAME_RE.match(fn)
            if not (m and m.group("name") == self.name
                    and int(m.group("iter")) == iteration):
                continue
            with np.load(os.path.join(self.path, fn)) as data:
                for k in data.files:
                    arr = np.asarray(data[k])
                    if k in merged:
                        prev = merged[k]
                        # Bytes comparison: NaN-safe (NaN == NaN must
                        # count as the same saved value) and dtype-exact.
                        if (prev.shape != arr.shape
                                or prev.dtype != arr.dtype
                                or prev.tobytes() != arr.tobytes()):
                            raise ValueError(
                                f"conflicting copies of {k!r} across "
                                f"ranks' snapshots at iteration "
                                f"{iteration} — corrupt checkpoint set"
                            )
                        continue
                    merged[k] = arr
        return merged

    @staticmethod
    def _global_from_shards(key: str, merged: dict, tshape, dtype):
        """Reassemble one leaf's FULL global array from the merged shard
        entries (any old-world sharding); raises if coverage has holes."""
        out = np.zeros(tshape, dtype)
        covered = np.zeros(tshape, bool)
        prefix = f"{key}{_SHARD_SEP}"
        found = False
        for skey, arr in merged.items():
            if not skey.startswith(prefix):
                continue
            found = True
            slices = tuple(
                slice(*map(int, part.split(":")))
                for part in skey[len(prefix):].split("|")
            )
            out[slices] = arr
            covered[slices] = True
        if not found:
            raise ValueError(f"no shards found for leaf {key!r}")
        if not covered.all():
            raise ValueError(
                f"shards for leaf {key!r} do not cover the full global "
                f"shape {tuple(tshape)} — snapshot set incomplete (all "
                "ranks' files must be on shared storage for a "
                "world-resize restore)"
            )
        return out

    # ------------------------------------------------------------------

    def save(self, state: PyTree, iteration: int, *, block: bool = True) -> str:
        """Snapshot ``state`` (any pytree of arrays) for this process, then
        GC old local snapshots beyond ``keep`` (the reference's round-robin
        stale-file GC).

        Arrays are keyed by their *tree path* (``jax.tree_util.keystr``),
        not position: a pytree reordered between save and load restores
        correctly by name, and a renamed/missing/extra leaf fails loudly at
        load instead of silently mis-assigning a shape-compatible array.

        ``block=False`` hands the serialized bytes to the native async
        writer (:mod:`chainermn_tpu.native.ckpt_writer`): the call returns
        once device arrays are fetched and pickled; write+fsync+rename run
        on a C++ worker thread. Call :meth:`wait_async` before treating the
        iteration as durable (``maybe_load`` does so automatically)."""
        arrays = _path_keyed_arrays(state)
        fname = self._fname(iteration)
        if not block:
            import io

            buf = io.BytesIO()
            np.savez(buf, **arrays)
            if self._writer is None:
                from chainermn_tpu.native.ckpt_writer import (
                    AsyncCheckpointWriter,
                )

                self._writer = AsyncCheckpointWriter()
            self._writer.submit(fname, buf.getvalue())
            # GC here too (not only at wait_async): long runs must not
            # accumulate snapshots unboundedly between drains. Only durable
            # (on-disk) files are scanned, so in-flight writes are safe.
            self._gc()
            return fname
        tmp = fname + ".tmp.npz"
        np.savez(tmp, **arrays)
        # fsync file AND directory before/after the rename: the blocking
        # path is the one durability-critical callers use (the preemption
        # guard saves right before exit), so a power-off must not be able
        # to publish a torn snapshot (async path does the same in C++).
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, fname)
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._gc()
        return fname

    def _gc(self) -> None:
        for it in self._local_iterations()[: -self.keep] if self.keep else []:
            try:
                os.remove(self._fname(it))
            except OSError:
                pass

    def wait_async(self) -> None:
        """Drain the async writer: on return every ``block=False`` save is
        durable (raises if any failed), and stale snapshots are GC'd (GC is
        deferred from async saves so it can't race the writes)."""
        if self._writer is not None:
            self._writer.wait()
            self._gc()

    def close(self) -> None:
        """Drain AND release: the native writer's C worker thread and
        queue buffers are freed here, not left for GC (long-lived
        processes create many checkpointers) — even when the drain
        surfaces a write failure."""
        try:
            self.wait_async()
        finally:
            if self._writer is not None:
                self._writer.finalize()
                self._writer = None

    def maybe_load(
        self, state_template: PyTree, *, allow_world_resize: bool = False
    ) -> tuple[PyTree, Optional[int]]:
        """Resume from the newest iteration available on *all* processes
        (reference: gather available iters -> max common -> deserialize,
        SURVEY.md section 3.5). Returns ``(state, iteration)`` or
        ``(state_template, None)`` when no common snapshot exists.

        ``allow_world_resize=True`` restores snapshots written by a
        DIFFERENT world size/mesh layout (beyond the reference's static
        MPI world): iterations are discovered directory-wide (new ranks
        have no files of their own), and any sharded leaf whose saved
        shard boundaries don't match the new template's sharding is
        reassembled globally from ALL ranks' files and re-sliced —
        requires the snapshot directory to be shared storage, and
        verifies full coverage leaf-by-leaf."""
        # Drain in-flight async saves so they count once durable. A raising
        # preamble BEFORE the collective would hang the other ranks inside
        # allgather — gather each rank's failure status along with its
        # iterations and raise symmetrically on every rank.
        drain_err = None
        try:
            self.wait_async()
        except RuntimeError as e:
            drain_err = str(e)
        its = (self._directory_iterations() if allow_world_resize
               else self._local_iterations())
        it = agree_max_common_step(self.comm, its, drain_err)
        if it is None:
            return state_template, None
        if allow_world_resize:
            merged = self._merged_shard_data(it)
            return self._restore_resized(state_template, it, merged)
        with np.load(self._fname(it)) as data:
            return self._restore_strict(state_template, it, data)

    def _restore_strict(self, state_template, it, data):
        flat, treedef = jax.tree_util.tree_flatten_with_path(state_template)
        keys = [_path_key(p) for p, _ in flat]
        # Shard entries (``path@@start:stop|...``) collapse onto their base
        # key for the key-set agreement check.
        saved = {k.split(_SHARD_SEP, 1)[0] for k in data.files}
        sharded_saved = {
            k.split(_SHARD_SEP, 1)[0] for k in data.files if _SHARD_SEP in k
        }
        wanted = set(keys)
        if saved != wanted and all(
            re.fullmatch(r"leaf_\d+", k) for k in saved
        ):
            raise ValueError(
                f"checkpoint {self._fname(it)} uses the legacy positional "
                "'leaf_{i}' format (pre-tree-path snapshots); it cannot be "
                "restored safely by name — re-save from a live state or "
                "delete the stale snapshot files"
            )
        if saved != wanted:
            raise ValueError(
                f"checkpoint {self._fname(it)} key set does not match the "
                f"state template: missing={sorted(wanted - saved)[:8]} "
                f"unexpected={sorted(saved - wanted)[:8]}"
            )
        restored = []
        for key, (_, t) in zip(keys, flat):
            tshape = np.shape(t)
            if key in sharded_saved:
                if not isinstance(t, jax.Array):
                    raise ValueError(
                        f"checkpoint leaf {key!r} was saved sharded but the "
                        "template leaf carries no sharding to restore it with"
                    )
                restored.append(_assemble_sharded(key, data, t, tshape))
                continue
            arr = np.asarray(data[key])
            if arr.shape != tshape:
                raise ValueError(
                    f"checkpoint leaf {key!r} has shape {arr.shape}, "
                    f"template expects {tshape}"
                )
            restored.append(
                jax.numpy.asarray(arr.astype(np.asarray(t).dtype))
            )
        return jax.tree.unflatten(treedef, restored), it

    def _restore_resized(self, state_template: PyTree, it: int,
                         merged: dict) -> tuple[PyTree, int]:
        """The world-resize restore path: every leaf comes from the
        MERGED cross-rank data; sharded leaves are reassembled globally
        and re-sliced onto the template's (new) sharding.

        Cost note: each restoring process reads the full old snapshot
        set and materialises each leaf at global size on the host (plus
        a transient bool coverage mask) — O(world x checkpoint) shared
        -storage traffic, paid once per RESIZE restore, not on the
        normal resume path."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            state_template
        )
        base_keys = {k.split(_SHARD_SEP, 1)[0] for k in merged}
        wanted = {_path_key(p) for p, _ in flat}
        # Same key-set agreement (and legacy-format detection) as the
        # strict path: a dropped template field or an orphaned saved
        # leaf must fail loudly, resize or not.
        if base_keys != wanted and all(
            re.fullmatch(r"leaf_\d+", k) for k in base_keys
        ):
            raise ValueError(
                f"checkpoint iteration {it} uses the legacy positional "
                "'leaf_{i}' format (pre-tree-path snapshots); it cannot "
                "be restored safely by name — re-save from a live state "
                "or delete the stale snapshot files"
            )
        if base_keys != wanted:
            raise ValueError(
                f"checkpoint iteration {it} key set does not match the "
                f"state template: missing={sorted(wanted - base_keys)[:8]} "
                f"unexpected={sorted(base_keys - wanted)[:8]}"
            )
        restored = []
        for path, t in flat:
            key = _path_key(path)
            tshape = np.shape(t)
            tdtype = np.dtype(
                t.dtype if hasattr(t, "dtype") else np.asarray(t).dtype
            )
            if key in merged:  # saved as a full global view
                arr = np.asarray(merged[key])
                if arr.shape != tshape:
                    raise ValueError(
                        f"checkpoint leaf {key!r} has shape {arr.shape}, "
                        f"template expects {tshape}"
                    )
            else:  # shard entries only: reassemble globally
                arr = self._global_from_shards(key, merged, tshape, tdtype)
            if isinstance(t, jax.Array) and not t.is_fully_addressable:
                sharding = t.sharding
                imap = sharding.addressable_devices_indices_map(tshape)
                pieces = [
                    jax.device_put(
                        arr[index].astype(tdtype, copy=False), device
                    )
                    for device, index in imap.items()
                ]
                restored.append(jax.make_array_from_single_device_arrays(
                    tshape, sharding, pieces
                ))
            elif isinstance(t, jax.Array):
                # Fully addressable (e.g. restoring into ONE process
                # with a multi-device mesh): honour the template's
                # sharding instead of silently defaulting it.
                restored.append(
                    jax.device_put(arr.astype(tdtype), t.sharding)
                )
            else:
                restored.append(jax.numpy.asarray(arr.astype(tdtype)))
        return jax.tree.unflatten(treedef, restored), it

    def cleanup(self) -> None:
        # Drain first: an in-flight async save landing AFTER the deletes
        # would resurrect a snapshot. Failures don't matter here — we are
        # removing everything anyway.
        if self._writer is not None:
            try:
                self._writer.wait()
            except RuntimeError:
                pass
        for it in self._local_iterations():
            try:
                os.remove(self._fname(it))
            except OSError:
                pass


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    *,
    path: str = "checkpoints",
    keep: int = 2,
) -> MultiNodeCheckpointer:
    """Factory mirroring the reference signature."""
    return MultiNodeCheckpointer(name, comm, path=path, keep=keep)
