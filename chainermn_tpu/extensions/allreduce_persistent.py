"""Allreduce of persistent (non-gradient) values.

Reference: ``chainermn/extensions/allreduce_persistent.py`` (dagger)
(location approximate; SURVEY.md section 2.7): averages persistent values
such as BatchNorm running statistics across ranks so that evaluation is
consistent no matter which rank's copy is used.

TPU-native: persistent state (e.g. flax ``batch_stats``) lives in the train
state pytree. When batch statistics are computed under data-parallel
``shard_map`` with :class:`~chainermn_tpu.links.MultiNodeBatchNormalization`
they are already identical on every shard; this extension covers the plain-BN
case and cross-process drift.
"""

from __future__ import annotations

from typing import Any

import jax

from chainermn_tpu.communicators.base import CommunicatorBase

PyTree = Any


class AllreducePersistent:
    """Callable extension: average a pytree of persistent values across the
    host plane (and replicate on the mesh)."""

    def __init__(self, communicator: CommunicatorBase) -> None:
        self.comm = communicator

    def __call__(self, persistent: PyTree) -> PyTree:
        host = self.comm.host
        if host.size > 1:
            import numpy as np

            leaves, treedef = jax.tree.flatten(persistent)
            as_np = [np.asarray(x) for x in leaves]
            summed = host.allreduce_obj(as_np)
            leaves = [s / host.size for s in summed]
            persistent = jax.tree.unflatten(treedef, leaves)
        return self.comm.bcast_data(persistent)
