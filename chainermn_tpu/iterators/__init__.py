"""Multi-node iterators.

Reference: ``chainermn/iterators/`` (dagger) (SURVEY.md section 2.6):
``create_multi_node_iterator`` — a master rank iterates the real dataset and
broadcasts each batch (input replication for model-parallel ranks); plus a
synchronized-shuffle iterator where all ranks draw the same order.

TPU-native: batches are numpy on the host until the jitted step; broadcast is
a host-plane ``bcast_obj`` (single-process: passthrough). The synchronized
iterator needs no communication at all — a shared seed yields the same
permutation on every process.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional, Sequence

import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


class _BatchIterator:
    """Minimal epoch-aware batch iterator (the role Chainer's
    ``SerialIterator`` played under the reference's wrappers)."""

    def __init__(
        self,
        dataset: Sequence[Any],
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self._rng = np.random.RandomState(seed)
        self._order = self._new_order()
        self._pos = 0

    def _new_order(self) -> np.ndarray:
        n = len(self.dataset)
        return self._rng.permutation(n) if self.shuffle else np.arange(n)

    def __iter__(self) -> Iterator[list]:
        return self

    def __next__(self) -> list:
        n = len(self.dataset)
        if self._pos >= n or (self.drop_last and self._pos + self.batch_size > n):
            self.epoch += 1
            self._order = self._new_order()
            self._pos = 0
            raise StopIteration
        idx = self._order[self._pos : self._pos + self.batch_size]
        self._pos += len(idx)
        return [self.dataset[int(i)] for i in idx]

    def reset(self) -> None:
        self._pos = 0


def create_multi_node_iterator(
    dataset: Sequence[Any],
    batch_size: int,
    comm: CommunicatorBase,
    *,
    rank_master: int = 0,
    shuffle: bool = True,
    seed: int = 0,
) -> Iterable[list]:
    """Master-broadcast iterator: ``rank_master`` draws batches, every rank
    receives identical batches (model-parallel input replication —
    reference ``create_multi_node_iterator``)."""
    if comm.host.size == 1:
        return _BatchIterator(dataset, batch_size, shuffle=shuffle, seed=seed)
    return _MasterBroadcastIterator(
        dataset, batch_size, comm, rank_master, shuffle, seed
    )


class _MasterBroadcastIterator:
    #: every process receives the identical batch — consumers assembling
    #: global arrays must treat it as replicated, not as a per-process
    #: data-parallel shard (see Trainer.batch_spec).
    replicated_batches = True

    def __init__(self, dataset, batch_size, comm, rank_master, shuffle, seed):
        self.comm = comm
        self.rank_master = rank_master
        self._inner = (
            _BatchIterator(dataset, batch_size, shuffle=shuffle, seed=seed)
            if comm.rank == rank_master
            else None
        )

    def __iter__(self):
        return self

    def __next__(self):
        if self.comm.rank == self.rank_master:
            try:
                batch = next(self._inner)
                payload = ("batch", batch)
            except StopIteration:
                payload = ("stop", None)
            payload = self.comm.bcast_obj(payload, self.rank_master)
        else:
            payload = self.comm.bcast_obj(None, self.rank_master)
        kind, batch = payload
        if kind == "stop":
            raise StopIteration
        return batch

    @property
    def epoch(self):
        return self._inner.epoch if self._inner is not None else None


def create_synchronized_iterator(
    dataset: Sequence[Any],
    batch_size: int,
    comm: CommunicatorBase,
    *,
    seed: int = 0,
    shuffle: bool = True,
) -> Iterable[list]:
    """Synchronized-shuffle iterator: every rank draws the *same* order from
    a shared seed — zero communication (the TPU-native version of the
    reference's synchronized iterator variant)."""
    del comm  # same seed on every process — nothing to exchange
    return _BatchIterator(dataset, batch_size, shuffle=shuffle, seed=seed)


__all__ = [
    "create_multi_node_iterator",
    "create_synchronized_iterator",
]
