"""Public testing utilities — the user-facing form of this repo's own
test harness.

The reference's users tested distributed code by launching pytest under
MPI (``mpiexec -n 2 pytest``, SURVEY.md section 4) with ``MPI.COMM_WORLD``
as the implicit fixture. The TPU-native analog is a single process with N
virtual CPU devices; these helpers package that recipe so downstream
projects don't have to rediscover it (device-count flags must be set
before JAX initialises, reference values must use CPU arithmetic, and the
key invariant — distributed result == single-device result — deserves a
one-call assertion).

Typical conftest.py in a downstream project::

    import chainermn_tpu.testing as cmt
    cmt.ensure_virtual_devices(8)      # BEFORE anything imports jax

    import pytest

    @pytest.fixture(scope="session")
    def comm():
        return cmt.make_test_communicator()

and in tests::

    cmt.assert_distributed_equals_single(
        distributed_fn, single_fn, comm, batch)
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

PyTree = Any


def ensure_virtual_devices(n: int = 8) -> None:
    """Arrange for ``n`` virtual CPU devices. Call BEFORE jax initialises
    (ideally before it is imported): the host-platform device count is a
    process-start XLA flag, not a runtime switch.

    Raises if jax is already initialised with fewer CPU devices — a later
    call cannot fix that, and silently continuing would make every
    mesh-of-``n`` test fail with confusing divisibility errors.
    """
    import re
    import sys

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif int(m.group(1)) < n:
        # Raise the pre-set count. XLA reads the flag at backend INIT, so
        # this works even after `import jax` — only a live backend (the
        # check below) makes it too late.
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}"
        )

    if "jax" in sys.modules:
        import jax

        from jax._src import xla_bridge as xb

        if xb._backends:
            have = len(jax.devices("cpu"))
            if have < n:
                raise RuntimeError(
                    f"jax already initialised with {have} CPU devices; "
                    f"ensure_virtual_devices({n}) must run before the "
                    "first jax backend use (put it at the top of "
                    "conftest.py)"
                )


def make_test_communicator(name: str = "naive", **kwargs):
    """The canonical hermetic test communicator: a CPU mesh that never
    touches (or hangs on) an accelerator plugin. See
    :class:`~chainermn_tpu.communicators.xla_communicator.NaiveCommunicator`
    for the platform-pinning contract.

    Also pins the DEFAULT device to CPU (as this repo's own conftest
    does): reference values computed eagerly in tests must use the same
    arithmetic as the CPU-mesh distributed computation — an accelerator
    default device's bf16 matmul passes would skew them by ~1e-3 and
    fail :func:`assert_distributed_equals_single` tolerances spuriously.
    """
    import jax

    from chainermn_tpu import create_communicator

    comm = create_communicator(name, **kwargs)
    if name == "naive":
        jax.config.update("jax_default_device", jax.devices("cpu")[0])
    return comm


def assert_allclose_tree(
    actual: PyTree,
    desired: PyTree,
    *,
    rtol: float = 1e-5,
    atol: float = 1e-6,
) -> None:
    """``np.testing.assert_allclose`` over two pytrees, leaf-wise, with the
    failing leaf's tree path in the error message."""
    import jax
    import numpy as np

    actual_leaves = jax.tree_util.tree_leaves_with_path(actual)
    desired_leaves = jax.tree_util.tree_leaves_with_path(desired)
    assert len(actual_leaves) == len(desired_leaves), (
        f"tree size mismatch: {len(actual_leaves)} vs {len(desired_leaves)}"
    )
    for (path_a, leaf_a), (path_d, leaf_d) in zip(
        actual_leaves, desired_leaves
    ):
        assert path_a == path_d, f"tree paths diverge: {path_a} vs {path_d}"
        np.testing.assert_allclose(
            np.asarray(leaf_a),
            np.asarray(leaf_d),
            rtol=rtol,
            atol=atol,
            err_msg=jax.tree_util.keystr(path_a),
        )


def assert_distributed_equals_single(
    distributed_fn: Callable,
    single_fn: Callable,
    comm,
    batch: PyTree,
    *,
    rtol: float = 1e-4,
    atol: float = 1e-5,
) -> None:
    """The reference's universal invariant (SURVEY.md section 4: "Key
    invariant tested everywhere"), as one call.

    Args:
      distributed_fn: ``distributed_fn(comm, batch) -> result`` — runs the
        distributed computation over the communicator's mesh (batch is the
        GLOBAL batch; shard it inside however the code under test does).
      single_fn: ``single_fn(batch) -> result`` — the single-device
        reference on the same global batch.
      comm: a communicator (typically :func:`make_test_communicator`).
      batch: the global input pytree.
    """
    assert_allclose_tree(
        distributed_fn(comm, batch),
        single_fn(batch),
        rtol=rtol,
        atol=atol,
    )


def seeded_batch(shape, seed: int = 0, *, scale: float = 1.0):
    """Deterministic synthetic f32 data — the same generator every example
    uses, exposed so downstream tests match docs/snippets exactly."""
    import numpy as np

    rng = np.random.RandomState(seed)
    return (rng.randn(*shape) * scale).astype(np.float32)


# ---------------------------------------------------------------------------
# Structural dependency analysis (the "measured, not asserted" convention
# for claims about communication — CLAUDE.md / the ppermute-count tests).
# ---------------------------------------------------------------------------

COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum_scatter", "pmin", "pmax", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "reduce_scatter",
})


def _subjaxprs(params):
    """(name, jaxpr-or-closed) pairs found in an eqn's params."""
    from jax.extend import core as jex_core

    out = []
    for k, v in params.items():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for item in vs:
            if isinstance(item, jex_core.ClosedJaxpr):
                out.append((k, item.jaxpr))
            elif isinstance(item, jex_core.Jaxpr):
                out.append((k, item))
    return out


def _taint_jaxpr(jaxpr, in_taint, targets):
    """Forward taint propagation: which jaxpr outputs data-depend on any
    primitive named in ``targets``. Precise through pjit/scan/cond/while/
    custom-AD calls; conservative (taint-all when any input is tainted OR
    a target exists inside) for anything unrecognised."""
    from jax.extend import core as jex_core

    Literal = jex_core.Literal
    env = {}

    def read(v):
        return False if isinstance(v, Literal) else env.get(v, False)

    def contains_target(j):
        for eqn in j.eqns:
            if eqn.primitive.name in targets:
                return True
            for _, sub in _subjaxprs(eqn.params):
                if contains_target(sub):
                    return True
        return False

    for v, t in zip(jaxpr.invars, in_taint):
        env[v] = t
    for v in jaxpr.constvars:
        env[v] = False

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [read(v) for v in eqn.invars]
        subs = _subjaxprs(eqn.params)
        if name in targets:
            outs = [True] * len(eqn.outvars)
        elif name == "scan":
            body = subs[0][1]
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            consts, carry = ins[:nc], ins[nc:nc + ncar]
            xs = ins[nc + ncar:]
            for _ in range(len(carry) + 1):  # carry fixpoint
                body_out = _taint_jaxpr(body, consts + carry + xs, targets)
                new_carry = [a or b for a, b in
                             zip(carry, body_out[:ncar])]
                if new_carry == carry:
                    break
                carry = new_carry
            outs = carry + body_out[ncar:]
        elif name == "while":
            sub_map = dict(subs)
            body = sub_map["body_jaxpr"]
            cond_j = sub_map["cond_jaxpr"]
            nb = eqn.params["body_nconsts"]
            ncc = eqn.params["cond_nconsts"]
            cconsts, bconsts = ins[:ncc], ins[ncc:ncc + nb]
            carry = ins[ncc + nb:]
            for _ in range(len(carry) + 1):
                body_out = _taint_jaxpr(body, bconsts + carry, targets)
                new_carry = [a or b for a, b in zip(carry, body_out)]
                if new_carry == carry:
                    break
                carry = new_carry
            # Control dependency: a collective-derived loop PREDICATE
            # decided how many iterations shaped every carry value.
            if any(_taint_jaxpr(cond_j, cconsts + carry, targets)):
                carry = [True] * len(carry)
            outs = carry
        elif name == "cond":
            branches = [s for k, s in subs if k == "branches"]
            per = [_taint_jaxpr(b, ins[1:], targets) for b in branches]
            outs = [any(col) for col in zip(*per)] if per else []
            # Control dependency: a collective-derived predicate SELECTS
            # the output — every output inherits its taint.
            if ins and ins[0]:
                outs = [True] * len(outs)
        elif subs and len(subs) == 1 and (
            len(subs[0][1].invars) == len(eqn.invars)
            and len(subs[0][1].outvars) == len(eqn.outvars)
        ):
            # pjit / remat / closed_call / custom_*_call with a 1:1
            # operand mapping: recurse precisely.
            outs = _taint_jaxpr(subs[0][1], ins, targets)
        elif subs:
            t = any(ins) or any(contains_target(s) for _, s in subs)
            outs = [t] * len(eqn.outvars)
        else:
            t = any(ins)
            outs = [t] * len(eqn.outvars)
        for v, t in zip(eqn.outvars, outs):
            env[v] = t

    return [read(v) for v in jaxpr.outvars]


def collective_taint(fn, *args, targets=COLLECTIVE_PRIMITIVES, axis_env=()):
    """Trace ``fn(*args)`` and report, per output leaf, whether it
    DATA-DEPENDS on any collective primitive in ``targets`` — e.g. to
    certify that a double-buffered optimizer's parameter update is
    independent of the SAME step's gradient allreduce (the precondition
    for overlapping the collective with compute; the reference bought
    this with a side CUDA stream, ``optimizers.py`` † — here it is a
    property of the dependency graph that XLA's async scheduler can
    exploit).

    Args:
      axis_env: ``[(axis_name, size), ...]`` for tracing named-axis
        collectives outside shard_map.

    Returns:
      A pytree of bools matching ``fn``'s output structure.
    """
    import jax

    closed, shape_tree = jax.make_jaxpr(
        fn, axis_env=list(axis_env), return_shape=True
    )(*args)
    flat_taint = _taint_jaxpr(
        closed.jaxpr, [False] * len(closed.jaxpr.invars), set(targets)
    )
    leaves, treedef = jax.tree.flatten(
        shape_tree, is_leaf=lambda x: x is None
    )
    assert len(leaves) == len(flat_taint), (len(leaves), len(flat_taint))
    return jax.tree.unflatten(treedef, flat_taint)


def count_primitives(fn, *args, axis_env=()):
    """Count primitive occurrences in the traced jaxpr of ``fn(*args)``,
    recursing into subjaxprs (pjit/scan/cond/...). The tool behind the
    structural collective-count tests (the ppermute-count convention:
    claims about communication are measured on the program, not asserted
    in prose). Returns ``{primitive_name: count}``."""
    import collections

    import jax

    closed = jax.make_jaxpr(fn, axis_env=list(axis_env))(*args)
    counts: collections.Counter = collections.Counter()

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            counts[eqn.primitive.name] += 1
            for _, sub in _subjaxprs(eqn.params):
                walk(sub)

    walk(closed.jaxpr)
    return dict(counts)


def collect_collectives(fn, *args, axis_env=(),
                        primitives=("reduce_scatter", "all_gather",
                                    "all_to_all", "ppermute", "psum")):
    """Trace ``fn(*args)`` and collect ``(primitive, axis_names, dtype)``
    for every matching collective, recursing into subjaxprs —
    ``axis_names`` normalised to a tuple. The shared scaffolding of the
    which-dtype-rides-which-axis structural certificates (the
    topology-aware wire tests)."""
    import jax
    from jax.extend import core as jex_core

    closed = jax.make_jaxpr(fn, axis_env=list(axis_env))(*args)
    seen: list = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in primitives:
                axes = eqn.params.get("axis_name")
                if not isinstance(axes, tuple):
                    axes = (axes,)
                dt = (eqn.invars[0].aval.dtype
                      if not isinstance(eqn.invars[0], jex_core.Literal)
                      else eqn.invars[0].val.dtype)
                seen.append((eqn.primitive.name, axes, str(dt)))
            for _, sub in _subjaxprs(eqn.params):
                walk(sub)

    walk(closed.jaxpr)
    return seen


__all__ = [
    "ensure_virtual_devices",
    "make_test_communicator",
    "assert_allclose_tree",
    "assert_distributed_equals_single",
    "seeded_batch",
    "collective_taint",
    "count_primitives",
    "collect_collectives",
    "COLLECTIVE_PRIMITIVES",
]
