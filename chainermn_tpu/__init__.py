"""chainermn_tpu — a TPU-native distributed training framework.

A brand-new framework with the capabilities of ChainerMN (reference:
``shu65/chainermn``), re-designed for TPU: a single jitted SPMD program over a
``jax.sharding.Mesh`` (ICI x DCN), XLA collectives instead of MPI+NCCL, and
Pallas kernels for the hot fused ops.

Public API (mirrors the reference package surface, see SURVEY.md section 2):

- :func:`create_communicator` — communicator factory
  (``chainermn/communicators/__init__.py`` (dagger) in the reference).
- :func:`create_multi_node_optimizer` — data-parallel optimizer wrapper
  (``chainermn/optimizers.py`` (dagger)).
- :func:`scatter_dataset`, :func:`create_empty_dataset` — data layer
  (``chainermn/datasets/`` (dagger)).
- :mod:`chainermn_tpu.functions` — differentiable cross-rank send/recv and
  collective functions (``chainermn/functions/`` (dagger)).
- :mod:`chainermn_tpu.links` — ``MultiNodeChainList``,
  ``MultiNodeBatchNormalization``, ``create_mnbn_model``
  (``chainermn/links/`` (dagger)).
- :mod:`chainermn_tpu.extensions` — multi-node evaluator, fault-tolerant
  checkpointer (npz + orbax backends) (``chainermn/extensions/`` (dagger)).
- :mod:`chainermn_tpu.parallel` — the TPU-era parallelism library the
  reference lacked: tensor/pipeline (GPipe + 1F1B)/sequence/expert
  parallelism, ZeRO, FSDP (see ``docs/parallelism.md``).
- :mod:`chainermn_tpu.training` — jitted train-step builder (gradient
  accumulation, device prefetch) and the Trainer loop.
- :mod:`chainermn_tpu.testing` — downstream test harness helpers (the
  ``mpiexec -n N pytest`` recipe, TPU-style).

The dagger convention follows SURVEY.md: the reference mount was empty at
survey time, so citations are to the public upstream layout.
"""

from chainermn_tpu import _jax_compat  # noqa: F401  (import installs the gate)
from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.communicators.base import ANY_SOURCE, CommunicatorBase
from chainermn_tpu.optimizers import (
    create_local_sgd,
    create_multi_node_optimizer,
)
from chainermn_tpu.datasets import scatter_dataset, create_empty_dataset
from chainermn_tpu.iterators import (
    create_multi_node_iterator,
    create_synchronized_iterator,
)
from chainermn_tpu.extensions.evaluator import create_multi_node_evaluator
from chainermn_tpu.extensions.checkpoint import create_multi_node_checkpointer
from chainermn_tpu import global_except_hook  # noqa: F401  (import installs nothing)

__version__ = "0.5.0"

__all__ = [
    "create_communicator",
    "ANY_SOURCE",
    "CommunicatorBase",
    "create_local_sgd",
    "create_multi_node_optimizer",
    "scatter_dataset",
    "create_empty_dataset",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
    "create_multi_node_evaluator",
    "create_multi_node_checkpointer",
]
