"""Cross-rank request journeys (ISSUE 17): one causal id per request,
hop-numbered span ids over every host-plane hop.

The trace plane measures each hop in isolation — ``route`` on the
router, ``kv_transfer`` at adoption, ``queue_wait``/``prefill``/
``finish`` on whichever scheduler ends up decoding — but a
disaggregated request scatters those events across N processes' JSONL
files with no shared causal key. This module is the key: a
:class:`JourneyContext` (journey id + a hop counter) rides the
``Request`` object in process, and rides the ``export_kv`` /
``tree_push`` payload dicts across processes, so every per-request
event gains three fields:

- ``journey`` — the request's cluster-unique journey id,
- ``span`` — this event's span id, ``"<journey>/<hop>"`` (hops number
  the causal chain, so merged timelines order WITHOUT trusting any
  clock),
- ``parent`` — the previous hop's span id (absent on hop 0).

Everything here is host-side metadata on already-host-side event
emission: no new jitted code anywhere, so recorder-on and recorder-off
programs lower to identical HLO (the structural convention the
serving tests pin). The reference framework had no tracing plane at
all — its debugging story was print-per-rank under ``mpiexec``
(``chainermn/communicators/mpi_communicator_base.py`` †); the journey
layer is what The Big Send-off (2504.18658) argues distributed serving
actually needs: *measured, attributed* per-request timelines.

The merge/report half lives here too (:func:`merge_journeys`,
:func:`decompose_ttft`) — one owner for the causal-chain rules, loaded
by ``tools/trace_report.py`` via file path (this module is pure
stdlib; it must never import jax).
"""

from __future__ import annotations

import itertools
import os
import threading
from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Optional

#: the key a journey snapshot rides under inside host-plane payload
#: dicts (``export_kv`` payloads, ``tree_push`` payloads). Engines
#: ignore unknown payload keys, so pre-journey peers keep adopting.
WIRE_KEY = "journey"

_counter = itertools.count()
_lock = threading.Lock()


def _mint_id(request_id: Optional[str]) -> str:
    """A cluster-unique journey id. The request_id prefix keeps merged
    reports readable; the pid+counter suffix keeps ids unique when two
    router processes (or two windows of one) reuse request ids."""
    with _lock:
        n = next(_counter)
    base = str(request_id) if request_id is not None else "j"
    return f"{base}@{os.getpid():x}.{n:x}"


@dataclass
class JourneyContext:
    """Journey id + hop counter + the last minted span (the next
    hop's ``parent``). Mutated only through :meth:`begin_hop` so the
    chain stays linear — a request's journey is a path, not a DAG
    (preemption/migration extend it; nothing forks it)."""

    journey: str
    hop: int = 0
    last_span: Optional[str] = None

    def begin_hop(self) -> dict:
        """Mint the next hop's event fields and advance the chain."""
        span = f"{self.journey}/{self.hop}"
        fields = {"journey": self.journey, "span": span}
        if self.last_span is not None:
            fields["parent"] = self.last_span
        self.hop += 1
        self.last_span = span
        return fields

    # ---- wire form (payload dicts over send_obj/recv_obj) -----------

    def to_wire(self) -> dict:
        return {"id": self.journey, "hop": self.hop,
                "last_span": self.last_span}

    @classmethod
    def from_wire(cls, wire: Mapping[str, Any]) -> "JourneyContext":
        return cls(journey=str(wire["id"]), hop=int(wire["hop"]),
                   last_span=wire.get("last_span"))


def new(request_id: Optional[str] = None) -> JourneyContext:
    return JourneyContext(_mint_id(request_id))


def ensure(request) -> JourneyContext:
    """Attach a context to ``request`` ONLY when absent — the
    keep_arrival rule's sibling: every (re)submission front door calls
    this, so a requeue, migration or cross-process adoption can never
    silently restart the chain."""
    ctx = getattr(request, "_journey", None)
    if ctx is None:
        ctx = new(getattr(request, "request_id", None))
        request._journey = ctx
    return ctx


def fields(request) -> dict:
    """The journey/span/parent fields for one event about ``request``
    — mints (and consumes) the next hop. Total: a request that never
    passed a front door gets its context here."""
    return ensure(request).begin_hop()


def attach_payload(payload: dict, request) -> dict:
    """Snapshot ``request``'s context into a host-plane payload dict so
    a peer process can continue the chain (:func:`adopt_payload`)."""
    payload[WIRE_KEY] = ensure(request).to_wire()
    return payload


def adopt_payload(request, payload: Mapping[str, Any]) -> None:
    """Continue a journey shipped inside ``payload`` on this process's
    ``request`` object (the decode rank of a multi-process handoff).
    A payload without a journey leaves the request untouched —
    :func:`ensure` at the admission site then mints a local chain, so
    pre-journey peers still produce complete (single-process)
    journeys."""
    wire = payload.get(WIRE_KEY)
    if wire:
        request._journey = JourneyContext.from_wire(wire)


# ----------------------------------------------------------------------
# Merge: per-rank JSONL files -> per-request causal timelines
# ----------------------------------------------------------------------

#: |residual| floor for the TTFT decomposition check: every dur_s in
#: the trace is rounded to 1e-9 s, and a decomposition sums a handful
#: of them — allow a microsecond before consulting clock uncertainty.
ROUNDING_TOLERANCE_S = 1e-6


def _span_hop(span: Any) -> int:
    """Hop number out of a span id (``"<journey>/<hop>"``); malformed
    ids sort last rather than raising (a merge tool must report a
    corrupt trace, not crash on it)."""
    try:
        return int(str(span).rsplit("/", 1)[1])
    except (IndexError, ValueError):
        return 1 << 30


def clock_offsets(events: Iterable[Mapping[str, Any]]) -> dict:
    """Per-rank clock alignment from ``clock_sync`` events (see
    :mod:`~chainermn_tpu.observability.clocksync`): rank r's epoch
    stamps shift by ``offset_s`` onto its sync peer's clock. The LAST
    sync per rank wins (offsets drift; the freshest estimate is the
    honest one). Returns ``{"offsets": {rank: {offset_s,
    uncertainty_s, peer}}, "max_uncertainty_s": float}`` — the error
    bar every cross-rank comparison must carry."""
    offsets: dict = {}
    for ev in events:
        if ev.get("kind") != "clock_sync":
            continue
        rank = ev.get("rank", 0)
        offsets[rank] = {
            "offset_s": float(ev.get("offset_s", 0.0)),
            "uncertainty_s": float(ev.get("uncertainty_s", 0.0)),
            "peer": ev.get("peer"),
        }
    max_u = max((o["uncertainty_s"] for o in offsets.values()),
                default=0.0)
    return {"offsets": offsets, "max_uncertainty_s": round(max_u, 9)}


def _adjust_t(ev: Mapping[str, Any], offsets: Mapping) -> Optional[float]:
    t = ev.get("t")
    if t is None:
        return None
    off = offsets.get(ev.get("rank", 0))
    return round(float(t) + (off["offset_s"] if off else 0.0), 6)


def decompose_ttft(events: list) -> Optional[dict]:
    """Critical-path decomposition of one journey's TTFT from its
    (hop-ordered) events. Components:

    - ``queue_wait_s`` — the whole-journey admission wait
      (``queue_wait`` events up to the first token),
    - ``handoff_s`` — disaggregated export→adoption latency
      (``kv_transfer`` events),
    - ``prefill_s`` — prefill-event duration NET of the handoff it
      contains on the adoption path (``admit_prefilled``'s ``dur_s``
      spans admission→adoption, which includes the transfer — the
      transfer must not be billed twice),
    - ``preempt_gap_s`` — the residual ``ttft_s - (queue + prefill +
      handoff)``: requeue gaps and re-fill work of a pre-first-token
      preemption, which no single event measures directly.

    ``residual_s`` is that same residual reported HONESTLY: for a
    journey that was never preempted before its first token it must be
    ~0 (sub-microsecond rounding), and the merge check holds every
    journey's ``|residual_s|`` against rounding + clock uncertainty —
    a blown check means the merger grouped the wrong events, exactly
    the failure a causal-id layer exists to catch. Returns None when
    the journey has no TTFT-bearing prefill event (e.g. finished at
    the prefill replica, or the trace was truncated)."""
    ttft_ev = None
    for ev in events:
        if (ev.get("kind") == "serving" and ev.get("phase") == "prefill"
                and ev.get("ttft_s") is not None):
            ttft_ev = ev
            break
    if ttft_ev is None:
        return None
    cut = _span_hop(ttft_ev.get("span"))
    pre = [ev for ev in events if _span_hop(ev.get("span")) <= cut]
    queue = sum(float(ev.get("dur_s") or 0.0) for ev in pre
                if ev.get("kind") == "serving"
                and ev.get("phase") == "queue_wait")
    handoff = sum(float(ev.get("dur_s") or 0.0) for ev in pre
                  if ev.get("kind") == "kv_transfer")
    prefill_raw = sum(float(ev.get("dur_s") or 0.0) for ev in pre
                      if ev.get("kind") == "serving"
                      and ev.get("phase") == "prefill")
    prefill = max(0.0, prefill_raw - handoff)
    ttft = float(ttft_ev["ttft_s"])
    preempts = sum(1 for ev in pre if ev.get("kind") == "serving"
                   and ev.get("phase") == "preempt")
    residual = ttft - (queue + prefill + handoff)
    gap = residual if preempts else 0.0
    out = {
        "ttft_s": round(ttft, 9),
        "queue_wait_s": round(queue, 9),
        "prefill_s": round(prefill, 9),
        "handoff_s": round(handoff, 9),
        "preempt_gap_s": round(gap, 9),
        "residual_s": round(residual - gap, 9),
        "preempts_before_first_token": preempts,
    }
    finish = next((ev for ev in events if ev.get("kind") == "serving"
                   and ev.get("phase") == "finish"), None)
    if finish is not None and finish.get("dur_s") is not None:
        total = float(finish["dur_s"])
        out["total_s"] = round(total, 9)
        out["decode_s"] = round(max(0.0, total - ttft), 9)
    return out


def merge_journeys(events: Iterable[Mapping[str, Any]], *,
                   top: int = 5) -> dict:
    """Merge (possibly multi-file, multi-rank) trace events into
    per-request causal journeys. Ordering inside a journey is by HOP
    NUMBER — the clock-free causal order the span ids encode; the
    clock-sync offsets only shift the displayed epoch stamps
    (``t_adj``) and set the error bar. Returns the ``journeys``
    report section (machine-readable; ``tools/trace_report.py
    --journeys`` renders it)."""
    events = list(events)
    clock = clock_offsets(events)
    by_id: dict = {}
    for ev in events:
        jid = ev.get("journey")
        if jid is not None and ev.get("span") is not None:
            by_id.setdefault(jid, []).append(ev)

    journeys = []
    n_orphans = 0
    n_complete = 0
    for jid, evs in by_id.items():
        evs.sort(key=lambda ev: _span_hop(ev.get("span")))
        spans = {ev.get("span") for ev in evs}
        orphans = sorted(
            str(ev.get("span")) for ev in evs
            if ev.get("parent") is not None
            and ev.get("parent") not in spans
        )
        n_orphans += len(orphans)
        hops = [_span_hop(ev.get("span")) for ev in evs]
        contiguous = hops == list(range(len(hops)))
        complete = any(ev.get("kind") == "serving"
                       and ev.get("phase") == "finish" for ev in evs)
        n_complete += bool(complete)
        decomp = decompose_ttft(evs)
        request = next((ev.get("request") for ev in evs
                        if ev.get("request") is not None), None)
        timeline = [{
            "hop": _span_hop(ev.get("span")),
            "span": ev.get("span"),
            "parent": ev.get("parent"),
            "kind": ev.get("kind"),
            "phase": ev.get("phase"),
            "rank": ev.get("rank"),
            "pid": ev.get("pid"),
            "t": ev.get("t"),
            "t_adj": _adjust_t(ev, clock["offsets"]),
            "t_mono": ev.get("t_mono"),
            "dur_s": ev.get("dur_s"),
        } for ev in evs]
        journeys.append({
            "journey": jid,
            "request": request,
            "n_spans": len(evs),
            "ranks": sorted({ev.get("rank", 0) for ev in evs}),
            "pids": sorted({ev.get("pid", 0) for ev in evs}),
            "complete": complete,
            "contiguous": contiguous,
            "orphan_spans": orphans,
            "decomposition": decomp,
            "spans": timeline,
        })

    def slow_key(j):
        d = j["decomposition"]
        return -(d["ttft_s"] if d else -1.0)

    journeys.sort(key=slow_key)
    return {
        "n_journeys": len(journeys),
        "n_complete": n_complete,
        "n_orphan_spans": n_orphans,
        "clock": clock,
        "slowest": journeys[:max(0, int(top))],
    }


def check_journeys(events: Iterable[Mapping[str, Any]], *,
                   expect: Optional[int] = None) -> list:
    """The acceptance predicate (tests + dryrun phase Q): every
    journey is a complete, contiguous, orphan-free causal chain whose
    TTFT decomposition sums back to the measured ``ttft_s`` within
    rounding + the reported clock uncertainty. Returns a list of
    problem strings — empty means the trace reconstructs cleanly."""
    report = merge_journeys(events, top=10 ** 9)
    tol = (ROUNDING_TOLERANCE_S
           + report["clock"]["max_uncertainty_s"])
    problems = []
    if expect is not None and report["n_journeys"] != expect:
        problems.append(
            f"expected {expect} journeys, merged {report['n_journeys']}")
    for j in report["slowest"]:
        tag = f"journey {j['journey']}"
        if not j["complete"]:
            problems.append(f"{tag}: no finish event")
        if not j["contiguous"]:
            problems.append(f"{tag}: hop numbering has gaps")
        if j["orphan_spans"]:
            problems.append(
                f"{tag}: orphan spans {j['orphan_spans']}")
        d = j["decomposition"]
        if d is None:
            problems.append(f"{tag}: no TTFT-bearing prefill event")
        elif abs(d["residual_s"]) > tol:
            problems.append(
                f"{tag}: decomposition residual {d['residual_s']}s "
                f"exceeds tolerance {tol}s")
    return problems


__all__ = [
    "JourneyContext",
    "ROUNDING_TOLERANCE_S",
    "WIRE_KEY",
    "adopt_payload",
    "attach_payload",
    "check_journeys",
    "clock_offsets",
    "decompose_ttft",
    "ensure",
    "fields",
    "merge_journeys",
    "new",
]
