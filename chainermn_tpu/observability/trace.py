"""Structured trace/event recorder — the collective-wire and step-time
telemetry layer (ISSUE 2 tentpole; docs/observability.md).

SURVEY.md section 5 records that the reference had no observability
beyond rank-0 ``print`` gating; this module measures the thing the
framework exists to optimize: bytes and time on the collective wire,
per step, per process. Three properties are load-bearing:

- **Host-side timestamps only.** Instrumentation wraps the *eager* API
  surface (communicator calls, trainer loop phases, host-plane object
  collectives); it never enters a jitted program, so an instrumented
  step lowers to EXACTLY the same HLO — zero added device-plane
  collectives (structural test: ``tests/test_trace.py``). Durations of
  eager device-plane calls are dispatch-to-return under JAX's async
  dispatch; set ``CHAINERMN_TPU_TRACE_SYNC=1`` (or ``enable(sync=True)``)
  to block on results for true wall durations — a measurement mode, not
  the default, because the sync serialises pipelining.
- **Near-zero overhead when off.** Every instrumentation site starts
  with ``trace.active()``; disabled, that is one global read and the
  site adds no timing, no allocation, no pickling.
- **One schema, versioned.** Every event is one JSON object with
  ``schema`` (:data:`TRACE_SCHEMA`), ``kind``, ``t`` (epoch seconds),
  ``pid``, ``rank``; kinds: ``meta``, ``collective``, ``step``, ``span``,
  ``dispatch`` (autotune provenance), ``straggler``, ``profile_start`` /
  ``profile_stop``, ``wire`` / ``overlap_config`` (ISSUE 3 per-bucket
  reduction telemetry), ``serving`` (ISSUE 4 queue_wait / prefill /
  decode_step / finish phases, plus the ISSUE 11 ``preempt`` phase),
  ``speculate`` (ISSUE 5 per-tick
  drafted/accepted counts), ``prefix_cache`` (ISSUE 7 per-admission
  prompt/hit/prefilled token counts + COW copies), ``prefill_chunk``
  (ISSUE 11 per-advanced-fill-row chunk telemetry from the mixed
  step).
  ``tools/trace_report.py`` summarizes a JSONL file;
  :func:`chrome_trace` converts to the ``chrome://tracing`` / Perfetto
  format.

Enable programmatically (:func:`enable`) or by environment:
``CHAINERMN_TPU_TRACE=<path.jsonl>`` turns the recorder on at first use
in any process — which is how ``bench.py``'s child processes and the
chip-capture path inherit tracing without plumbing.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Iterable, Mapping, Optional

#: Version stamped into every event. Bump on any incompatible field
#: change; consumers (tools/trace_report.py) key on it.
TRACE_SCHEMA = 1

_ENV_PATH = "CHAINERMN_TPU_TRACE"
_ENV_SYNC = "CHAINERMN_TPU_TRACE_SYNC"

#: In-memory event cap per recorder — a runaway loop must not eat the
#: host; overflow increments ``dropped`` (file writes continue; the
#: metrics plane exports the count live as ``trace_dropped_events``).
MAX_BUFFERED_EVENTS = 200_000

# The nearest-rank percentile rule, shared with the metrics histograms
# (ISSUE 6 satellite: one owner in observability/stats.py). This module
# is ALSO loaded by file path from tools/trace_report.py with no package
# context (to avoid paying a jax import in a report tool) — load stats
# the same way there.
if __package__:
    from chainermn_tpu.observability.stats import jain_index, nearest_rank
else:  # pragma: no cover - exercised via tools/trace_report.py
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "_obs_stats",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "stats.py"),
    )
    _mod = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_mod)
    nearest_rank = _mod.nearest_rank
    jain_index = _mod.jain_index

#: Event sinks (ISSUE 6): callables ``sink(event_dict)`` invoked for
#: every event ANY recorder emits — the metrics tap and the flight ring
#: register here, so every already-instrumented site feeds the live
#: plane with zero new call sites. Sinks fire only while a recorder is
#: active; a raising sink is dropped from that event, never propagated
#: into an instrumentation site.
_sinks: list = []


def add_sink(fn) -> None:
    """Register an event sink (idempotent)."""
    if fn not in _sinks:
        _sinks.append(fn)


def remove_sink(fn) -> None:
    try:
        _sinks.remove(fn)
    except ValueError:
        pass


def _process_rank() -> int:
    """Host-plane rank WITHOUT triggering jax backend discovery (the
    recorder must be usable in processes that never import jax — the
    bench parent — and before backend init): native-TCP env first, then
    the jax distributed client state if someone initialised it."""
    r = os.environ.get("CHAINERMN_TPU_RANK")
    if r is not None:
        try:
            return int(r)
        except ValueError:
            pass
    try:
        from jax._src import distributed

        state = distributed.global_state
        if state.client is not None:
            return int(state.process_id)
    except Exception:
        pass
    return 0


class Recorder:
    """Append-only structured event stream, optionally write-through to
    a JSONL file (append mode, line-buffered: a crash loses at most the
    current line). Thread-safe: the trainer's prefetch generator and the
    main loop may both record."""

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        sync: bool = False,
        mode: str = "a",
        meta: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self.path = path
        self.sync = sync
        self.events: list[dict] = []
        self.dropped = 0
        #: epoch seconds of the most recent event — the exporter's
        #: ``/healthz`` last-event-age signal.
        self.last_event_t: float = 0.0
        self._lock = threading.Lock()
        self._rank = _process_rank()
        self._file = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._file = open(path, mode, buffering=1)
        self.event(
            "meta",
            started_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            sync=bool(sync),
            **dict(meta or {}),
        )

    # ------------------------------------------------------------------

    def event(self, kind: str, **fields: Any) -> dict:
        """Record one event; returns the event dict (callers may inspect
        it in tests). Non-JSON-serialisable field values are repr()'d
        rather than ever raising out of an instrumentation site."""
        ev = {
            "schema": TRACE_SCHEMA,
            "kind": kind,
            "t": round(time.time(), 6),
            # Monotonic sibling stamp (ISSUE 17 satellite): ``t`` is
            # epoch (comparable across processes once clock-synced but
            # steppable by NTP/admin), ``t_mono`` is perf_counter
            # (process-local, step-free) — same-process ordering in
            # the journey merger reads THIS, never the wall clock.
            "t_mono": round(time.perf_counter(), 9),
            "pid": os.getpid(),
            "rank": self._rank,
            **fields,
        }
        with self._lock:
            if len(self.events) < MAX_BUFFERED_EVENTS:
                self.events.append(ev)
            else:
                self.dropped += 1
            if self._file is not None:
                try:
                    line = json.dumps(ev)
                except (TypeError, ValueError):
                    ev = {k: (v if _jsonable(v) else repr(v))
                          for k, v in ev.items()}
                    line = json.dumps(ev)
                try:
                    self._file.write(line + "\n")
                except (OSError, ValueError):
                    # full disk / closed file must never break training
                    self._file = None
        self.last_event_t = ev["t"]
        # Sinks OUTSIDE the lock: a sink may inspect this recorder (the
        # metrics health hook reads .dropped) without deadlocking, and a
        # slow sink must not serialise other recording threads.
        for sink in tuple(_sinks):
            try:
                sink(ev)
            except Exception:
                pass
        return ev

    def collective(
        self,
        op: str,
        *,
        nbytes: Optional[int] = None,
        dur_s: Optional[float] = None,
        plane: str = "device",
        wire_dtype: Optional[str] = None,
        provenance: Optional[dict] = None,
        **extra: Any,
    ) -> dict:
        """One collective-wire counter event. ``provenance`` is the
        autotune decision record behind an ``'auto'``-resolved
        configuration (name/winner/source/key), attached so every auto
        collective in a trace names why it took the path it took."""
        fields: dict = {"op": op, "plane": plane}
        if nbytes is not None:
            fields["nbytes"] = int(nbytes)
        if dur_s is not None:
            fields["dur_s"] = round(float(dur_s), 9)
        if wire_dtype is not None:
            fields["wire_dtype"] = str(wire_dtype)
        if provenance is not None:
            fields["provenance"] = provenance
        fields.update(extra)
        return self.event("collective", **fields)

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    if self.dropped:
                        self._file.write(json.dumps({
                            "schema": TRACE_SCHEMA, "kind": "meta",
                            "t": round(time.time(), 6),
                            "t_mono": round(time.perf_counter(), 9),
                            "pid": os.getpid(), "rank": self._rank,
                            "dropped_events": self.dropped,
                        }) + "\n")
                    self._file.close()
                except (OSError, ValueError):
                    pass
                self._file = None


def _jsonable(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


# ----------------------------------------------------------------------
# Global recorder
# ----------------------------------------------------------------------

_active: Optional[Recorder] = None
_env_checked = False


def enable(
    path: Optional[str] = None,
    *,
    sync: Optional[bool] = None,
    mode: str = "a",
    meta: Optional[Mapping[str, Any]] = None,
) -> Recorder:
    """Install (and return) the process-global recorder. ``path=None``
    keeps events in memory only (tests). Replaces any prior recorder
    (closing its file)."""
    global _active, _env_checked
    if sync is None:
        sync = bool(os.environ.get(_ENV_SYNC))
    # Construct FIRST: if the path is unwritable this raises with the
    # previous recorder still installed and functional — never leave a
    # closed (file-less) recorder as the active one, silently buffering
    # events nobody will ever see.
    new = Recorder(path, sync=sync, mode=mode, meta=meta)
    if _active is not None:
        _active.close()
    _env_checked = True
    _active = new
    return _active


def disable() -> None:
    """Tear down the global recorder (file closed; events discarded)."""
    global _active
    if _active is not None:
        _active.close()
        _active = None


def active() -> Optional[Recorder]:
    """The global recorder, or None when tracing is off. First call
    honours ``CHAINERMN_TPU_TRACE=<path>`` — the env contract that lets
    subprocesses (bench children, the capture script's stages) inherit
    tracing."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        _env_checked = True
        path = os.environ.get(_ENV_PATH)
        if path:
            try:
                enable(path)
            except OSError:
                pass  # unwritable path must not break the workload
    return _active


@contextlib.contextmanager
def span(name: str, kind: str = "span", **fields: Any):
    """Timed span event (recorded at exit, with ``dur_s`` and ``ok``).
    Yields a mutable dict merged into the event — callers may attach
    results discovered inside the block. No-op when tracing is off."""
    rec = active()
    if rec is None:
        yield {}
        return
    extra: dict = {}
    t0 = time.perf_counter()
    try:
        yield extra
    except BaseException:
        rec.event(kind, name=name, dur_s=round(time.perf_counter() - t0, 9),
                  ok=False, **{**fields, **extra})
        raise
    rec.event(kind, name=name, dur_s=round(time.perf_counter() - t0, 9),
              ok=True, **{**fields, **extra})


def sync_point(x: Any) -> Any:
    """Block on ``x`` when the recorder is in sync mode (true wall
    durations for eager device-plane calls); identity otherwise."""
    rec = _active
    if rec is not None and rec.sync:
        import jax

        jax.block_until_ready(x)
    return x


def tree_nbytes(tree: Any) -> Optional[int]:
    """Total payload bytes of an array pytree (None when unknowable) —
    the byte counter behind the wire events. Never raises."""
    try:
        import jax

        total = 0
        for leaf in jax.tree.leaves(tree):
            nb = getattr(leaf, "nbytes", None)
            if nb is None:
                import numpy as np

                nb = np.asarray(leaf).nbytes
            total += int(nb)
        return total
    except Exception:
        return None


def obj_nbytes(obj: Any) -> Optional[int]:
    """Pickled size of a host-plane object payload. Only called when
    tracing is active (it costs one pickle — host-plane objects are
    metadata-sized by convention, never gradients)."""
    try:
        import pickle

        return len(pickle.dumps(obj, protocol=4))
    except Exception:
        return None


# ----------------------------------------------------------------------
# Exports
# ----------------------------------------------------------------------

def read_jsonl(path: str) -> list[dict]:
    """Parse a trace JSONL file, skipping unparseable lines (a crashed
    writer may leave a torn tail)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def summarize_overlap(events: Iterable[Mapping[str, Any]]) -> Optional[dict]:
    """Comm/compute-overlap rollup from ``wire`` + ``overlap_config``
    events (ISSUE 3: the consumer side of the per-bucket wire events;
    one owner shared by ``tools/trace_report.py`` and bench).

    Two wire-event flavours feed it:

    - trace-time layout events (in-jit bucketed schedules; no
      ``dur_s``): counted per schedule with their ``overlapped`` flag —
      what the compiled program COMMITTED to. Events carrying a
      ``composition`` signature (ISSUE 12: one event per bucket per
      STAGE) group under ``compositions`` instead, keyed by signature
      with a per-stage bytes/time table — the consumer side of the
      composed schedules' stage events. Stage events carrying a
      ``slice`` address (ISSUE 15: sliced compositions emit one event
      per stage PER SLICE) additionally group under the stage row's
      ``slices`` sub-table (``s<i>`` -> n/bytes and, when measured,
      ``dur_ms``/``blocked_ms``), while the stage row keeps the
      across-slice totals — per-slice columns without disturbing
      unsliced rows;
    - measured events (the eager ``OverlappedBucketReducer``; ``dur_s``
      = dispatch->ready, ``blocked_s`` = wait actually paid at
      collect): aggregated into comm time total vs comm time hidden
      behind compute, and the ``hidden_fraction`` between them.

    ``sched_search`` events (ISSUE 16: the cost-model schedule search's
    audit record — predicted prices for every ranked arm, measured ms
    for the arms actually timed, the model error vs the measurement
    spread) land under ``sched_search``: per-signature
    predicted/measured rows plus the mode/provenance/error header the
    report's loud-flag rule keys on — and each matching composition row
    above gains a ``predicted_ms`` column.

    Returns None when the trace carries none (section omitted)."""
    configs: list[dict] = []
    layout: dict = {}
    composed: dict = {}
    search: Optional[dict] = None
    n_measured = 0
    comm_s = 0.0
    blocked_s = 0.0
    for ev in events:
        kind = ev.get("kind")
        if kind == "sched_search":
            rows: dict = {}
            pred = ev.get("predicted_ms") or {}
            meas = ev.get("measured_ms") or {}
            for sig in sorted(set(pred) | set(meas)):
                row: dict = {}
                if sig in pred:
                    row["predicted_ms"] = round(float(pred[sig]), 4)
                if sig in meas:
                    row["measured_ms"] = round(float(meas[sig]), 4)
                else:
                    row["skipped"] = True
                rows[sig] = row
            search = {
                "mode": ev.get("mode"),
                "provenance": ev.get("provenance"),
                "rows": rows,
            }
            for k in ("err_pct", "spread_pct"):
                if ev.get(k) is not None:
                    search[k] = float(ev[k])
        elif kind == "overlap_config":
            configs.append({
                k: ev.get(k)
                for k in ("double_buffering", "staleness", "schedule",
                          "donate")
            })
        elif kind == "wire":
            dur = ev.get("dur_s")
            if ev.get("composition"):
                sig = str(ev["composition"])
                row = composed.setdefault(sig, {
                    "schedule": str(ev.get("schedule", sig)),
                    "buckets": 0, "nbytes": 0, "overlapped": 0,
                    "stages": {},
                })
                # stage_index 0 marks a bucket's first stage event —
                # one bucket, not one per stage
                if not ev.get("stage_index"):
                    row["buckets"] += 1
                    row["overlapped"] += 1 if ev.get("overlapped") else 0
                row["nbytes"] += int(ev.get("nbytes") or 0)
                st = row["stages"].setdefault(
                    str(ev.get("stage", "?")),
                    {"op": ev.get("stage_op"), "n": 0, "nbytes": 0},
                )
                st["n"] += 1
                st["nbytes"] += int(ev.get("nbytes") or 0)
                if dur is not None:
                    # a measured composed event (eager executors):
                    # per-stage time lands in the table too
                    st["dur_ms"] = round(
                        st.get("dur_ms", 0.0) + float(dur) * 1e3, 4
                    )
                b = ev.get("blocked_s")
                if b is not None:
                    st["blocked_ms"] = round(
                        st.get("blocked_ms", 0.0) + float(b) * 1e3, 4
                    )
                if ev.get("slice") is not None:
                    # ISSUE 15: the per-slice column of the stage table
                    sl = st.setdefault("slices", {}).setdefault(
                        f"s{int(ev['slice'])}", {"n": 0, "nbytes": 0}
                    )
                    sl["n"] += 1
                    sl["nbytes"] += int(ev.get("nbytes") or 0)
                    if dur is not None:
                        sl["dur_ms"] = round(
                            sl.get("dur_ms", 0.0) + float(dur) * 1e3, 4
                        )
                    if b is not None:
                        sl["blocked_ms"] = round(
                            sl.get("blocked_ms", 0.0) + float(b) * 1e3, 4
                        )
            elif dur is None:
                key = str(ev.get("schedule", "?"))
                row = layout.setdefault(
                    key, {"buckets": 0, "nbytes": 0, "overlapped": 0}
                )
                row["buckets"] += 1
                row["nbytes"] += int(ev.get("nbytes") or 0)
                row["overlapped"] += 1 if ev.get("overlapped") else 0
            else:
                n_measured += 1
                comm_s += float(dur)
                # None (absent) falls back to dur; an explicit 0.0 is a
                # FULLY-HIDDEN bucket and must count as such.
                b = ev.get("blocked_s")
                blocked_s += float(dur if b is None else b)
    if (not configs and not layout and not composed and not n_measured
            and search is None):
        return None
    out: dict = {}
    if configs:
        out["config"] = configs
    if layout:
        out["schedules"] = {
            k: layout[k] for k in sorted(layout)
        }
    if composed:
        if search is not None:
            # the predicted-vs-measured column on the composition rows
            for sig, row in composed.items():
                p = search["rows"].get(sig, {}).get("predicted_ms")
                if p is not None:
                    row["predicted_ms"] = p
        out["compositions"] = {
            k: composed[k] for k in sorted(composed)
        }
    if search is not None:
        out["sched_search"] = search
    if n_measured:
        hidden_s = max(0.0, comm_s - blocked_s)
        out["measured"] = {
            "n": n_measured,
            "comm_ms_total": round(comm_s * 1e3, 4),
            "comm_ms_blocked": round(blocked_s * 1e3, 4),
            "comm_ms_hidden": round(hidden_s * 1e3, 4),
            "hidden_fraction": (round(hidden_s / comm_s, 4)
                                if comm_s > 0 else 0.0),
        }
    return out


def summarize_serving(events: Iterable[Mapping[str, Any]]) -> Optional[dict]:
    """Serving rollup from ``serving`` (+ ``speculate``) events (ISSUE
    4/5: the consumer side of the scheduler's per-phase events; one
    owner shared by ``tools/trace_report.py`` and bench's ``serving``
    phase).

    Definitions (deterministic — the report contract pins them):

    - ``generated_tokens`` = one per prefill (its sampled first token)
      plus each ``decode_step``'s ``tokens`` field;
    - ``tokens_per_sec`` = generated tokens / (prefill + decode step
      durations) — device-busy time, not wall (queue idle gaps are the
      scheduler's property, not the engine's);
    - ``token_ms_p50``/``p99`` = nearest-rank percentiles (ceil(q*n))
      over ``decode_step`` durations — under plain decode each active
      request gains one token per step, so the step duration IS its
      per-token latency (under speculation it is the TICK latency for
      1..K+1 tokens per request — divide by ``generated_tokens /
      decode_steps`` for an amortized per-token figure);
    - ``ttft_ms_p50``/``p99`` = nearest-rank percentiles over the
      prefill events' ``ttft_s`` (submit → first token; None for
      traces predating the field; a preemption-resume's re-prefill
      carries no ``ttft_s`` and never re-enters the percentile);
    - ``tpot_ms_p50``/``p99`` (ISSUE 11 satellite) = nearest-rank
      percentiles over PER-REQUEST mean inter-token latency — the
      finish events' ``tpot_ms`` field (first token → finish over
      ``generated - 1`` intervals; preemption gaps included), falling
      back to ``(dur_s - ttft_s) / (generated - 1)`` for traces
      predating the field;
    - ``slo_attainment`` (present only when some finished request
      carried TTFT/TPOT targets, ISSUE 11) = fraction of
      target-bearing finished requests whose every stated target was
      met (the finish events' ``slo_ttft_ok``/``slo_tpot_ok``
      verdicts), with ``slo_requests`` the denominator;
    - ``preemptions`` (present only when > 0, ISSUE 11) = count of
      ``phase='preempt'`` events;
    - ``chunked_prefill`` (present only when ``prefill_chunk`` events
      exist, ISSUE 11) = chunk count and prompt tokens written through
      the mixed step's fill rows;
    - ``occupancy_mean`` = mean of ``n_active / n_slots`` over decode
      steps;
    - ``speculation`` (present only when ``speculate`` events exist) =
      drafted/accepted token totals, ``accept_rate`` = accepted /
      drafted, and ``accept_len_hist`` — accept-length counts keyed by
      stringified length (JSON-stable), the trace_report histogram;
    - ``prefix_cache`` (present only when ``prefix_cache`` events
      exist, ISSUE 7) = admission lookups/hits, ``hit_rate`` = hits /
      lookups, prompt vs prefilled vs cache-served token totals
      (``prefilled_tokens`` is the MEASURED prefill work — the bench
      acceptance reads it, not prose), ``hit_token_rate`` = hit tokens
      / prompt tokens, and total ``cow_blocks`` copied;
    - ``tenants`` (present when any prefill/finish event exists,
      ISSUE 14) = per-tenant rollup — requests, generated tokens,
      TTFT/TPOT p50/p99, SLO attainment where targets were stated —
      keyed by the events' ``tenant`` field with a ``'default'``
      fallback, so pre-tenant traces keep parsing (they roll up as one
      ``'default'`` tenant); ``tenant_fairness_jain`` = Jain's index
      over the per-tenant generated-token totals
      (:func:`~chainermn_tpu.observability.stats.jain_index` — 1.0 for
      a single tenant by construction).

    Returns None when the trace carries no serving events."""
    queue_waits: list[float] = []
    prefills: list[float] = []
    ttfts: list[float] = []
    ttft_by_req: dict = {}
    tpots: list[float] = []
    steps: list[float] = []
    occupancy: list[float] = []
    step_tokens = 0
    finishes = 0
    finish_evs: list = []
    preemptions = 0
    chunks = chunk_tokens = 0
    spec_ticks = 0
    spec_drafted = 0
    spec_accepted = 0
    accept_hist: dict = {}
    px_lookups = px_hits = 0
    px_hit_tokens = px_prompt_tokens = px_prefill_tokens = px_cow = 0
    tenant_ttfts: dict = {}
    tenant_fin: dict = {}
    for ev in events:
        kind = ev.get("kind")
        if kind == "prefill_chunk":
            chunks += 1
            chunk_tokens += int(ev.get("tokens") or 0)
            continue
        if kind == "speculate":
            spec_ticks += 1
            spec_drafted += int(ev.get("drafted") or 0)
            spec_accepted += int(ev.get("accepted") or 0)
            for a in (ev.get("accept_lens") or ()):
                k = str(int(a))
                accept_hist[k] = accept_hist.get(k, 0) + 1
            continue
        if kind == "prefix_cache":
            px_lookups += 1
            if int(ev.get("hit_blocks") or 0) > 0:
                px_hits += 1
            px_hit_tokens += int(ev.get("hit_tokens") or 0)
            px_prompt_tokens += int(ev.get("prompt_tokens") or 0)
            px_prefill_tokens += int(ev.get("prefill_tokens") or 0)
            px_cow += int(ev.get("cow_blocks") or 0)
            continue
        if kind != "serving":
            continue
        phase = ev.get("phase")
        dur = float(ev.get("dur_s") or 0.0)
        if phase == "queue_wait":
            queue_waits.append(dur)
        elif phase == "prefill":
            prefills.append(dur)
            if ev.get("ttft_s") is not None:
                ttfts.append(float(ev["ttft_s"]))
                tenant_ttfts.setdefault(
                    ev.get("tenant") or "default", []
                ).append(float(ev["ttft_s"]))
                rid = ev.get("request")
                if rid is not None and rid not in ttft_by_req:
                    ttft_by_req[rid] = float(ev["ttft_s"])
        elif phase == "decode_step":
            steps.append(dur)
            step_tokens += int(ev.get("tokens") or 0)
            n_slots = ev.get("n_slots")
            if n_slots:
                occupancy.append(float(ev.get("n_active") or 0)
                                 / float(n_slots))
        elif phase == "preempt":
            preemptions += 1
        elif phase == "finish":
            finishes += 1
            finish_evs.append(ev)
    # Per-request TPOT: the finish event's own tpot_ms when present
    # (preferred — the scheduler's first-token clock survives
    # preemption), else derived from dur - ttft over generated - 1.
    slo_total = slo_ok = 0
    for ev in finish_evs:
        tpot = ev.get("tpot_ms")
        if tpot is None:
            gen = int(ev.get("generated") or 0)
            rid = ev.get("request")
            ttft = ttft_by_req.get(rid)
            if gen > 1 and ttft is not None and ev.get("dur_s"):
                tpot = (float(ev["dur_s"]) - ttft) / (gen - 1) * 1e3
        if tpot is not None:
            tpots.append(float(tpot))
        verdicts = [ev.get(k) for k in ("slo_ttft_ok", "slo_tpot_ok")
                    if ev.get(k) is not None]
        if verdicts:
            slo_total += 1
            if all(verdicts):
                slo_ok += 1
        # Per-tenant accumulation (ISSUE 14): the 'default' fallback
        # keeps pre-tenant traces rolling up as one tenant.
        tf = tenant_fin.setdefault(
            ev.get("tenant") or "default",
            {"requests": 0, "tokens": 0, "tpots": [],
             "slo_total": 0, "slo_ok": 0},
        )
        tf["requests"] += 1
        tf["tokens"] += int(ev.get("generated") or 0)
        if tpot is not None:
            tf["tpots"].append(float(tpot))
        if verdicts:
            tf["slo_total"] += 1
            if all(verdicts):
                tf["slo_ok"] += 1
    if not (queue_waits or prefills or steps or finishes or spec_ticks
            or px_lookups or preemptions or chunks):
        return None

    pct = nearest_rank  # the shared ceil(q*n) rule (observability.stats)

    tokens = step_tokens + len(prefills)
    busy_s = sum(prefills) + sum(steps)
    out: dict = {
        "requests": finishes,
        "prefills": len(prefills),
        "generated_tokens": tokens,
        "decode_steps": len(steps),
        "queue_wait_ms_mean": (
            round(sum(queue_waits) / len(queue_waits) * 1e3, 4)
            if queue_waits else None),
        "prefill_ms_mean": (round(sum(prefills) / len(prefills) * 1e3, 4)
                            if prefills else None),
        "token_ms_p50": (round(pct(steps, 0.5) * 1e3, 4)
                         if steps else None),
        "token_ms_p99": (round(pct(steps, 0.99) * 1e3, 4)
                         if steps else None),
        "ttft_ms_p50": (round(pct(ttfts, 0.5) * 1e3, 4)
                        if ttfts else None),
        "ttft_ms_p99": (round(pct(ttfts, 0.99) * 1e3, 4)
                        if ttfts else None),
        "tpot_ms_p50": (round(pct(tpots, 0.5), 4) if tpots else None),
        "tpot_ms_p99": (round(pct(tpots, 0.99), 4) if tpots else None),
        "occupancy_mean": (round(sum(occupancy) / len(occupancy), 4)
                           if occupancy else None),
        "tokens_per_sec": (round(tokens / busy_s, 2) if busy_s > 0
                           else None),
    }
    if slo_total:
        out["slo_requests"] = slo_total
        out["slo_attainment"] = round(slo_ok / slo_total, 4)
    if preemptions:
        out["preemptions"] = preemptions
    if chunks:
        out["chunked_prefill"] = {"chunks": chunks,
                                  "chunk_tokens": chunk_tokens}
    if spec_ticks:
        out["speculation"] = {
            "ticks": spec_ticks,
            "drafted": spec_drafted,
            "accepted": spec_accepted,
            "accept_rate": (round(spec_accepted / spec_drafted, 4)
                            if spec_drafted else None),
            "accept_len_hist": {
                k: accept_hist[k]
                for k in sorted(accept_hist, key=int)
            },
        }
    if px_lookups:
        out["prefix_cache"] = {
            "lookups": px_lookups,
            "hits": px_hits,
            "hit_rate": round(px_hits / px_lookups, 4),
            "prompt_tokens": px_prompt_tokens,
            "hit_tokens": px_hit_tokens,
            "prefilled_tokens": px_prefill_tokens,
            "hit_token_rate": (round(px_hit_tokens / px_prompt_tokens, 4)
                               if px_prompt_tokens else None),
            "cow_blocks": px_cow,
        }
    if tenant_fin or tenant_ttfts:
        tenants: dict = {}
        for t in sorted(set(tenant_fin) | set(tenant_ttfts)):
            tf = tenant_fin.get(t, {"requests": 0, "tokens": 0,
                                    "tpots": [], "slo_total": 0,
                                    "slo_ok": 0})
            tts = tenant_ttfts.get(t, [])
            row: dict = {
                "requests": tf["requests"],
                "generated_tokens": tf["tokens"],
                "ttft_ms_p50": (round(pct(tts, 0.5) * 1e3, 4)
                                if tts else None),
                "ttft_ms_p99": (round(pct(tts, 0.99) * 1e3, 4)
                                if tts else None),
                "tpot_ms_p50": (round(pct(tf["tpots"], 0.5), 4)
                                if tf["tpots"] else None),
                "tpot_ms_p99": (round(pct(tf["tpots"], 0.99), 4)
                                if tf["tpots"] else None),
            }
            if tf["slo_total"]:
                row["slo_requests"] = tf["slo_total"]
                row["slo_attainment"] = round(
                    tf["slo_ok"] / tf["slo_total"], 4)
            tenants[t] = row
        out["tenants"] = tenants
        out["tenant_fairness_jain"] = round(jain_index(
            [tenants[t]["generated_tokens"] for t in tenants]), 4)
    return out


def chrome_trace(events: Iterable[Mapping[str, Any]]) -> dict:
    """Convert trace events to the Chrome trace-event format (load in
    ``chrome://tracing`` or https://ui.perfetto.dev). Events with a
    duration become complete ('X') slices; instants become 'i' marks.
    pid = process rank, tid = event kind — one track per subsystem.
    Journey-linked spans (ISSUE 17) whose ``parent`` span lives on a
    DIFFERENT rank additionally emit a flow-arrow pair (``ph: s``/``f``,
    ``bp: e``) so cross-rank handoffs render as arrows between pids."""
    out = []
    # span id -> (end ts us, rank, kind) for the flow pass; same-rank
    # parent links stay implicit (one pid track already reads in order).
    span_ix: dict = {}
    flows: list = []
    for ev in events:
        kind = ev.get("kind", "?")
        if kind == "meta":
            continue
        dur = ev.get("dur_s")
        name = ev.get("op") or ev.get("name") or kind
        ts = float(ev.get("t", 0.0)) * 1e6
        args = {k: v for k, v in ev.items()
                if k not in ("kind", "t", "t_mono", "pid", "rank",
                             "schema")}
        base = {
            "name": str(name),
            "cat": kind,
            "pid": ev.get("rank", 0),
            "tid": kind,
            "args": args,
        }
        if dur:
            # 't' stamps event END for spans recorded at exit; chrome
            # wants the start.
            start = ts - float(dur) * 1e6
            out.append({**base, "ph": "X", "ts": start,
                        "dur": float(dur) * 1e6})
        else:
            start = ts
            out.append({**base, "ph": "i", "ts": ts, "s": "p"})
        span = ev.get("span")
        if span is not None:
            span_ix[span] = (ts, ev.get("rank", 0), kind)
            parent = ev.get("parent")
            if parent is not None:
                flows.append((parent, start, ev.get("rank", 0), kind,
                              str(ev.get("journey", span))))
    for n, (parent, start, rank, kind, journey) in enumerate(flows):
        src = span_ix.get(parent)
        if src is None or src[1] == rank:
            continue  # orphan link or same-rank hop — no arrow
        p_ts, p_rank, p_kind = src
        flow = {"name": journey, "cat": "journey", "id": n + 1}
        out.append({**flow, "ph": "s", "ts": p_ts, "pid": p_rank,
                    "tid": p_kind})
        out.append({**flow, "ph": "f", "bp": "e", "ts": max(start, p_ts),
                    "pid": rank, "tid": kind})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(jsonl_path: str, out_path: str) -> int:
    """JSONL trace file -> Chrome trace JSON; returns the event count."""
    trace = chrome_trace(read_jsonl(jsonl_path))
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
