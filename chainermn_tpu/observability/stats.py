"""Shared order statistics for the observability plane (ISSUE 6
satellite: ONE owner of the nearest-rank percentile rule).

Before this module, the ceil(q*n) nearest-rank rule lived as a local
``pct()`` closure inside :func:`trace.summarize_serving` (and every
consumer of that rollup — ``Scheduler.summary``, bench's serving rows,
``tools/trace_report.py`` — inherited the copy). The metrics plane's
streaming histogram quantiles need the SAME rule, so it moves here:

    nearest-rank percentile of q over n sorted samples = the sample at
    1-based rank ceil(q * n)  (clamped into [1, n]).

Deliberately dependency-free (stdlib ``math`` only): ``trace.py`` is
loaded BY FILE PATH from ``tools/trace_report.py`` to avoid paying for
a jax import in a report tool, and anything trace.py pulls in must
honour the same constraint.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence


def nearest_rank_index(n: int, q: float) -> int:
    """0-based index of the nearest-rank percentile ``q`` in a sorted
    sequence of length ``n``: ``ceil(q * n) - 1`` clamped into
    ``[0, n - 1]``. The histogram quantile walks cumulative bucket
    counts with exactly this rank."""
    if n < 1:
        raise ValueError(f"need n >= 1 samples, got {n}")
    return min(n - 1, max(0, math.ceil(q * n) - 1))


def nearest_rank(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of ``values`` (None when empty) — the
    ceil(q*n) rule shared by the serving rollup and the metrics
    histograms (pinned by tests/test_metrics.py)."""
    if not values:
        return None
    s = sorted(values)
    return s[nearest_rank_index(len(s), q)]


def jain_index(values: Sequence[float]) -> Optional[float]:
    """Jain's fairness index over per-tenant allocations (ISSUE 14):

        J(x) = (sum x_i)^2 / (n * sum x_i^2)

    1.0 = perfectly even, 1/n = one tenant took everything. ONE owner
    shared by the serving rollup's tenant fairness, the scheduler's
    summary, and bench's ``serving_tenants`` phase — pinned against a
    literal numpy reference in tests/test_adapters.py. Pass allocations
    pre-divided by weight to measure WEIGHTED fairness. None when
    empty; an all-zero allocation reads as perfectly fair (nobody got
    anything — 1.0, not a division error)."""
    xs = [float(v) for v in values]
    if not xs:
        return None
    sq = sum(x * x for x in xs)
    if sq == 0.0:
        return 1.0
    return (sum(xs) ** 2) / (len(xs) * sq)
