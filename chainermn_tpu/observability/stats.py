"""Shared order statistics for the observability plane (ISSUE 6
satellite: ONE owner of the nearest-rank percentile rule).

Before this module, the ceil(q*n) nearest-rank rule lived as a local
``pct()`` closure inside :func:`trace.summarize_serving` (and every
consumer of that rollup — ``Scheduler.summary``, bench's serving rows,
``tools/trace_report.py`` — inherited the copy). The metrics plane's
streaming histogram quantiles need the SAME rule, so it moves here:

    nearest-rank percentile of q over n sorted samples = the sample at
    1-based rank ceil(q * n)  (clamped into [1, n]).

Deliberately dependency-free (stdlib ``math`` only): ``trace.py`` is
loaded BY FILE PATH from ``tools/trace_report.py`` to avoid paying for
a jax import in a report tool, and anything trace.py pulls in must
honour the same constraint.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence


def nearest_rank_index(n: int, q: float) -> int:
    """0-based index of the nearest-rank percentile ``q`` in a sorted
    sequence of length ``n``: ``ceil(q * n) - 1`` clamped into
    ``[0, n - 1]``. The histogram quantile walks cumulative bucket
    counts with exactly this rank."""
    if n < 1:
        raise ValueError(f"need n >= 1 samples, got {n}")
    return min(n - 1, max(0, math.ceil(q * n) - 1))


def nearest_rank(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of ``values`` (None when empty) — the
    ceil(q*n) rule shared by the serving rollup and the metrics
    histograms (pinned by tests/test_metrics.py)."""
    if not values:
        return None
    s = sorted(values)
    return s[nearest_rank_index(len(s), q)]
