"""Process-local live metrics registry: Counter / Gauge / Histogram
(ISSUE 6 tentpole; docs/observability.md "Live metrics").

PR 2's trace subsystem is post-hoc — a JSONL file read after the run.
This module is the LIVE half: a registry of named series a running
process updates in place and :mod:`~chainermn_tpu.observability.exporter`
serves over HTTP while the workload runs. Two feeding paths:

- **Recorder tap** (:func:`install_tap`): one sink registered on the
  trace :class:`~chainermn_tpu.observability.trace.Recorder` forwards
  every emitted event into metric updates, so every already-
  instrumented site (``collective`` wire counters, ``step`` timelines,
  ``serving``/``speculate`` phases, ``straggler`` reports) populates
  metrics with ZERO new call sites and zero HLO change (the
  instrumentation stays host-side timestamps only — structural test in
  tests/test_metrics.py, same pattern as tests/test_trace.py).
- **Direct gauges** at host planes that have state but no events:
  scheduler queue depth / in-flight count, engine slot occupancy,
  KV-block pool free/leased, trainer step counter. Those sites guard on
  :func:`active_registry` — one global read when the plane is off, the
  trace module's overhead discipline.

Histograms use FIXED log-spaced buckets (:func:`log_buckets`), so
streaming p50/p90/p99 come from cumulative bucket counts — no samples
are retained; the quantile rule is the shared nearest-rank
``ceil(q*n)`` (:mod:`~chainermn_tpu.observability.stats`), with the
bucket UPPER BOUND reported (a conservative <= one-bucket-width
overestimate; the +Inf bucket reports ``inf``).

Like the recorder, the registry is process-local and thread-safe
(exporter scrape thread vs workload threads). No new dependencies:
stdlib only.
"""

from __future__ import annotations

import bisect
import collections
import math
import os
import re
import threading
import time
from typing import Any, Callable, Dict, Iterable, Mapping, Optional, Tuple

# Like trace.py, this module is ALSO loaded by file path from
# tools/metrics_dump.py with no package context (the tool must not pay
# for ``import chainermn_tpu`` -> jax just to format a scrape) — load
# the stdlib-only siblings the same way there.
if __package__:
    from chainermn_tpu.observability import trace as _trace
    from chainermn_tpu.observability.stats import nearest_rank_index
else:  # pragma: no cover - exercised via tools/metrics_dump.py
    import importlib.util as _ilu

    def _load_sibling(fname, modname):
        spec = _ilu.spec_from_file_location(
            modname,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         fname),
        )
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _trace = _load_sibling("trace.py", "_obs_trace")
    nearest_rank_index = _load_sibling("stats.py", "_obs_stats")\
        .nearest_rank_index

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: quantiles every histogram snapshot reports — the serving SLO set.
SNAPSHOT_QUANTILES = (0.5, 0.9, 0.99)


def log_buckets(lo: float = 1e-5, hi: float = 100.0,
                per_decade: int = 4) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds covering ``[lo, hi]`` —
    the default latency ladder (10 us .. 100 s at 4 buckets/decade,
    ~29 bounds). Fixed by construction: every process cuts the same
    ladder, so cross-rank merges never need bucket alignment."""
    if not (0 < lo < hi) or per_decade < 1:
        raise ValueError(f"need 0 < lo < hi and per_decade >= 1, got "
                         f"lo={lo} hi={hi} per_decade={per_decade}")
    n = int(math.ceil(math.log10(hi / lo) * per_decade))
    return tuple(lo * 10 ** (i / per_decade) for i in range(n + 1))


DEFAULT_TIME_BUCKETS = log_buckets()


def _labels_key(labels: Mapping[str, str]) -> Tuple[Tuple[str, str], ...]:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _render_labels(key: Tuple[Tuple[str, str], ...],
                   extra: Optional[Mapping[str, str]] = None) -> str:
    pairs = list(key)
    if extra:
        pairs = sorted(dict(list(key) + list(extra.items())).items())
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


class _Family:
    """One named metric family; children are keyed by label sets."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, lock: threading.Lock) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help_
        self._lock = lock
        self._children: Dict[Tuple[Tuple[str, str], ...], Any] = {}


class Counter(_Family):
    """Monotone total."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels: str) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {value})")
        key = _labels_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + value

    def value(self, **labels: str) -> float:
        with self._lock:
            return float(self._children.get(_labels_key(labels), 0.0))


class Gauge(_Family):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + value

    def value(self, **labels: str) -> Optional[float]:
        with self._lock:
            v = self._children.get(_labels_key(labels))
            return None if v is None else float(v)


class Histogram(_Family):
    """Fixed-bucket streaming histogram: per child, cumulative-ready
    counts per bucket plus sum/count — p50/p90/p99 without retaining
    samples (module docstring)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, lock: threading.Lock,
                 buckets: Iterable[float] = DEFAULT_TIME_BUCKETS) -> None:
        super().__init__(name, help_, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(b <= 0 for b in bs) or len(set(bs)) != len(bs):
            raise ValueError(f"buckets must be positive, unique, "
                             f"non-empty; got {buckets}")
        self.buckets = bs  # upper bounds; +Inf bucket is implicit

    def _child(self, key):
        st = self._children.get(key)
        if st is None:
            st = {"counts": [0] * (len(self.buckets) + 1),
                  "sum": 0.0, "n": 0}
            self._children[key] = st
        return st

    def observe(self, value: float, **labels: str) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)  # first ub >= value
        key = _labels_key(labels)
        with self._lock:
            st = self._child(key)
            st["counts"][idx] += 1
            st["sum"] += value
            st["n"] += 1

    def count(self, **labels: str) -> int:
        with self._lock:
            st = self._children.get(_labels_key(labels))
            return int(st["n"]) if st else 0

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Nearest-rank quantile over the bucket counts: the bucket
        UPPER BOUND holding 1-based rank ``ceil(q*n)`` (the shared
        stats rule); ``inf`` when the rank falls in the overflow
        bucket; None with no observations."""
        with self._lock:
            st = self._children.get(_labels_key(labels))
            if not st or not st["n"]:
                return None
            rank = nearest_rank_index(st["n"], q) + 1  # 1-based
            cum = 0
            for i, c in enumerate(st["counts"]):
                cum += c
                if cum >= rank:
                    return (self.buckets[i] if i < len(self.buckets)
                            else math.inf)
        return math.inf  # unreachable; counts always sum to n


class MetricsRegistry:
    """Name -> family map with get-or-create accessors (an existing
    family is returned as-is; a kind mismatch raises — two subsystems
    silently sharing one name as different types is a bug)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Family] = {}
        self._collect_hooks: list[Callable[["MetricsRegistry"], None]] = []

    def _get_or_create(self, cls, name: str, help_: str, **kw) -> _Family:
        with self._lock:
            fam = self._metrics.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, requested {cls.kind}"
                    )
                return fam
            fam = cls(name, help_, self._lock, **kw)
            self._metrics[name] = fam
            return fam

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Iterable[float] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_, buckets=buckets)

    def register_collect(self, fn: Callable[["MetricsRegistry"], None]
                         ) -> None:
        """Hook run before every snapshot/exposition — how scrape-time
        values (recorder drop counts, pool sizes) stay live without a
        per-event write. Hooks must never raise out of a scrape."""
        if fn not in self._collect_hooks:
            self._collect_hooks.append(fn)

    def _run_collect(self) -> None:
        for fn in tuple(self._collect_hooks):
            try:
                fn(self)
            except Exception:
                pass

    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able view of every family: counters/gauges as values,
        histograms as count/sum/cumulative buckets + the SLO quantiles.
        This is the peer-merge payload (exporter) and the bench
        artifact (``metrics_snapshot`` in BENCH_DETAILS.json)."""
        self._run_collect()
        out: dict = {}
        with self._lock:
            for name, fam in sorted(self._metrics.items()):
                rows = []
                if isinstance(fam, Histogram):
                    for key, st in sorted(fam._children.items()):
                        cum, buckets = 0, []
                        for i, c in enumerate(st["counts"][:-1]):
                            cum += c
                            buckets.append([fam.buckets[i], cum])
                        buckets.append(["+Inf", st["n"]])
                        rows.append({
                            "labels": dict(key),
                            "count": st["n"],
                            "sum": round(st["sum"], 9),
                            "buckets": buckets,
                        })
                else:
                    for key, v in sorted(fam._children.items()):
                        rows.append({"labels": dict(key), "value": v})
                out[name] = {"type": fam.kind, "help": fam.help,
                             "values": rows}
        # Quantiles OUTSIDE the lock pass (quantile() re-locks). inf
        # (rank fell in the overflow bucket) becomes None: strict-JSON
        # consumers of the snapshot must not meet bare Infinity.
        # Iterate the families CAPTURED in pass 1: a family first
        # created between the passes (workload thread racing a scrape)
        # has no `out` entry yet and must not KeyError the scrape.
        for name, fam in list(self._metrics.items()):
            if isinstance(fam, Histogram) and name in out:
                for row in out[name]["values"]:
                    qs = {}
                    for q in SNAPSHOT_QUANTILES:
                        v = fam.quantile(q, **row["labels"])
                        qs[f"p{int(q * 100)}"] = (
                            v if v is None or math.isfinite(v) else None
                        )
                    row["quantiles"] = qs
        return out

    def exposition(self, extra_snapshots: Iterable[Tuple[str, dict]] = ()
                   ) -> str:
        """Prometheus text exposition (v0.0.4): ``# HELP`` / ``# TYPE``
        per family, then the sample lines; histograms expand into
        ``_bucket{le=...}`` / ``_sum`` / ``_count``. ``extra_snapshots``
        are (rank, snapshot) pairs from peer processes (exporter's
        rank-0 merge) — their series carry an added ``rank`` label."""
        return render_exposition(
            self.snapshot(), extra_snapshots=extra_snapshots
        )


def render_exposition(snapshot: Mapping[str, dict],
                      extra_snapshots: Iterable[Tuple[str, dict]] = ()
                      ) -> str:
    """Snapshot(s) -> exposition text (one owner for own + peer
    rendering, and for tools/metrics_dump.py's offline mode)."""
    merged: Dict[str, dict] = {}

    def fold(snap: Mapping[str, dict], extra_labels: dict) -> None:
        for name, fam in snap.items():
            slot = merged.setdefault(
                name, {"type": fam.get("type", "untyped"),
                       "help": fam.get("help", ""), "rows": []}
            )
            for row in fam.get("values", ()):
                labels = {**row.get("labels", {}), **extra_labels}
                slot["rows"].append({**row, "labels": labels})

    fold(snapshot, {})
    for rank, snap in extra_snapshots:
        fold(snap, {"rank": str(rank)})

    lines: list[str] = []
    for name in sorted(merged):
        fam = merged[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for row in fam["rows"]:
            key = _labels_key(row["labels"])
            if fam["type"] == "histogram":
                for le, cum in row["buckets"]:
                    le_s = "+Inf" if le == "+Inf" else repr(float(le))
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels(key, {'le': le_s})} {cum}"
                    )
                lines.append(f"{name}_sum{_render_labels(key)} "
                             f"{repr(float(row['sum']))}")
                lines.append(f"{name}_count{_render_labels(key)} "
                             f"{row['count']}")
            else:
                v = row["value"]
                v_s = repr(float(v)) if not float(v).is_integer() \
                    else str(int(v))
                lines.append(f"{name}{_render_labels(key)} {v_s}")
    return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                         ...]], float]:
    """Inverse of :func:`render_exposition` for tests and the dryrun
    self-scrape: ``{(name, sorted-label-tuple): value}``. Raises on a
    malformed sample line — the exporter golden test leans on that."""
    out: dict = {}
    sample = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? '
        r'([0-9eE+.inf-]+|NaN)$'
    )
    labelpair = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    _UNESCAPE = re.compile(r'\\(.)')
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = sample.match(line)
        if not m:
            raise ValueError(f"malformed exposition line: {line!r}")
        name, _, labelbody, value = m.groups()
        labels = []
        if labelbody:
            matched = labelpair.findall(labelbody)
            # One pass over escapes: a sequential replace chain turns
            # the escaped form of backslash+'n' (\\n) into
            # backslash+newline — \\ must not re-expose an n to the \n
            # rule (render->parse must round-trip).
            labels = [
                (k, _UNESCAPE.sub(
                    lambda m: "\n" if m.group(1) == "n" else m.group(1), v
                ))
                for k, v in matched
            ]
        out[(name, tuple(sorted(labels)))] = float(value)
    return out


# ----------------------------------------------------------------------
# Global registry + the recorder tap
# ----------------------------------------------------------------------

_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()
_tap_installed = False


def registry() -> MetricsRegistry:
    """The process-global registry, created on first use."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def active_registry() -> Optional[MetricsRegistry]:
    """The global registry or None — the one-global-read guard every
    direct-gauge site starts with (the plane costs nothing until
    something creates the registry)."""
    return _registry


def reset() -> None:
    """Tear down the global registry and the tap (tests)."""
    global _registry, _tap_installed, _dropped_seen
    uninstall_tap()
    with _registry_lock:
        _registry = None
    _tap_installed = False
    _dropped_seen = None
    _reset_slo_window()
    _reset_spec_totals()


def install_tap(reg: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Register the recorder->metrics sink (idempotent) plus the
    scrape-time recorder-health hook. Events only flow while a trace
    recorder is active; the sink itself adds no cost with tracing off
    (it is simply never called)."""
    global _registry, _tap_installed
    if reg is not None:
        with _registry_lock:
            _registry = reg
    reg = registry()
    if not _tap_installed:
        _trace.add_sink(_tap_event)
        _tap_installed = True
    reg.register_collect(_collect_recorder_health)
    reg.register_collect(_collect_slo_burn)
    reg.register_collect(_collect_spec_accept)
    return reg


def uninstall_tap() -> None:
    global _tap_installed
    _trace.remove_sink(_tap_event)
    _tap_installed = False


# (recorder-identity, last-seen dropped) — the counter accumulates
# DELTAS across recorder generations: each Recorder's `dropped` starts
# at 0, so mirroring it with a bare max() would hide a later, smaller
# recorder's drops behind an earlier recorder's total (review finding).
# The watermark read-modify-write is guarded: ThreadingHTTPServer
# scrapes concurrently, and two unsynchronized collects would both see
# the same prev and double-count the delta (review finding). Safe to
# take here — collect hooks run OUTSIDE the registry lock.
_dropped_seen: Optional[Tuple[int, int]] = None
_dropped_lock = threading.Lock()


def _collect_recorder_health(reg: MetricsRegistry) -> None:
    """Scrape-time sync of recorder-owned monotone state: the live
    ``trace_dropped_events`` counter (ISSUE 6 satellite — before this,
    ``Recorder.dropped`` surfaced only in the ``close()`` meta event;
    process-lifetime total across recorder generations) and the
    buffered-event gauge."""
    global _dropped_seen
    rec = _trace.active()
    if rec is None:
        return
    rec_id = id(rec)
    with _dropped_lock:
        # One read: drops landing between two reads would advance the
        # watermark without ever being counted.
        dropped = rec.dropped
        prev = _dropped_seen[1] if (
            _dropped_seen is not None and _dropped_seen[0] == rec_id
        ) else 0
        delta = dropped - prev
        if delta < 0:
            # dropped is monotone per recorder: a decrease means id()
            # reuse by a NEW recorder — its whole count is fresh.
            delta = dropped
        _dropped_seen = (rec_id, dropped)
    reg.counter(
        "trace_dropped_events",
        "trace events dropped by the recorder's in-memory buffer cap",
    ).inc(float(delta))  # inc(0) still exports the series on a lossless run
    reg.gauge(
        "trace_buffered_events", "events in the recorder's memory buffer"
    ).set(len(rec.events))


# ----------------------------------------------------------------------
# SLO burn rate (ISSUE 17): sliding-window violation fraction
# ----------------------------------------------------------------------
#
# ``serving_slo_violations_total`` is a counter — it can only say "how
# many ever", which makes a dashboard alert integrate-by-hand. The burn
# rate is the operational form: the fraction of target-bearing finishes
# inside the trailing window that MISSED their target, per (kind,
# tenant). 0.0 = clean, 1.0 = every request burning. Window length is
# ``CHAINERMN_TPU_SLO_WINDOW_S`` (seconds, default 60); a pair whose
# verdicts have all aged out reads 0.0 — the gauge stays exported (a
# vanished series and a healthy one must not look alike).

_SLO_WINDOW_ENV = "CHAINERMN_TPU_SLO_WINDOW_S"
_SLO_WINDOW_DEFAULT_S = 60.0

#: (monotonic stamp, kind, tenant, ok) per finish-event verdict —
#: monotonic, not epoch: a stepped wall clock must not dump or pin the
#: window.
_slo_window: collections.deque = collections.deque()
_slo_pairs_seen: set = set()
_slo_lock = threading.Lock()


def _slo_window_s() -> float:
    try:
        v = float(os.environ.get(_SLO_WINDOW_ENV, _SLO_WINDOW_DEFAULT_S))
    except ValueError:
        return _SLO_WINDOW_DEFAULT_S
    return v if v > 0 else _SLO_WINDOW_DEFAULT_S


def _record_slo_verdict(kind: str, tenant: str, ok: bool) -> None:
    with _slo_lock:
        _slo_window.append((time.monotonic(), kind, tenant, bool(ok)))
        _slo_pairs_seen.add((kind, tenant))


def slo_burn_rates(window_s: Optional[float] = None) -> dict:
    """``{kind: {tenant: burn}}`` over the trailing window — burn is
    violations/total among finishes carrying that SLO verdict. Every
    (kind, tenant) pair ever seen this process stays in the map (0.0
    once its verdicts age out). Feeds both the ``serving_slo_burn_rate``
    gauge and the exporter's ``/healthz`` body."""
    if window_s is None:
        window_s = _slo_window_s()
    cutoff = time.monotonic() - window_s
    counts: dict = {}
    with _slo_lock:
        while _slo_window and _slo_window[0][0] < cutoff:
            _slo_window.popleft()
        for _t, kind, tenant, ok in _slo_window:
            tot, bad = counts.get((kind, tenant), (0, 0))
            counts[(kind, tenant)] = (tot + 1, bad + (0 if ok else 1))
        pairs = sorted(_slo_pairs_seen)
    out: dict = {}
    for kind, tenant in pairs:
        tot, bad = counts.get((kind, tenant), (0, 0))
        out.setdefault(kind, {})[tenant] = (
            round(bad / tot, 6) if tot else 0.0)
    return out


def _reset_slo_window() -> None:
    with _slo_lock:
        _slo_window.clear()
        _slo_pairs_seen.clear()


def _collect_slo_burn(reg: MetricsRegistry) -> None:
    """Scrape-time hook: re-derive the burn gauges from the window (a
    sliding-window value must DECAY without new events — only a
    collect hook, never a per-event write, can show that)."""
    for kind, tenants in slo_burn_rates().items():
        for tenant, burn in tenants.items():
            reg.gauge(
                "serving_slo_burn_rate",
                "fraction of SLO-bearing finishes in the trailing "
                f"window (${_SLO_WINDOW_ENV}, default "
                f"{_SLO_WINDOW_DEFAULT_S:g}s) that missed their target",
            ).set(burn, kind=kind, tenant=tenant)


# ----------------------------------------------------------------------
# Speculative acceptance by sampling mode (ISSUE 18)
# ----------------------------------------------------------------------
#
# The unlabeled ``speculate_drafted_total``/``accepted_total`` counters
# predate sampled speculation and stay exactly as they were (pinned in
# tests/test_metrics.py). Now that verify ticks run in two acceptance
# regimes — exact-match greedy vs rejection-sampling sampled
# (docs/serving.md "Sampling") — the operational question is the RATE
# per regime: a sampled acceptance collapse is a drafter-mismatch
# signal that an aggregate counter would average away.

#: {mode: (drafted, accepted)} — process-lifetime totals.
_spec_totals: dict = {}
_spec_lock = threading.Lock()


def _record_spec(mode: str, drafted: float, accepted: float) -> None:
    with _spec_lock:
        tot, acc = _spec_totals.get(mode, (0.0, 0.0))
        _spec_totals[mode] = (tot + drafted, acc + accepted)


def spec_accept_rates() -> dict:
    """``{mode: rate}`` — accepted/drafted per sampling mode over the
    process lifetime. A mode that has drafted nothing reads 0.0 but
    stays in the map once seen (same vanished-vs-healthy rule as the
    burn gauges). Feeds the ``serving_spec_accept_rate`` gauge and the
    exporter's ``/healthz`` body."""
    with _spec_lock:
        return {
            mode: (round(acc / tot, 6) if tot else 0.0)
            for mode, (tot, acc) in sorted(_spec_totals.items())
        }


def _reset_spec_totals() -> None:
    with _spec_lock:
        _spec_totals.clear()


def _collect_spec_accept(reg: MetricsRegistry) -> None:
    """Scrape-time hook: derive the per-mode acceptance-rate gauge from
    the totals (a ratio is a derived value — exporting it per-event
    would snapshot whichever tick scraped last)."""
    for mode, rate in spec_accept_rates().items():
        reg.gauge(
            "serving_spec_accept_rate",
            "speculative tokens accepted / drafted by sampling mode "
            "(process lifetime)",
        ).set(rate, mode=mode)


def _tap_event(ev: Mapping[str, Any]) -> None:
    """The recorder sink: one trace event -> metric updates. Must never
    raise (the recorder swallows sink errors, but a broken tap would
    silently stop updating — keep each branch total)."""
    reg = _registry
    if reg is None:
        return
    kind = ev.get("kind")
    if kind == "collective":
        op = str(ev.get("op", "?"))
        plane = str(ev.get("plane", "device"))
        reg.counter(
            "wire_events_total", "collective-wire events by op"
        ).inc(op=op, plane=plane)
        nb = ev.get("nbytes")
        if nb is not None:
            reg.counter(
                "wire_bytes_total", "collective-wire payload bytes by op"
            ).inc(float(nb), op=op, plane=plane)
        dur = ev.get("dur_s")
        if dur is not None:
            reg.counter(
                "wire_seconds_total", "collective-wire seconds by op"
            ).inc(float(dur), op=op, plane=plane)
            reg.histogram(
                "collective_seconds", "per-collective duration"
            ).observe(float(dur), op=op, plane=plane)
    elif kind == "step":
        reg.counter("train_steps_total", "trainer iterations").inc()
        it = ev.get("iteration")
        if it is not None:
            reg.gauge("train_iteration", "last completed trainer "
                      "iteration").set(float(it))
        for phase, v in (ev.get("phases") or {}).items():
            reg.histogram(
                "step_phase_seconds", "trainer step-timeline phase seconds"
            ).observe(float(v), phase=str(phase))
    elif kind == "serving":
        phase = ev.get("phase")
        dur = float(ev.get("dur_s") or 0.0)
        if phase == "queue_wait":
            reg.histogram(
                "serving_queue_wait_seconds", "submit -> admission wait"
            ).observe(dur)
        elif phase == "prefill":
            reg.histogram(
                "serving_prefill_seconds", "bucketed prefill duration"
            ).observe(dur)
            if ev.get("ttft_s") is not None:
                reg.histogram(
                    "serving_ttft_seconds",
                    "submit -> first token (the TTFT SLO)",
                ).observe(float(ev["ttft_s"]))
                if ev.get("tenant") is not None:
                    # Per-tenant TTFT (ISSUE 14): the tenant label set
                    # is bounded by adapter-bank capacity, so the
                    # cardinality stays small by construction.
                    reg.histogram(
                        "serving_tenant_ttft_seconds",
                        "submit -> first token per tenant",
                    ).observe(float(ev["ttft_s"]),
                              tenant=str(ev["tenant"]))
            reg.counter(
                "serving_tokens_total", "generated tokens (first token "
                "per prefill + decode-step tokens)"
            ).inc()
        elif phase == "decode_step":
            reg.histogram(
                "serving_decode_step_seconds",
                "fused decode-step duration (per-token latency under "
                "plain decode; tick latency under speculation)",
            ).observe(dur)
            reg.counter("serving_decode_steps_total",
                        "fused decode steps").inc()
            toks = ev.get("tokens")
            if toks:
                reg.counter(
                    "serving_tokens_total", "generated tokens (first "
                    "token per prefill + decode-step tokens)"
                ).inc(float(toks))
        elif phase == "finish":
            reg.counter("serving_requests_total",
                        "completed serving requests").inc()
            if ev.get("tenant") is not None:
                reg.counter(
                    "serving_tenant_requests_total",
                    "completed serving requests per tenant",
                ).inc(tenant=str(ev["tenant"]))
                gen = ev.get("generated")
                if gen:
                    reg.counter(
                        "serving_tenant_tokens_total",
                        "generated tokens per tenant (from finishes)",
                    ).inc(float(gen), tenant=str(ev["tenant"]))
            # SLO verdicts (ISSUE 11): one violation count per missed
            # target kind — a request can miss both. Every verdict
            # (pass or fail) also lands in the burn-rate window
            # (ISSUE 17) — a rate needs the denominator too.
            tenant = str(ev.get("tenant") or "default")
            if ev.get("slo_ttft_ok") is False:
                reg.counter(
                    "serving_slo_violations_total",
                    "finished requests outside a stated SLO target",
                ).inc(kind="ttft")
            if ev.get("slo_tpot_ok") is False:
                reg.counter(
                    "serving_slo_violations_total",
                    "finished requests outside a stated SLO target",
                ).inc(kind="tpot")
            if ev.get("slo_ttft_ok") is not None:
                _record_slo_verdict("ttft", tenant, ev["slo_ttft_ok"])
            if ev.get("slo_tpot_ok") is not None:
                _record_slo_verdict("tpot", tenant, ev["slo_tpot_ok"])
        elif phase == "preempt":
            reg.counter(
                "serving_preemptions_total",
                "in-flight requests preempted back to the queue "
                "(SLO scheduling)",
            ).inc()
    elif kind == "prefill_chunk":
        reg.counter(
            "serving_prefill_chunks_total",
            "prompt chunks written through the mixed step",
        ).inc()
        reg.counter(
            "serving_chunk_tokens_total",
            "prompt tokens prefilled through mixed-step chunks",
        ).inc(float(ev.get("tokens") or 0))
    elif kind == "speculate":
        drafted = float(ev.get("drafted") or 0)
        accepted = float(ev.get("accepted") or 0)
        reg.counter("speculate_drafted_total",
                    "speculative tokens drafted").inc(drafted)
        reg.counter("speculate_accepted_total",
                    "speculative tokens accepted").inc(accepted)
        _record_spec(str(ev.get("mode") or "greedy"), drafted, accepted)
    elif kind == "moe_dispatch":
        # ISSUE 20: host-side mirror of one MoE dispatch observation
        # (parallel.moe.record_moe_dispatch). Counters accumulate the
        # drop/pad token flow; gauges snapshot the latest per-expert
        # load histogram and the static capacity.
        reg.counter(
            "moe_dropped_tokens_total",
            "MoE capacity-overflow token assignments (carried by the "
            "residual path, not corrupted)",
        ).inc(float(ev.get("dropped") or 0))
        reg.counter(
            "moe_padded_tokens_total",
            "empty MoE queue slots shipped over the a2a wire anyway "
            "(the static-shape tax)",
        ).inc(float(ev.get("padded") or 0))
        layer = ev.get("layer")
        labels = {"layer": str(layer)} if layer is not None else {}
        for i, v in enumerate(ev.get("expert_load") or ()):
            reg.gauge(
                "moe_expert_load",
                "kept tokens routed to each expert at the last "
                "observed dispatch",
            ).set(float(v), expert=str(i), **labels)
        if ev.get("capacity") is not None:
            reg.gauge(
                "moe_capacity",
                "per-expert token capacity of the MoE dispatch",
            ).set(float(ev["capacity"]), **labels)
    elif kind == "prefix_cache":
        reg.counter("kv_prefix_lookups_total",
                    "prefix-trie lookups at admission").inc()
        if int(ev.get("hit_blocks") or 0) > 0:
            reg.counter("kv_prefix_hits_total",
                        "admissions that adopted cached blocks").inc()
        reg.counter(
            "kv_prefix_hit_tokens_total",
            "prompt tokens served from the prefix cache (not "
            "re-prefilled)",
        ).inc(float(ev.get("hit_tokens") or 0))
        reg.counter(
            "kv_prefix_prefill_tokens_total",
            "prompt tokens actually prefilled (the unshared tails)",
        ).inc(float(ev.get("prefill_tokens") or 0))
        cow = float(ev.get("cow_blocks") or 0)
        if cow:
            reg.counter("kv_prefix_cow_blocks_total",
                        "copy-on-write block copies").inc(cow)
    elif kind == "route":
        reg.counter(
            "cluster_routes_total",
            "requests placed on a replica by the cluster router",
        ).inc(rank=str(ev.get("replica")))
        if ev.get("requeue"):
            reg.counter(
                "cluster_requeues_total",
                "requests re-routed after a deferral or replica loss",
            ).inc()
    elif kind == "kv_transfer":
        reg.counter(
            "kv_transfer_total",
            "cross-replica KV handoffs (disaggregated prefill/decode)",
        ).inc()
        reg.counter(
            "kv_transfer_bytes_total",
            "KV block bytes streamed between replicas",
        ).inc(float(ev.get("nbytes") or 0))
        reg.counter(
            "kv_transfer_blocks_total",
            "KV blocks streamed between replicas",
        ).inc(float(ev.get("blocks") or 0))
        if ev.get("dur_s") is not None:
            reg.histogram(
                "kv_transfer_seconds",
                "export -> adoption latency of one KV handoff",
            ).observe(float(ev["dur_s"]))
    elif kind == "straggler":
        reg.counter("straggler_reports_total",
                    "straggler-monitor flag reports").inc()
