"""Flight recorder + distributed hang watchdog (ISSUE 6 tentpole;
docs/observability.md "Hang forensics").

The failure mode this exists for: one rank enters a collective whose
peers never arrive, and the job stalls SILENTLY — no exception, no
log line, nothing to attach a debugger to hours later. Three always-on,
always-cheap host-side signals turn that into a diagnosable artifact:

- **Event ring** — a bounded deque of recent trace events, fed by a
  recorder sink (one deque append per event; only active while a trace
  recorder is). The last ~512 events of context ride into every dump.
- **In-flight collective marker** — ``collective_entered(op, ...)`` /
  ``collective_exited(token)`` push/remove a (time, info) entry on the
  calling THREAD's stack, because collectives nest: ``bcast`` runs a
  host-plane ``bcast_obj`` inside it, ``allreduce_grad`` a per-leaf
  ``allreduce`` — a one-slot cell would be cleared by the inner exit
  and lose the outer marker exactly where composite multi-host hangs
  park (review finding). Stacks are PER THREAD (the async
  double-buffered host reducer completes its previous-step collectives
  on a background thread while the main thread marks its own — one
  shared stack would pop the wrong thread's marker and the dump would
  name the wrong op), and exits remove their OWN entry by identity, so
  an exception unwinding through nested markers can never over-pop an
  enclosing one. One append/remove per call, no lock (CPython
  list/dict single ops are atomic): the communicator surface marks
  every eager collective's entry/exit, and the host object plane
  (``_host_comm``) marks its blocking collectives — a hang INSIDE a
  collective is named by op, payload bytes, axes, and age, innermost
  first.
- **Heartbeat** — ``beat(step)`` from the trainer loop (once per step)
  and the serving scheduler (once per decode round); loops call
  :func:`quiesce` when they END, so a process idling between runs is
  never mistaken for a wedged one.

:class:`HangWatchdog` is a daemon thread that polls those signals; when
no progress lands for ``stall_s`` seconds (a beat or a collective exit
both count) — or an in-flight collective alone exceeds ``stall_s`` —
it writes ``hang_dump_<rank>.json``: all-thread stacks
(``sys._current_frames``; the ``faulthandler`` module is the
lower-level fallback when even the JSON writer could be wedged), the
in-flight marker, the last beat, and the event ring. It fires ONCE and
exits (the process is presumed wedged; a second dump would only
overwrite the evidence), and it never fires in a process that has shown
no activity at all (an idle import must not dump).

Enable explicitly (:func:`start_watchdog`) or by environment —
``CHAINERMN_TPU_HANG_DUMP_S=<seconds>`` (threshold) and optional
``CHAINERMN_TPU_HANG_DUMP_DIR`` — checked by the trainer and the
exporter via :func:`maybe_start_from_env`. ``tests/conftest.py`` pops
the env vars: the suite never grows watchdog threads.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Optional

from chainermn_tpu.observability import trace as _trace

_ENV_STALL = "CHAINERMN_TPU_HANG_DUMP_S"
_ENV_DIR = "CHAINERMN_TPU_HANG_DUMP_DIR"

#: dump schema version (bump on incompatible field changes).
HANG_DUMP_SCHEMA = 1

RING_CAPACITY = 512

_ring: collections.deque = collections.deque(maxlen=RING_CAPACITY)
# Lock-free cells (list/dict single ops are atomic in CPython):
#: thread-id -> STACK of (t_monotonic, {"op": ..., ...}) entries.
_inflight: dict = {}
_last_beat: list = [None]  # (t_monotonic, step) | None
_progress: list = [None]   # monotonic time of the last progress signal


def _ring_sink(ev: dict) -> None:
    _ring.append(ev)


# Installed at import: a deque append per trace event is the "always
# cheap" budget, and the ring must predate any explicit setup — the
# whole point is having context around when nobody planned for a hang.
_trace.add_sink(_ring_sink)


def collective_entered(op: str, **info: Any) -> tuple:
    """Mark collective entry (communicator call sites). Cheap enough
    for the eager hot path: one tuple build + one list append onto the
    calling thread's stack. Returns the entry TOKEN: pass it back to
    :func:`collective_exited` (the sites pair them in a ``finally``;
    composites nest cleanly on the stack)."""
    tid = threading.get_ident()
    entry = (time.monotonic(),
             {"op": op, "thread": threading.current_thread().name, **info})
    _inflight.setdefault(tid, []).append(entry)
    return entry


def collective_exited(token: Optional[tuple] = None) -> None:
    """Remove the calling thread's marker — ``token`` (the
    :func:`collective_entered` return) by identity when given, else the
    thread's innermost — and count progress. Identity removal makes
    the exit idempotent, so an exception unwinding through nested
    ``finally`` blocks can never over-pop an ENCLOSING collective's
    marker. Progress only refreshes an already-armed chain (a beat
    arms it): a one-off collective in an intentionally idle process
    (post-:func:`quiesce` weight refresh, peer-snapshot merge) must
    not re-arm the no-progress rule and spend the fire-once watchdog
    on a healthy idle (review finding)."""
    tid = threading.get_ident()
    stack = _inflight.get(tid)
    if stack:
        try:
            if token is None:
                stack.pop()
            else:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] is token:
                        del stack[i]
                        break
        except IndexError:
            pass  # unbalanced exit must never take down the caller
        if not stack:
            _inflight.pop(tid, None)  # dead threads must not accrete
    if _progress[0] is not None:
        _progress[0] = time.monotonic()


def beat(step: Optional[int] = None) -> None:
    """Progress heartbeat: the trainer beats once per step, the serving
    scheduler once per decode round."""
    now = time.monotonic()
    _last_beat[0] = (now, step)
    _progress[0] = now


def quiesce() -> None:
    """Mark the process INTENTIONALLY idle (a training run returned, a
    serving loop drained its queue): clears the beat/progress signals,
    so the watchdog's no-progress rule stands down — a process waiting
    for work is indistinguishable from a wedged one by silence alone
    (review finding: without this, a drained serving replica dumped
    after stall_s of legitimate quiet and the fire-once watchdog then
    missed the real hang hours later). A genuinely stuck collective
    still fires: the in-flight marker rule is independent of beats."""
    _last_beat[0] = None
    _progress[0] = None


def _stacks_snapshot() -> list:
    """All threads' live stacks, oldest outermost entry first."""
    stacks = [list(s) for s in list(_inflight.values())]
    stacks = [s for s in stacks if s]
    stacks.sort(key=lambda s: s[0][0])
    return stacks


def in_flight() -> Optional[dict]:
    """The most specific name for where a wedged process is parked:
    the INNERMOST entry of the thread with the OLDEST outermost marker
    (the longest-stuck nesting's deepest leg), with its age. None when
    nothing is in flight."""
    stacks = _stacks_snapshot()
    if not stacks:
        return None
    t0, info = stacks[0][-1]
    return {**info, "age_s": round(time.monotonic() - t0, 3)}


def in_flight_stack() -> list:
    """Every thread's nesting flattened oldest-first with ages — the
    dump's view; e.g. ``bcast`` > ``bcast_obj`` when a composite wedges
    on its host leg (entries carry ``thread`` to separate concurrent
    collectives, e.g. the async host reducer's background thread)."""
    now = time.monotonic()
    entries = [e for s in _stacks_snapshot() for e in s]
    entries.sort(key=lambda e: e[0])
    return [
        {**info, "age_s": round(now - t0, 3)} for t0, info in entries
    ]


def last_beat() -> Optional[dict]:
    slot = _last_beat[0]
    if slot is None:
        return None
    t0, step = slot
    return {"step": step, "age_s": round(time.monotonic() - t0, 3)}


def progress_age() -> Optional[float]:
    """Seconds since the last progress signal (beat or collective
    exit); None when the process has shown no activity yet."""
    p = _progress[0]
    return None if p is None else time.monotonic() - p


def tail(n: int = 100) -> list:
    """Most recent <= n ring events, oldest first. Lock-free snapshot:
    CPython deques raise RuntimeError when another thread appends
    mid-iteration (the exporter scrapes while the workload records) —
    retry the copy a few times, and prefer an empty tail over taking
    the scrape (or the hang dump) down."""
    n = int(n)
    if n <= 0:
        return []  # a -0 slice would return EVERYTHING
    for _ in range(5):
        try:
            return list(_ring)[-n:]
        except RuntimeError:
            continue
    return []


def reset() -> None:
    """Clear ring/marker/beat state (tests)."""
    _ring.clear()
    _inflight.clear()
    _last_beat[0] = None
    _progress[0] = None


def _thread_stacks() -> dict:
    """{thread-name (id): [frame lines]} for every live thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')} ({tid})"
        out[label] = [
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        ]
    return out


def write_hang_dump(out_dir: str = ".", *, reason: str = "manual",
                    stall_s: Optional[float] = None) -> str:
    """Write ``hang_dump_<rank>.json`` and return its path: the
    watchdog's payload, also callable directly (e.g. from a SIGTERM
    handler). Never raises — forensics must not add a second failure;
    returns "" when even the write fails."""
    try:
        rank = _trace._process_rank()
        path = os.path.join(out_dir, f"hang_dump_{rank}.json")
        payload = {
            "schema": HANG_DUMP_SCHEMA,
            "kind": "hang_dump",
            "t": round(time.time(), 6),
            "pid": os.getpid(),
            "rank": rank,
            "reason": reason,
            "stall_s": stall_s,
            "progress_age_s": (round(progress_age(), 3)
                               if progress_age() is not None else None),
            "in_flight": in_flight(),
            "in_flight_stack": in_flight_stack(),
            "last_beat": last_beat(),
            "threads": _thread_stacks(),
            "ring": tail(RING_CAPACITY),
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=repr)
            f.write("\n")
        return path
    except Exception:
        # The payload build races live threads by design (stacks, ring,
        # markers): ANY failure here must not take the watchdog thread
        # down with the forensics unwritten.
        return ""


class HangWatchdog(threading.Thread):
    """Daemon thread; see module docstring. Fires at most once."""

    def __init__(self, stall_s: float = 300.0, out_dir: str = ".",
                 poll_s: Optional[float] = None) -> None:
        if stall_s <= 0:
            raise ValueError(f"stall_s must be > 0, got {stall_s}")
        super().__init__(name="chainermn-hang-watchdog", daemon=True)
        self.stall_s = float(stall_s)
        self.out_dir = out_dir
        self.poll_s = float(poll_s) if poll_s else max(
            0.05, min(self.stall_s / 4.0, 10.0)
        )
        self.dump_path: Optional[str] = None
        # NOT named _stop: threading.Thread has a private _stop METHOD
        # that join() calls — shadowing it with an Event breaks join.
        self._halt = threading.Event()

    def _stalled(self) -> Optional[str]:
        """Reason string when the process looks wedged, else None."""
        now = time.monotonic()
        # Oldest outermost entry across all threads: the true stall
        # duration of a composite (the inner legs churn; the outer age
        # is how long the whole collective has failed to come back).
        stacks = _stacks_snapshot()
        t0 = stacks[0][0][0] if stacks else None
        if t0 is not None and now - t0 > self.stall_s:
            return f"collective in flight > {self.stall_s}s"
        p = _progress[0]
        if p is not None and now - p > self.stall_s:
            return f"no progress (beat/collective-exit) > {self.stall_s}s"
        return None

    def run(self) -> None:
        while not self._halt.wait(self.poll_s):
            reason = self._stalled()
            if reason is not None:
                self.dump_path = write_hang_dump(
                    self.out_dir, reason=reason, stall_s=self.stall_s
                )
                if self.dump_path:
                    print(
                        f"[chainermn_tpu] HANG detected ({reason}); "
                        f"flight dump: {self.dump_path}",
                        file=sys.stderr, flush=True,
                    )
                return  # fire once; the process is presumed wedged

    def stop(self) -> None:
        self._halt.set()


_watchdog: Optional[HangWatchdog] = None
_watchdog_lock = threading.Lock()


def start_watchdog(stall_s: float = 300.0, out_dir: str = ".",
                   poll_s: Optional[float] = None) -> HangWatchdog:
    """Start (or return the already-running) process watchdog."""
    global _watchdog
    with _watchdog_lock:
        if _watchdog is not None and _watchdog.is_alive():
            return _watchdog
        _watchdog = HangWatchdog(stall_s, out_dir, poll_s)
        _watchdog.start()
        return _watchdog


def stop_watchdog() -> None:
    global _watchdog
    with _watchdog_lock:
        if _watchdog is not None:
            _watchdog.stop()
            _watchdog = None


def maybe_start_from_env() -> Optional[HangWatchdog]:
    """Env-gated start: ``CHAINERMN_TPU_HANG_DUMP_S=<seconds>`` (and
    optional ``..._DIR``). No-op (None) when unset or unparsable."""
    v = os.environ.get(_ENV_STALL)
    if not v:
        return None
    try:
        stall = float(v)
    except ValueError:
        return None
    if stall <= 0:
        return None
    return start_watchdog(stall, os.environ.get(_ENV_DIR, "."))
