"""Cross-rank straggler/drift detection (ISSUE 2 tentpole, layer 3).

A multihost data-parallel step runs at the pace of its slowest process;
one slow host (thermal throttle, noisy neighbour, dying NIC) shows up
only as a globally slower step — silently. This monitor turns that into
a logged, testable signal: every ``interval`` updates the window's
per-phase step-time summaries are exchanged in ONE host-plane
collective (:meth:`ObservationAggregator.flush_per_rank` — an object
allgather, the same wire the metrics aggregation already rides, zero
device-plane collectives), and any process whose phase time diverges
from the cross-rank median by more than ``threshold`` is flagged.

Use standalone (:meth:`StragglerMonitor.update` with a phase-time dict)
or as a :class:`~chainermn_tpu.training.trainer.Trainer` extension
(:meth:`attach`), where it drains the trainer's per-phase window
(data_wait / h2d / compute / logging / extensions).

Collective contract: ``update``/``__call__`` must be invoked at the
same point on every process of the communicator (the Trainer's
fixed-interval extension trigger guarantees this).
"""

from __future__ import annotations

import sys
from typing import Mapping, Optional

from chainermn_tpu.extensions.observation_aggregator import (
    ObservationAggregator,
)
from chainermn_tpu.observability import trace

#: default ``out`` sentinel: resolve ``sys.stderr`` at PRINT time, not
#: at class-definition time — a harness that redirects stderr after
#: import (capsys, redirect_stderr) must still capture the warning.
#: ``out=None`` keeps meaning "no printing".
_STDERR = object()


class StragglerMonitor:
    """Flag processes whose step-phase times drift from the pack.

    Args:
      comm: communicator whose HOST plane the summaries ride (one entry
        per process — the "1 slow host" granularity).
      interval: updates per detection window (as a Trainer extension
        this is the extension interval; see :meth:`attach`).
      threshold: relative divergence that flags a rank:
        ``(value - median) / median > threshold``. Only slower-than-
        median ranks are flagged — a fast rank is not a straggler.
      min_phase_s: phases whose cross-rank median is below this are
        skipped (relative spread on a ~0 ms phase is noise).
      out: stream for the rank-0 warning line (None = no printing).
    """

    def __init__(
        self,
        comm,
        *,
        interval: int = 50,
        threshold: float = 0.3,
        min_phase_s: float = 1e-4,
        out=_STDERR,
    ) -> None:
        if interval < 1:
            raise ValueError(f"interval must be >= 1, got {interval}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        self.comm = comm
        self.interval = interval
        self.threshold = threshold
        self.min_phase_s = min_phase_s
        self.out = out
        self._agg = ObservationAggregator(comm, interval=1)
        #: reports with at least one flagged rank, newest last
        self.reports: list[dict] = []

    # -- Trainer extension protocol ------------------------------------

    def attach(self, trainer) -> "StragglerMonitor":
        """Register on ``trainer`` at this monitor's interval."""
        trainer.extend(self, interval=self.interval)
        return self

    def __call__(self, trainer) -> Optional[dict]:
        return self.update(trainer.consume_phase_window())

    # -- core ----------------------------------------------------------

    def update(self, phases: Mapping[str, float]) -> Optional[dict]:
        """Exchange one window's mean phase times and check divergence.
        COLLECTIVE: every process must call at the same point. Returns
        the report dict, or None when the window was empty everywhere."""
        self._agg.add(dict(phases))
        per_rank = self._agg.flush_per_rank()
        if not any(per_rank):
            return None
        return self.check(per_rank)

    def check(self, per_rank: list) -> dict:
        """Pure detection over per-process summaries (separated from the
        collective exchange so tests can feed synthetic rank data).
        ``per_rank[i]`` is process i's ``{phase: mean_seconds}``."""
        report: dict = {"n_ranks": len(per_rank), "phases": {},
                        "flagged_ranks": []}
        keys = sorted({k for r in per_rank if r for k in r})
        flagged_all: set[int] = set()
        for key in keys:
            vals = [(i, float(r[key])) for i, r in enumerate(per_rank)
                    if r and key in r]
            if len(vals) < 2:
                continue
            xs = sorted(v for _, v in vals)
            n = len(xs)
            med = (xs[n // 2] if n % 2
                   else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
            if med < self.min_phase_s:
                continue
            devs = {i: (v - med) / med for i, v in vals}
            flagged = sorted(i for i, d in devs.items()
                             if d > self.threshold)
            flagged_all.update(flagged)
            worst = max(devs, key=lambda i: devs[i])
            report["phases"][key] = {
                "median_s": round(med, 6),
                "worst_rank": worst,
                "worst_rel_dev": round(devs[worst], 4),
                "flagged": flagged,
            }
        report["flagged_ranks"] = sorted(flagged_all)
        if flagged_all:
            self.reports.append(report)
            rec = trace.active()
            if rec is not None:
                rec.event("straggler", **report)
            stream = sys.stderr if self.out is _STDERR else self.out
            if stream is not None and self.comm.rank == 0:
                detail = "; ".join(
                    f"{k}: rank {v['worst_rank']} "
                    f"+{v['worst_rel_dev'] * 100:.0f}% vs median "
                    f"{v['median_s'] * 1e3:.1f} ms"
                    for k, v in report["phases"].items() if v["flagged"]
                )
                print(
                    f"[chainermn_tpu] straggler warning: rank(s) "
                    f"{report['flagged_ranks']} diverge >"
                    f"{self.threshold * 100:.0f}% — {detail}",
                    file=stream, flush=True,
                )
        return report
