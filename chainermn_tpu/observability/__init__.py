"""Structured observability: collective-wire counters, step-time
breakdown, and cross-rank straggler detection (ISSUE 2; see
docs/observability.md).

Three integrated layers, all host-side (an instrumented program lowers
to exactly the same HLO — zero added device-plane collectives):

- :mod:`~chainermn_tpu.observability.trace` — the event recorder. Wire
  counters for every communicator collective (op, payload bytes, wire
  dtype, duration, autotune provenance of any ``'auto'`` decision),
  step-timeline events from the Trainer, JSONL + Chrome-trace export.
  Enable with ``CHAINERMN_TPU_TRACE=<path.jsonl>`` or
  :func:`~chainermn_tpu.observability.trace.enable`.
- :mod:`~chainermn_tpu.observability.straggler` — cross-rank drift
  detection over :class:`ObservationAggregator` windows.
- ``tools/trace_report.py`` — per-op bytes/time tables (with roofline
  floors where device peaks are known) from an emitted JSONL.

The pre-existing ``jax.profiler`` wrappers stay in
:mod:`chainermn_tpu.utils.observability`; ``profile()`` now records its
start/stop into this event stream as well.
"""

from chainermn_tpu.observability.trace import (
    TRACE_SCHEMA,
    Recorder,
    active,
    chrome_trace,
    disable,
    enable,
    read_jsonl,
    span,
    summarize_overlap,
    write_chrome_trace,
)


def __getattr__(name):
    # Lazy: straggler pulls in ObservationAggregator -> communicators,
    # while the communicators themselves import this package for the
    # trace module — eager re-export here would be a circular import.
    if name == "StragglerMonitor":
        from chainermn_tpu.observability.straggler import StragglerMonitor

        return StragglerMonitor
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )

__all__ = [
    "TRACE_SCHEMA",
    "Recorder",
    "StragglerMonitor",
    "active",
    "chrome_trace",
    "disable",
    "enable",
    "read_jsonl",
    "span",
    "summarize_overlap",
    "write_chrome_trace",
]
