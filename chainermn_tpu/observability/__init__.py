"""Structured observability: collective-wire counters, step-time
breakdown, and cross-rank straggler detection (ISSUE 2; see
docs/observability.md).

Three integrated layers, all host-side (an instrumented program lowers
to exactly the same HLO — zero added device-plane collectives):

- :mod:`~chainermn_tpu.observability.trace` — the event recorder. Wire
  counters for every communicator collective (op, payload bytes, wire
  dtype, duration, autotune provenance of any ``'auto'`` decision),
  step-timeline events from the Trainer, JSONL + Chrome-trace export.
  Enable with ``CHAINERMN_TPU_TRACE=<path.jsonl>`` or
  :func:`~chainermn_tpu.observability.trace.enable`.
- :mod:`~chainermn_tpu.observability.straggler` — cross-rank drift
  detection over :class:`ObservationAggregator` windows.
- ``tools/trace_report.py`` — per-op bytes/time tables (with roofline
  floors where device peaks are known) from an emitted JSONL.

The LIVE plane (ISSUE 6) sits beside the post-hoc trace:

- :mod:`~chainermn_tpu.observability.metrics` — process-local
  Counter/Gauge/Histogram registry, fed by a recorder *tap* (every
  traced site populates metrics with zero new call sites) plus direct
  gauges at stateful host planes; streaming SLO percentiles from fixed
  log-spaced buckets.
- :mod:`~chainermn_tpu.observability.exporter` — stdlib HTTP daemon
  serving ``/metrics`` (Prometheus text), ``/healthz``, and
  ``/trace/tail``; gated by ``CHAINERMN_TPU_METRICS_PORT``.
- :mod:`~chainermn_tpu.observability.flight` — bounded event ring,
  in-flight collective marker, trainer heartbeat, and the hang
  watchdog that turns a silent distributed stall into
  ``hang_dump_<rank>.json``.
- :mod:`~chainermn_tpu.observability.stats` — the shared nearest-rank
  percentile rule (``ceil(q*n)``) behind both the serving rollup and
  the histogram quantiles.

The pre-existing ``jax.profiler`` wrappers stay in
:mod:`chainermn_tpu.utils.observability`; ``profile()`` now records its
start/stop into this event stream as well.
"""

from chainermn_tpu.observability.trace import (
    TRACE_SCHEMA,
    Recorder,
    active,
    chrome_trace,
    disable,
    enable,
    read_jsonl,
    span,
    summarize_overlap,
    write_chrome_trace,
)


def __getattr__(name):
    # Lazy: straggler pulls in ObservationAggregator -> communicators,
    # while the communicators themselves import this package for the
    # trace module — eager re-export here would be a circular import.
    # The live-plane modules stay lazy for the same reason (flight and
    # metrics are imported by the communicator base / host comm).
    if name == "StragglerMonitor":
        from chainermn_tpu.observability.straggler import StragglerMonitor

        return StragglerMonitor
    if name in ("metrics", "exporter", "flight", "stats", "journey",
                "clocksync"):
        import importlib

        return importlib.import_module(
            f"chainermn_tpu.observability.{name}"
        )
    if name == "nearest_rank":
        from chainermn_tpu.observability.stats import nearest_rank

        return nearest_rank
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )

__all__ = [
    "TRACE_SCHEMA",
    "Recorder",
    "StragglerMonitor",
    "active",
    "chrome_trace",
    "clocksync",
    "disable",
    "enable",
    "exporter",
    "flight",
    "journey",
    "metrics",
    "nearest_rank",
    "read_jsonl",
    "span",
    "stats",
    "summarize_overlap",
    "write_chrome_trace",
]
