"""NTP-style clock-offset estimation over the host object plane
(ISSUE 17): the honesty layer under cross-rank timeline merges.

Every trace event stamps ``t`` from the local ``time.time()`` — two
processes' epochs can disagree by milliseconds (or, over a tunnelled
relay, much more), which is larger than the handoff latencies the
journey merge wants to display. The classic two-way exchange bounds
it without any new transport: the client stamps ``t0``, the server
answers with its own clock ``t_srv``, the client stamps ``t1``, and

    offset_sample = t_srv - (t0 + t1) / 2        (server - client)

is exact when the path is symmetric and wrong by at most half the
round trip when it is not. Over ``n`` exchanges the estimate is the
MEDIAN sample (robust to a GC pause or a retransmit polluting one
exchange) and the uncertainty is ``min(rtt) / 2`` — the tightest
half-RTT seen, the standard NTP error bound. The result is emitted as
one ``clock_sync`` trace event, so merged timelines shift honestly
AND carry their error bar (``journey.clock_offsets`` consumes it; a
merge that silently trusted raw epochs would manufacture causality).

Transport contract: anything with ``send_obj(obj, dest)`` /
``recv_obj(source)`` — ``TcpHostComm`` across processes, the
in-process ``LoopbackHub`` endpoints in tests and the dryrun (where
``recv_obj`` raises instead of blocking: pass ``pump`` to run the
server's half between the client's send and recv). The reference
framework leaned on MPI's globally synchronized launch and never
needed this; a host-plane serving cluster has no such luxury.

Pure stdlib — loadable by file path from ``tools/`` without jax.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Callable, Mapping, Optional, Sequence

PING = "clock_ping"
PONG = "clock_pong"

#: exchanges per sync — enough for a stable median, cheap enough to
#: run at cluster start and again whenever drift is suspected.
DEFAULT_EXCHANGES = 8


def estimate_offset(samples: Sequence[tuple]) -> dict:
    """The pure math over ``(t0, t_remote, t1)`` exchange stamps; split
    out so tests can pin it against hand-computed skews."""
    if not samples:
        raise ValueError("clock sync needs at least one exchange")
    offs = sorted(t_remote - (t0 + t1) / 2.0
                  for t0, t_remote, t1 in samples)
    rtts = [t1 - t0 for t0, _t, t1 in samples]
    min_rtt = max(0.0, min(rtts))
    return {
        "offset_s": round(statistics.median(offs), 9),
        "uncertainty_s": round(min_rtt / 2.0, 9),
        "min_rtt_s": round(min_rtt, 9),
        "n": len(samples),
    }


def sync_server_step(endpoint, client: int, *,
                     clock: Callable[[], float] = time.time) -> None:
    """Answer ONE ping from ``client``. The reply is stamped as late
    as possible (right before the send) so the server-side dwell sits
    in the client's RTT, not in the offset."""
    msg = endpoint.recv_obj(client)
    if not isinstance(msg, Mapping) or msg.get("kind") != PING:
        raise ValueError(
            f"clock sync: expected a {PING!r} from rank {client}, got "
            f"{type(msg).__name__}"
        )
    endpoint.send_obj({"kind": PONG, "i": msg.get("i"),
                       "t": float(clock())}, client)


def sync_server(endpoint, client: int, n: int = DEFAULT_EXCHANGES, *,
                clock: Callable[[], float] = time.time) -> None:
    """The server half: answer ``n`` pings from ``client`` (blocking
    transports only — in-process hubs drive :func:`sync_server_step`
    through the client's ``pump``)."""
    for _ in range(n):
        sync_server_step(endpoint, client, clock=clock)


def sync_client(endpoint, server: int, n: int = DEFAULT_EXCHANGES, *,
                pump: Optional[Callable[[], Any]] = None,
                clock: Callable[[], float] = time.time) -> dict:
    """The client half: run ``n`` ping/pong exchanges against
    ``server``, estimate this process's offset TO the server's clock
    (``offset_s`` = server − client: ADD it to local epoch stamps to
    land on the server's timeline), and emit one ``clock_sync`` event
    when a recorder is active. ``pump`` (in-process hubs) is called
    between send and recv to run the server's answering half —
    loopback ``recv_obj`` is loud-not-blocking by design."""
    if n < 1:
        raise ValueError(f"need at least one exchange, got {n}")
    samples = []
    for i in range(n):
        t0 = float(clock())
        endpoint.send_obj({"kind": PING, "i": i}, server)
        if pump is not None:
            pump()
        reply = endpoint.recv_obj(server)
        t1 = float(clock())
        if not isinstance(reply, Mapping) or reply.get("kind") != PONG:
            raise ValueError(
                f"clock sync: expected a {PONG!r} from rank {server}, "
                f"got {type(reply).__name__}"
            )
        samples.append((t0, float(reply["t"]), t1))
    est = estimate_offset(samples)
    # Local import: tools/ loads this module by file path, where the
    # package-absolute import would pull the whole package (and jax).
    if __package__:
        from chainermn_tpu.observability import trace as _trace

        rec = _trace.active()
        if rec is not None:
            rec.event("clock_sync", peer=int(server), **est)
    return est


__all__ = [
    "DEFAULT_EXCHANGES",
    "PING",
    "PONG",
    "estimate_offset",
    "sync_client",
    "sync_server",
    "sync_server_step",
]
