"""HTTP exposition of the live metrics plane (ISSUE 6 tentpole;
docs/observability.md "Live metrics").

A stdlib ``http.server`` daemon thread (no new dependencies) serving:

- ``GET /metrics`` — Prometheus text exposition (v0.0.4) of the
  process registry; on rank 0, peer snapshots cached by
  :meth:`MetricsExporter.merge_peer_snapshots` are appended with a
  ``rank`` label.
- ``GET /healthz`` — JSON liveness: rank, pid, trainer step (the
  flight heartbeat), last-event age, uptime.
- ``GET /trace/tail?n=N`` — the flight ring's most recent N events as
  JSON (forensics without waiting for the JSONL file to flush).

Port contract (``CHAINERMN_TPU_METRICS_PORT``): unset = no server;
``0`` = ephemeral port (the bound port is on the returned exporter and
in ``/healthz`` — tests and the dryrun self-scrape use this);
``N > 0`` = ``N + rank`` per process, so a multi-process job exposes
one endpoint per rank without coordination. The server binds loopback
by default — metrics name workload internals; fronting them publicly
is a deployment decision, not a library default.

The peer merge deliberately does NOT run host collectives from the
scrape thread: an HTTP GET arriving at rank 0 cannot make every other
rank enter an allgather, and trying would deadlock the job on a
monitoring request. Instead :meth:`~MetricsExporter.merge_peer_snapshots`
is a COLLECTIVE the workload calls on every rank (e.g. as a trainer
extension) over the existing ``_host_comm`` object plane; rank 0
caches the gathered snapshots and ``/metrics`` serves own + cached
peers.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import time
import urllib.parse
from typing import Optional

from chainermn_tpu.observability import flight as _flight
from chainermn_tpu.observability import metrics as _metrics
from chainermn_tpu.observability import trace as _trace

ENV_PORT = "CHAINERMN_TPU_METRICS_PORT"

CONTENT_TYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"


class MetricsExporter:
    """One bound, running exposition server; see module docstring."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1") -> None:
        self.registry = (registry if registry is not None
                         else _metrics.registry())
        self.rank = _trace._process_rank()
        self._t0 = time.time()
        self._peer_snapshots: list = []  # [(rank, snapshot), ...]
        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # one scrape per line in a server log would drown the
            # trainer's own output; exposition servers stay silent
            def log_message(self, *_a):
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):
                try:
                    parsed = urllib.parse.urlparse(self.path)
                    if parsed.path == "/metrics":
                        body = exporter.registry.exposition(
                            extra_snapshots=tuple(exporter._peer_snapshots)
                        ).encode()
                        self._reply(200, body, CONTENT_TYPE_METRICS)
                    elif parsed.path == "/healthz":
                        body = (json.dumps(exporter.health())
                                .encode() + b"\n")
                        self._reply(200, body, "application/json")
                    elif parsed.path == "/trace/tail":
                        q = urllib.parse.parse_qs(parsed.query)
                        try:
                            n = int(q.get("n", ["100"])[0])
                        except ValueError:
                            n = 100
                        body = (json.dumps(_flight.tail(n), default=repr)
                                .encode() + b"\n")
                        self._reply(200, body, "application/json")
                    else:
                        self._reply(404, b"not found\n", "text/plain")
                except Exception as e:  # a scrape must never kill the job
                    try:
                        self._reply(
                            500, f"{type(e).__name__}: {e}\n".encode(),
                            "text/plain",
                        )
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"chainermn-metrics-exporter:{self.port}", daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------------

    def health(self) -> dict:
        beat = _flight.last_beat()
        rec = _trace.active()
        last_ev_age = None
        if rec is not None and getattr(rec, "last_event_t", None):
            last_ev_age = round(time.time() - rec.last_event_t, 3)
        return {
            "ok": True,
            "rank": self.rank,
            "pid": os.getpid(),
            "port": self.port,
            "step": beat["step"] if beat else None,
            "last_beat_age_s": beat["age_s"] if beat else None,
            "last_event_age_s": last_ev_age,
            "in_flight_collective": _flight.in_flight(),
            "uptime_s": round(time.time() - self._t0, 3),
            "peer_snapshots": len(self._peer_snapshots),
            # Trailing-window SLO burn per kind/tenant (ISSUE 17) —
            # the health probe's "are we burning the error budget
            # RIGHT NOW" answer; {} until an SLO-bearing finish lands.
            "slo_burn": _metrics.slo_burn_rates(),
            # Speculative acceptance per sampling mode (ISSUE 18) —
            # a sampled-mode collapse is drafter mismatch, not load;
            # {} until a verify tick lands.
            "spec_accept": _metrics.spec_accept_rates(),
        }

    def merge_peer_snapshots(self, comm) -> int:
        """COLLECTIVE over the host object plane — every process of
        ``comm`` must call (trainer-extension cadence, NOT the scrape
        thread; see module docstring). Gathers each rank's registry
        snapshot; rank 0 caches peers for ``/metrics``. Returns the
        number of peer snapshots this rank now serves."""
        snaps = comm.allgather_obj(self.registry.snapshot())
        my = comm.host.rank
        self._peer_snapshots = [
            (r, s) for r, s in enumerate(snaps) if r != my
        ]
        return len(self._peer_snapshots)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5)


# ----------------------------------------------------------------------
# Module-level lifecycle (env-gated autostart)
# ----------------------------------------------------------------------

_exporter: Optional[MetricsExporter] = None
_exporter_lock = threading.Lock()
_env_checked = False


def start(port: int = 0,
          registry: Optional[_metrics.MetricsRegistry] = None,
          host: str = "127.0.0.1") -> MetricsExporter:
    """Start an exposition server (explicit form; tests and dryrun).
    Does not touch the module-global autostarted instance."""
    return MetricsExporter(registry=registry, port=port, host=host)


def active() -> Optional[MetricsExporter]:
    """The env-autostarted exporter, or None."""
    return _exporter


def maybe_start_from_env() -> Optional[MetricsExporter]:
    """Idempotent env-gated start (the trainer / scheduler front door):
    honours ``CHAINERMN_TPU_METRICS_PORT`` (module docstring), installs
    the recorder tap so the endpoint is actually populated, and arms
    the hang watchdog when ITS env gate is set. Unset/unusable env
    returns None and is never re-checked (one string lookup per call
    after that)."""
    global _exporter, _env_checked
    if _exporter is not None:
        return _exporter
    if _env_checked:
        return None
    with _exporter_lock:
        if _exporter is not None or _env_checked:
            return _exporter
        _env_checked = True
        # The watchdog's env gate is independent of the metrics port:
        # arm it FIRST, unconditionally — a serving process with
        # HANG_DUMP_S set but no (or an unbindable) metrics port must
        # still get hang forensics (review finding: the early returns
        # below used to silently disarm it).
        _flight.maybe_start_from_env()
        v = os.environ.get(ENV_PORT)
        if v is None or v == "":
            return None
        try:
            base = int(v)
        except ValueError:
            return None
        if base < 0:
            return None
        port = 0 if base == 0 else base + _trace._process_rank()
        reg = _metrics.install_tap()
        try:
            _exporter = MetricsExporter(registry=reg, port=port)
        except OSError:
            return None  # port taken: telemetry must never kill the job
        return _exporter


def stop() -> None:
    """Tear down the env-autostarted exporter (tests)."""
    global _exporter, _env_checked
    with _exporter_lock:
        if _exporter is not None:
            _exporter.close()
        _exporter = None
        _env_checked = False
