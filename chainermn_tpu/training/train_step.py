"""The jitted SPMD train step.

TPU mapping of the reference's hot loop (SURVEY.md section 3.2): where
ChainerMN ran eager backward, then packed gradients into a flat buffer,
``ncclAllReduce``-d it, scaled and unpacked (``pure_nccl_communicator.py``
(dagger)), here the *entire iteration* — forward, backward, gradient pmean
over the mesh, optimizer update — is one ``jax.jit`` program: XLA fuses the
packing/scaling away and overlaps the collective with remaining backward
compute (its latency-hiding scheduler provides what double buffering bought
on GPU).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.optimizers import (
    MultiNodeOptimizer,
    _ErrorFeedbackState,
    allreduce_gradients,
)

PyTree = Any


def _arity(fn: Callable) -> int:
    """Number of positional parameters ``fn`` accepts (inf if *args)."""
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return 2
    n = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            n += 1
        elif p.kind == p.VAR_POSITIONAL:
            return 99
    return n


class TrainState(NamedTuple):
    """Replicated training state. ``model_state`` carries non-gradient
    collections (e.g. BatchNorm running stats — the values the reference's
    ``AllreducePersistent`` synchronized)."""

    params: PyTree
    opt_state: Any
    step: jax.Array
    model_state: PyTree = ()


def create_train_state(
    params: PyTree,
    optimizer,
    comm: Optional[CommunicatorBase] = None,
    *,
    model_state: PyTree = (),
) -> TrainState:
    """Initialise (and replicate, when a communicator is given) the state —
    the explicit version of the reference's first-update ``bcast_data``.

    With an error-feedback optimizer the EF residual is PER-RANK state:
    it is initialised stacked ``[n_slots, ...]`` and SHARDED over the
    communicator's grad axes, so the jitted train step can carry it with
    honest per-rank sharding (see ``make_train_step``'s EF state spec)."""
    if comm is not None:
        params = comm.bcast_data(params)
        if jax.tree.leaves(model_state):
            model_state = comm.bcast_data(model_state)
    opt_state = optimizer.init(params)
    if getattr(optimizer, "error_feedback", False):
        if comm is None:
            raise ValueError(
                "error_feedback training state needs a communicator "
                "(the residual is sharded over its grad axes)"
            )
        sharding = NamedSharding(comm.mesh, P(comm.grad_axes))
        n = comm.size

        def stack(r):
            # Created directly sharded: a bare jnp.zeros + device_put
            # would commit the full n x params array to device 0 first
            # (the same spike trainer.py's prefetch placement avoids).
            shape = (n,) + r.shape
            return jax.make_array_from_callback(
                shape, sharding,
                lambda idx: np.zeros(
                    tuple(len(range(*sl.indices(dim)))
                          for sl, dim in zip(idx, shape)),
                    r.dtype,
                ),
            )

        opt_state = opt_state._replace(
            residual=jax.tree.map(stack, opt_state.residual)
        )
    state = TrainState(
        params=params,
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
        model_state=model_state,
    )
    if comm is not None:
        state = _place_state(state, optimizer, comm)
    return state


def _train_state_spec(optimizer, comm):
    """The :class:`TrainState` prefix-spec the jitted step carries
    (``P()`` when fully replicated) — ONE owner shared by
    ``make_train_step`` (shard_map in/out specs) and
    ``create_train_state`` (initial placement): the state is created
    already laid out exactly as the compiled step expects, so the
    second step cannot recompile on a committed-ness change — step
    compiles stay pinned at 1 (the ISSUE 12 dryrun's trainer pin)."""
    if getattr(optimizer, "error_feedback", False):
        # The EF residual is PER-RANK state: stacked [n_slots, ...] over
        # the COMMUNICATOR's grad axes (the layout create_train_state
        # initialises), the rest replicated.
        return TrainState(
            params=P(),
            opt_state=_ErrorFeedbackState(
                inner=P(), residual=P(comm.grad_axes)
            ),
            step=P(),
            model_state=P(),
        )
    # Schedule-aware state carry: a 'zero' reduction schedule's
    # optimizer state is 1/n per shard (stacked [n, ...] leaves) — the
    # optimizer publishes the prefix spec and the step threads it, the
    # same honest-sharding pattern as the EF residual.
    opt_spec = P()
    spec_fn = getattr(optimizer, "opt_state_spec", None)
    if spec_fn is not None:
        opt_spec = spec_fn()
    if opt_spec != P():
        return TrainState(
            params=P(), opt_state=opt_spec, step=P(), model_state=P()
        )
    return P()


def _place_state(state: "TrainState", optimizer, comm) -> "TrainState":
    """Commit every state leaf to ``comm.mesh`` per the step's own spec
    (:func:`_train_state_spec`): already-placed leaves (bcast params,
    the EF residual's sharded stack) pass through untouched, everything
    else lands replicated (or per its prefix spec). Placement at
    creation time is what pins the step's jit cache at 1 — an
    uncommitted opt_state would compile once unspecified and once
    committed. Multi-process meshes are left alone: ``device_put`` of a
    host array onto non-addressable devices is not a local operation
    (the 4-proc scaling rehearsal caught a gloo wire fault from it) —
    there the jit boundary keeps owning placement, at the documented
    cost of its one extra compile."""
    mesh_devices = comm.mesh.devices.flat
    try:
        pidx = jax.process_index()
    except Exception:
        return state
    if any(d.process_index != pidx for d in mesh_devices):
        return state
    spec = _train_state_spec(optimizer, comm)

    def put(x, s):
        if not isinstance(x, (jax.Array, np.ndarray)):
            return x  # exotic leaf: leave its semantics alone
        sharding = NamedSharding(comm.mesh, s)
        if isinstance(x, jax.Array) and x.sharding == sharding:
            return x  # already placed (no copy)
        return jax.device_put(jnp.asarray(x), sharding)

    if isinstance(spec, P):
        return jax.tree.map(lambda x: put(x, spec), state)
    # prefix tree: broadcast each P leaf over its state subtree
    return jax.tree.map(
        lambda s, sub: jax.tree.map(lambda x: put(x, s), sub),
        spec, state, is_leaf=lambda s: isinstance(s, P),
    )


def normalize_loss_fn(loss_fn: Callable) -> Callable:
    """Wrap the user's ``loss_fn`` into the canonical
    ``(params, batch, model_state) -> (loss, (metrics, new_model_state))``
    form, accepting every documented return shape: plain ``loss``,
    ``(loss, metrics)``, or ``(loss, (metrics, new_model_state))``; with or
    without the ``model_state`` argument. The single place that owns this
    contract — used by the shard_map step here and the FSDP step
    (:mod:`chainermn_tpu.parallel.fsdp`)."""
    takes_model_state = _arity(loss_fn) >= 3

    def _loss_with_aux(params, batch, model_state):
        if takes_model_state:
            out = loss_fn(params, batch, model_state)
        else:
            out = loss_fn(params, batch)
        if isinstance(out, tuple):
            loss, aux = out
            if isinstance(aux, tuple) and len(aux) == 2:
                metrics, new_model_state = aux
            else:
                metrics, new_model_state = aux, model_state
        else:
            loss, metrics, new_model_state = out, {}, model_state
        return loss, (metrics, new_model_state)

    return _loss_with_aux


def make_train_step(
    loss_fn: Callable,
    optimizer,
    comm: Optional[CommunicatorBase] = None,
    *,
    axis_name: Optional[str] = None,
    batch_spec: P | None = None,
    donate: bool = True,
    accum_steps: int = 1,
    plan=None,
    param_specs=None,
    pipeline=None,
):
    """Build the jitted data-parallel train step.

    Args:
      loss_fn: ``loss_fn(params, batch, model_state) -> (loss, (metrics_dict,
        new_model_state))`` or ``loss_fn(params, batch) -> loss``. The loss
        must be the *local-batch mean*; cross-shard averaging is applied by
        the step (gradient pmean — the reference's ``allreduce_grad``).
      optimizer: a :class:`MultiNodeOptimizer` (does its own reduction,
        honouring compression/double-buffering) or any plain optax transform
        (the step then reduces gradients itself).
      comm: the communicator whose mesh the step compiles over. May be
        omitted when ``plan`` is given.
      batch_spec: PartitionSpec for every batch leaf; defaults to sharding
        the leading dim over the communicator's grad axes.
      plan: a :class:`~chainermn_tpu.parallel.plan.ParallelPlan` — the
        global-view path: the step is compiled by the plan (one shard_map
        over the plan's ``data x zero x pipe x model`` mesh, spec
        providers instead of call-site wrappers, donation threaded
        through). ``optimizer`` is unwrapped to its plain inner transform
        via :func:`chainermn_tpu.optimizers.inner_transform`; build the
        state with ``plan.create_train_state``. ``param_specs`` marks
        model/pipe-stacked leaves and ``pipeline`` passes the
        :class:`~chainermn_tpu.parallel.plan.PipelinePlanSpec` of a
        ``pipe`` plan; ``axis_name``/``accum_steps``/``batch_spec`` do
        not apply on this path.
      accum_steps: gradient accumulation — each shard's batch is split into
        this many microbatches, run through a ``lax.scan`` (one compiled
        program, activations live for ONE microbatch at a time), and the
        averaged gradient crosses the wire in a SINGLE allreduce. The
        large-effective-batch regime of the reference's 32K-batch ImageNet
        runs (SURVEY.md section 6) without the memory of the full batch.
        Microbatches see identical params; for STATELESS models the
        accumulated step equals the full-batch step exactly. Models with
        ``model_state`` (BatchNorm) thread it sequentially through the
        microbatches — batch statistics become per-microbatch and running
        averages get ``accum_steps`` momentum updates per step, the
        standard grad-accumulation semantics but NOT identical to one
        full-batch pass.

    Returns:
      ``step(state, batch) -> (state, metrics)``, jitted over ``comm.mesh``
      (or the plan's mesh).
    """
    if plan is not None:
        if accum_steps != 1 or axis_name is not None or batch_spec is not None:
            raise ValueError(
                "plan= owns the batch/axis layout: axis_name, batch_spec "
                "and accum_steps do not apply to a plan-compiled step"
            )
        return plan.compile_train_step(
            loss_fn, optimizer,
            param_specs=param_specs, donate=donate, pipeline=pipeline,
        )
    if comm is None:
        raise ValueError("pass a communicator (or plan=)")
    if param_specs is not None or pipeline is not None:
        raise ValueError(
            "param_specs/pipeline only apply to the plan= path"
        )
    mesh = comm.mesh
    axes = axis_name if axis_name is not None else comm.grad_axes
    if batch_spec is None:
        batch_spec = P(axes)
    reduce_in_step = not getattr(optimizer, "handles_cross_rank_sync",
                                 False)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    # The EF residual is PER-RANK state: carry it with an honest
    # per-rank spec (stacked [n_slots, ...] over the COMMUNICATOR's grad
    # axes — the layout create_train_state initialises; independent of
    # any axis_name override, because the EF reduction itself always
    # runs over comm.grad_axes) instead of the replicated P() the rest
    # of the state uses. The optimizer sees a single layout: local_step
    # squeezes the per-slot [1, ...] slice around opt.update.
    ef = getattr(optimizer, "error_feedback", False)
    # One owner for the state layout (_train_state_spec): the same spec
    # create_train_state places the initial state with, so the compiled
    # step's inputs arrive exactly as laid out — no second compile.
    state_spec: Any = _train_state_spec(optimizer, comm)

    _loss_with_aux = normalize_loss_fn(loss_fn)

    def _grads_single(state, batch):
        grad_fn = jax.value_and_grad(_loss_with_aux, has_aux=True)
        (loss, (metrics, model_state)), grads = grad_fn(
            state.params, batch, state.model_state
        )
        return grads, loss, metrics, model_state

    def _grads_accumulated(state, batch):
        def to_micro(leaf):
            if leaf.shape[0] % accum_steps != 0:
                raise ValueError(
                    f"local batch dim {leaf.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}"
                )
            return leaf.reshape(
                accum_steps, leaf.shape[0] // accum_steps, *leaf.shape[1:]
            )

        micro = jax.tree.map(to_micro, batch)
        grad_fn = jax.value_and_grad(_loss_with_aux, has_aux=True)

        def body(carry, mb):
            gsum, model_state = carry
            (loss, (metrics, model_state)), g = grad_fn(
                state.params, mb, model_state
            )
            gsum = jax.tree.map(jnp.add, gsum, g)
            return (gsum, model_state), (loss, metrics)

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        (gsum, model_state), (losses, metrics_stack) = lax.scan(
            body, (zeros, state.model_state), micro
        )
        grads = jax.tree.map(lambda g: g / accum_steps, gsum)
        loss = losses.mean()
        metrics = jax.tree.map(lambda m: m.mean(0), metrics_stack)
        return grads, loss, metrics, model_state

    def local_step(state: TrainState, batch):
        if accum_steps == 1:
            grads, loss, metrics, model_state = _grads_single(state, batch)
        else:
            grads, loss, metrics, model_state = _grads_accumulated(
                state, batch
            )
        if reduce_in_step:
            grads = allreduce_gradients(grads, comm)
        opt_in = state.opt_state
        if ef:
            # Hand the optimizer its single supported layout: this
            # slot's squeezed residual (the [n_slots, ...] layout is
            # validated host-side before the jitted call).
            opt_in = opt_in._replace(
                residual=jax.tree.map(lambda e: e[0], opt_in.residual)
            )
        updates, opt_state = optimizer.update(grads, opt_in, state.params)
        if ef:
            opt_state = opt_state._replace(
                residual=jax.tree.map(lambda e: e[None],
                                      opt_state.residual)
            )
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **metrics}
        metrics = lax.pmean(metrics, axes)
        # model_state (e.g. BN stats) must not drift across shards:
        model_state = lax.pmean(model_state, axes)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            step=state.step + 1,
            model_state=model_state,
        )
        return new_state, metrics

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    jitted = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    # Overlap metadata for the observability layer: the Trainer emits
    # this once as an ``overlap_config`` trace event, so a trace's
    # comm-hidden numbers carry the mode that produced them (schedule,
    # staleness, donation). Best-effort — the jit wrapper may refuse
    # attributes on some jax versions.
    db = bool(getattr(optimizer, "double_buffering", False))
    overlap_info = {
        "double_buffering": db,
        "staleness": 1 if db else 0,
        "schedule": getattr(optimizer, "reduction_schedule", None),
        "donate": bool(donate),
    }
    try:
        jitted.overlap_info = overlap_info
    except (AttributeError, TypeError):
        pass
    if not ef:
        return jitted

    template_cache: dict = {}

    def step_with_residual_check(state, batch):
        # Host-side shape gate BEFORE shard_map applies its specs: a
        # bare optimizer.init() state (unstacked residual) would
        # otherwise die in a generic divisibility/rank sharding error
        # that never names the real mistake. The expected per-slot
        # shapes come from the OPTIMIZER's own residual template
        # (eval_shape of init — abstract, no allocation): full-param
        # leaves for the flat wire, per-bucket shard buffers for the
        # topology-aware wire. Cached per params-structure.
        key = (
            jax.tree.structure(state.params),
            tuple((np.shape(p), str(getattr(p, "dtype", "?")))
                  for p in jax.tree.leaves(state.params)),
        )
        if key not in template_cache:
            template_cache[key] = jax.tree.leaves(
                jax.eval_shape(optimizer.init, state.params).residual
            )
        t_leaves = template_cache[key]
        e_leaves = jax.tree.leaves(state.opt_state.residual)
        if len(e_leaves) != len(t_leaves):
            raise ValueError(
                "error-feedback residual has "
                f"{len(e_leaves)} leaves but this optimizer's residual "
                f"template has {len(t_leaves)} — a partially restored or "
                "hand-edited opt_state cannot be carried by "
                "make_train_step; rebuild it with create_train_state(...)"
            )
        for e, t in zip(e_leaves, t_leaves):
            eshape = np.shape(e)
            if eshape != (comm.size,) + t.shape:
                raise ValueError(
                    "error-feedback residual leaf has shape "
                    f"{eshape}, expected {(comm.size,) + t.shape} "
                    "(stacked per mesh slot) — build the state with "
                    "create_train_state(...); a bare "
                    "optimizer.init(params) state cannot be carried by "
                    "make_train_step"
                )
        return jitted(state, batch)

    step_with_residual_check.overlap_info = overlap_info
    return step_with_residual_check


def make_eval_step(
    metric_fn: Callable,
    comm: CommunicatorBase,
    *,
    batch_spec: P | None = None,
):
    """Jitted eval step: ``metric_fn(params, batch, model_state) -> dict`` of
    local-batch-mean metrics, pmean-ed over the mesh (device plane of the
    reference's multi-node evaluator)."""
    mesh = comm.mesh
    axes = comm.grad_axes
    if batch_spec is None:
        batch_spec = P(axes)

    takes_model_state = _arity(metric_fn) >= 3

    def local(params, batch, model_state):
        if takes_model_state:
            metrics = metric_fn(params, batch, model_state)
        else:
            metrics = metric_fn(params, batch)
        return lax.pmean(metrics, axes)

    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), batch_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
