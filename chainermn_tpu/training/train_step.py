"""The jitted SPMD train step.

TPU mapping of the reference's hot loop (SURVEY.md section 3.2): where
ChainerMN ran eager backward, then packed gradients into a flat buffer,
``ncclAllReduce``-d it, scaled and unpacked (``pure_nccl_communicator.py``
(dagger)), here the *entire iteration* — forward, backward, gradient pmean
over the mesh, optimizer update — is one ``jax.jit`` program: XLA fuses the
packing/scaling away and overlaps the collective with remaining backward
compute (its latency-hiding scheduler provides what double buffering bought
on GPU).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.optimizers import MultiNodeOptimizer, allreduce_gradients

PyTree = Any


def _arity(fn: Callable) -> int:
    """Number of positional parameters ``fn`` accepts (inf if *args)."""
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return 2
    n = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            n += 1
        elif p.kind == p.VAR_POSITIONAL:
            return 99
    return n


class TrainState(NamedTuple):
    """Replicated training state. ``model_state`` carries non-gradient
    collections (e.g. BatchNorm running stats — the values the reference's
    ``AllreducePersistent`` synchronized)."""

    params: PyTree
    opt_state: Any
    step: jax.Array
    model_state: PyTree = ()


def create_train_state(
    params: PyTree,
    optimizer,
    comm: Optional[CommunicatorBase] = None,
    *,
    model_state: PyTree = (),
) -> TrainState:
    """Initialise (and replicate, when a communicator is given) the state —
    the explicit version of the reference's first-update ``bcast_data``."""
    if comm is not None:
        params = comm.bcast_data(params)
        if jax.tree.leaves(model_state):
            model_state = comm.bcast_data(model_state)
    return TrainState(
        params=params,
        opt_state=optimizer.init(params),
        step=jnp.zeros((), jnp.int32),
        model_state=model_state,
    )


def normalize_loss_fn(loss_fn: Callable) -> Callable:
    """Wrap the user's ``loss_fn`` into the canonical
    ``(params, batch, model_state) -> (loss, (metrics, new_model_state))``
    form, accepting every documented return shape: plain ``loss``,
    ``(loss, metrics)``, or ``(loss, (metrics, new_model_state))``; with or
    without the ``model_state`` argument. The single place that owns this
    contract — used by the shard_map step here and the FSDP step
    (:mod:`chainermn_tpu.parallel.fsdp`)."""
    takes_model_state = _arity(loss_fn) >= 3

    def _loss_with_aux(params, batch, model_state):
        if takes_model_state:
            out = loss_fn(params, batch, model_state)
        else:
            out = loss_fn(params, batch)
        if isinstance(out, tuple):
            loss, aux = out
            if isinstance(aux, tuple) and len(aux) == 2:
                metrics, new_model_state = aux
            else:
                metrics, new_model_state = aux, model_state
        else:
            loss, metrics, new_model_state = out, {}, model_state
        return loss, (metrics, new_model_state)

    return _loss_with_aux


def make_train_step(
    loss_fn: Callable,
    optimizer,
    comm: CommunicatorBase,
    *,
    axis_name: Optional[str] = None,
    batch_spec: P | None = None,
    donate: bool = True,
):
    """Build the jitted data-parallel train step.

    Args:
      loss_fn: ``loss_fn(params, batch, model_state) -> (loss, (metrics_dict,
        new_model_state))`` or ``loss_fn(params, batch) -> loss``. The loss
        must be the *local-batch mean*; cross-shard averaging is applied by
        the step (gradient pmean — the reference's ``allreduce_grad``).
      optimizer: a :class:`MultiNodeOptimizer` (does its own reduction,
        honouring compression/double-buffering) or any plain optax transform
        (the step then reduces gradients itself).
      batch_spec: PartitionSpec for every batch leaf; defaults to sharding
        the leading dim over the communicator's grad axes.

    Returns:
      ``step(state, batch) -> (state, metrics)``, jitted over ``comm.mesh``.
    """
    mesh = comm.mesh
    axes = axis_name if axis_name is not None else comm.grad_axes
    if batch_spec is None:
        batch_spec = P(axes)
    reduce_in_step = not isinstance(optimizer, MultiNodeOptimizer)

    _loss_with_aux = normalize_loss_fn(loss_fn)

    def local_step(state: TrainState, batch):
        grad_fn = jax.value_and_grad(_loss_with_aux, has_aux=True)
        (loss, (metrics, model_state)), grads = grad_fn(
            state.params, batch, state.model_state
        )
        if reduce_in_step:
            grads = allreduce_gradients(grads, comm)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **metrics}
        metrics = lax.pmean(metrics, axes)
        # model_state (e.g. BN stats) must not drift across shards:
        model_state = lax.pmean(model_state, axes)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            step=state.step + 1,
            model_state=model_state,
        )
        return new_state, metrics

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_eval_step(
    metric_fn: Callable,
    comm: CommunicatorBase,
    *,
    batch_spec: P | None = None,
):
    """Jitted eval step: ``metric_fn(params, batch, model_state) -> dict`` of
    local-batch-mean metrics, pmean-ed over the mesh (device plane of the
    reference's multi-node evaluator)."""
    mesh = comm.mesh
    axes = comm.grad_axes
    if batch_spec is None:
        batch_spec = P(axes)

    takes_model_state = _arity(metric_fn) >= 3

    def local(params, batch, model_state):
        if takes_model_state:
            metrics = metric_fn(params, batch, model_state)
        else:
            metrics = metric_fn(params, batch)
        return lax.pmean(metrics, axes)

    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), batch_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
