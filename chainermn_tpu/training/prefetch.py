"""Device-side input prefetching.

The reference's input story was Chainer's ``MultiprocessIterator`` (host
worker processes); its device transfer happened synchronously inside the
update. This framework's native C++ loader covers the host side
(:mod:`chainermn_tpu.native.data_loader`); this module covers the
device side: keep the next ``size`` batches already submitted for
transfer so the host→HBM copy of batch ``t+1`` overlaps the step running
on batch ``t`` (JAX dispatch is asynchronous — ``device_put`` returns
while the copy is in flight; yielding from a bounded deque gives the
copies a head start without unbounded memory growth).

The classic pattern (flax's ``jax_utils.prefetch_to_device``) adapted to
this framework's batch flow: works on any pytree iterator, optionally
placing to an explicit sharding (multihost global batches pass through
untouched — they are already device-resident).
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator, Optional

import jax

PyTree = Any


def prefetch_to_device(
    iterator: Iterable[PyTree],
    size: int = 2,
    *,
    sharding: Optional[Any] = None,
) -> Iterator[PyTree]:
    """Yield batches from ``iterator`` with up to ``size`` of them already
    submitted to the device.

    Args:
      iterator: yields host-side batch pytrees (numpy or jax arrays; jax
        arrays pass through placement untouched when already committed).
      size: in-flight batch count. 2 = classic double buffering; each
        buffered batch holds HBM for its full pytree, so keep it small.
      sharding: optional ``jax.sharding.Sharding`` (or pytree of them) for
        ``jax.device_put``; default places to the default device (the
        jitted step re-places under its own in_shardings as needed, which
        for host arrays is free — the bytes are already on device).
    """
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")

    def put(batch: PyTree) -> PyTree:
        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.tree.map(
            lambda leaf: leaf
            if isinstance(leaf, jax.Array)
            else jax.device_put(leaf),
            batch,
        )

    def gen() -> Iterator[PyTree]:
        queue: collections.deque = collections.deque()
        it = iter(iterator)
        try:
            while True:
                while len(queue) < size:
                    queue.append(put(next(it)))
                yield queue.popleft()
        except StopIteration:
            while queue:
                yield queue.popleft()

    # Validate eagerly at the call site (a generator function would defer
    # the ValueError to the first next(), far from the faulty argument).
    return gen()


__all__ = ["prefetch_to_device"]
