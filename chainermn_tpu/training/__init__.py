"""Training integration: jitted SPMD train-step builder and a minimal trainer.

The reference embedded into Chainer's Trainer/Updater (SURVEY.md section 3.2);
this framework owns its loop. The heart is :func:`make_train_step`: ONE jitted
function per iteration — forward, backward, gradient psum over the mesh, and
the optimizer update — which is the TPU mapping of the reference's whole
``_MultiNodeOptimizer.update`` hot path.
"""

from chainermn_tpu.training.train_step import TrainState, make_train_step, make_eval_step
from chainermn_tpu.training.trainer import Trainer
from chainermn_tpu.training.prefetch import prefetch_to_device

__all__ = [
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "Trainer",
    "prefetch_to_device",
]
