"""Minimal trainer loop with rank-0 reporting and extension triggers.

The reference rode Chainer's ``Trainer``/``Updater``/``Extension`` machinery
(external to it); a standalone framework needs its own loop. Reporting
follows the reference's observability pattern exactly (SURVEY.md section 5):
**gate reporter output on rank 0** (``comm.rank == 0`` in every example
(dagger)), aggregate metrics across processes before logging.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Iterable, Optional

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.observability import flight as _flight
from chainermn_tpu.observability import metrics as _metrics
from chainermn_tpu.observability import trace as _trace

PyTree = Any


def default_collate(batch: list) -> Any:
    """list of examples -> stacked numpy pytree. Examples may be tuples
    (``(x, y)``), dicts, or plain arrays."""
    first = batch[0]
    if isinstance(first, tuple):
        return tuple(np.stack([b[i] for b in batch]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([b[k] for b in batch]) for k in first}
    return np.stack(batch)


def host_local_batch_to_global(batch: Any, comm: CommunicatorBase, spec=None):
    """Assemble each process's host-local batch into the global sharded
    arrays a jitted step's ``in_specs`` expect. No-op on a single process.

    Default ``spec`` treats local batches as this process's data-parallel
    shard (leading dim concatenated over processes — the
    ``scatter_dataset`` norm). Pass ``P()`` for master-broadcast iterators
    where every process holds the identical batch.
    """
    if comm.host.size == 1:
        return batch
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    spec = P(comm.grad_axes) if spec is None else spec
    return multihost_utils.host_local_array_to_global_array(
        batch, comm.mesh, spec
    )


class Trainer:
    """Drive ``step_fn`` over an iterator with periodic extensions.

    Extensions are callables ``ext(trainer) -> None`` registered with an
    iteration interval — the shape of Chainer's extension protocol, enough
    to host the multi-node evaluator and checkpointer (SURVEY.md section 2.7).
    """

    def __init__(
        self,
        step_fn: Callable,
        state: Any,
        train_iter: Iterable,
        comm: CommunicatorBase,
        *,
        collate: Callable = default_collate,
        batch_spec=None,
        log_interval: int = 100,
        out=sys.stdout,
        prefetch: int = 0,
    ) -> None:
        self.step_fn = step_fn
        self.state = state
        self.train_iter = train_iter
        self.comm = comm
        self.collate = collate
        #: PartitionSpec describing what each process's local batch IS in
        #: the global batch (see :func:`host_local_batch_to_global`).
        # Master-broadcast iterators deliver the IDENTICAL batch to every
        # process; treating those as data-parallel shards would silently
        # duplicate every example, so detect and default to replicated.
        if batch_spec is None and getattr(
            train_iter, "replicated_batches", False
        ):
            from jax.sharding import PartitionSpec

            batch_spec = PartitionSpec()
        self.batch_spec = batch_spec
        self.log_interval = log_interval
        self.out = out
        #: batches kept in flight on device ahead of the step (0 = off;
        #: 2 = double buffering). See
        #: :func:`chainermn_tpu.training.prefetch.prefetch_to_device`.
        self.prefetch = prefetch
        self.iteration = 0
        #: cross-rank aggregated host metrics at the last log point —
        #: populated on EVERY rank (via :class:`ObservationAggregator`),
        #: so non-zero ranks can drive extensions off metrics; rank 0
        #: additionally pretty-prints its LOCAL metrics, unchanged.
        self.observation: dict[str, float] = {}
        self._extensions: list[tuple[int, Callable]] = []
        # Step-phase window for the observability layer: per-phase
        # second sums since the last consume_phase_window() (the
        # straggler monitor's input) + the h2d handoff slot from the
        # batch generator.
        self._phase_sums: dict[str, float] = {}
        self._phase_steps = 0
        self._h2d_pending = 0.0
        from chainermn_tpu.extensions.observation_aggregator import (
            ObservationAggregator,
        )

        self._obs_agg = ObservationAggregator(comm)

    def extend(self, extension: Callable, *, interval: int = 1) -> None:
        self._extensions.append((interval, extension))

    # ------------------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.comm.rank == 0:
            print(msg, file=self.out, flush=True)

    def _collated_batches(self, n: int):
        """Yield exactly ``n`` collated, mesh-global batches, restarting
        the epoch iterator as needed (with the empty-epoch guard)."""
        produced = 0
        it = iter(self.train_iter)
        fresh_epoch = True
        while produced < n:
            try:
                batch = next(it)
                fresh_epoch = False
            except StopIteration:
                if fresh_epoch:
                    raise RuntimeError(
                        "train iterator yielded no batches in a full epoch "
                        "(dataset shard smaller than batch size with "
                        "drop_last?) — aborting instead of spinning"
                    )
                it = iter(self.train_iter)
                fresh_epoch = True
                continue
            produced += 1
            collated = self.collate(batch)
            # Time the host→device/global-array assembly separately from
            # the pull (the step-timeline's ``h2d`` phase). ACCUMULATED,
            # not assigned: with ``prefetch`` on, one loop pull can
            # drive several assemblies (queue fill) — they all belong to
            # the step whose data interval paid for them, so the loop
            # drains the accumulator once per step.
            t_h2d = time.perf_counter()
            out = host_local_batch_to_global(
                collated, self.comm, self.batch_spec
            )
            self._h2d_pending += time.perf_counter() - t_h2d
            yield out

    def run(self, max_iterations: int) -> Any:
        try:
            return self._run_impl(max_iterations)
        finally:
            # The run is OVER — returned OR raised: stand the heartbeat
            # down so a process that lingers after training (eval,
            # checkpointing, a driver that caught the exception) is not
            # mistaken for a hang by the watchdog; its fire-once dump
            # must stay in the barrel for a real stall (review finding:
            # the raise path used to leave a stale beat).
            _flight.quiesce()

    def _run_impl(self, max_iterations: int) -> Any:
        t0 = time.perf_counter()
        # Live-telemetry front door (ISSUE 6): honour the metrics-port
        # and hang-watchdog env gates once per run. Both are no-ops
        # (one env read) when unset — and must never break training.
        try:
            from chainermn_tpu.observability import exporter as _exporter

            _exporter.maybe_start_from_env()
            _flight.maybe_start_from_env()
        except Exception:
            pass
        rec0 = _trace.active()
        if rec0 is not None:
            # Comm/compute-overlap configuration of the step driving this
            # loop (make_train_step attaches it): recorded once so the
            # trace's wire events can be read against the mode —
            # double-buffered staleness, reduction schedule, donation —
            # that produced them (tools/trace_report.py "overlap").
            info = getattr(self.step_fn, "overlap_info", None)
            if info:
                rec0.event("overlap_config", **dict(info))
        batches = self._collated_batches(max_iterations - self.iteration)
        if self.prefetch:
            import math

            from jax.sharding import NamedSharding, PartitionSpec

            from chainermn_tpu.training.prefetch import prefetch_to_device

            # Place straight to the step's batch sharding: a bare
            # device_put would commit the whole global batch to device 0
            # (prefetch-deep HBM spike there) and the step would then
            # reshard device-to-device.
            spec = (
                self.batch_spec
                if self.batch_spec is not None
                else PartitionSpec(self.comm.grad_axes)
            )
            sharding = NamedSharding(self.comm.mesh, spec)
            dim0_axes = spec[0] if len(spec) else None
            if dim0_axes is None:
                n_data = 1
            elif isinstance(dim0_axes, tuple):
                n_data = math.prod(
                    self.comm.mesh.shape[a] for a in dim0_axes
                )
            else:
                n_data = self.comm.mesh.shape[dim0_axes]

            def _place(bs):
                # Enabling prefetch must never change which batches are
                # accepted: mesh-shard only batches whose leading dims
                # divide the data axes; others keep the default placement
                # (prefetch_to_device passes jax.Arrays through).
                for b in bs:
                    fits = all(
                        leaf.shape[0] % n_data == 0
                        for leaf in jax.tree.leaves(b)
                        if getattr(leaf, "ndim", 0) >= 1
                    )
                    yield jax.device_put(b, sharding) if fits else b

            batches = prefetch_to_device(_place(batches), self.prefetch)
        it = iter(batches)
        while True:
            # --- data-wait: pulling the next collated global batch
            # (collate + epoch restarts; with prefetch, also the queue
            # wait). The generator accumulates its h2d sub-spans into
            # ``_h2d_pending``; draining it here keeps the two phases
            # disjoint even when one pull runs several assemblies
            # (prefetch queue fill).
            self._h2d_pending = 0.0
            t_data = time.perf_counter()
            try:
                collated = next(it)
            except StopIteration:
                break
            h2d = self._h2d_pending
            data_wait = time.perf_counter() - t_data - h2d

            # --- compute: the jitted step. Dispatch-to-return under
            # async dispatch; a sync-mode recorder blocks on the metrics
            # for true wall time (measurement mode — serialises overlap).
            t_step = time.perf_counter()
            self.state, metrics = self.step_fn(self.state, collated)
            rec = _trace.active()
            if rec is not None and rec.sync:
                jax.block_until_ready(metrics)
            compute = time.perf_counter() - t_step
            self.iteration += 1
            # Hang-watchdog heartbeat + the direct step-counter gauge
            # (ISSUE 6): the trainer's state plane has no trace event of
            # its own until the step event below — the beat and gauge
            # stay live even with tracing off. One slot store; the gauge
            # guards on the registry existing at all.
            _flight.beat(self.iteration)
            reg = _metrics.active_registry()
            if reg is not None:
                reg.gauge(
                    "train_iteration", "last completed trainer iteration"
                ).set(float(self.iteration))

            log_s = 0.0
            if self.iteration % self.log_interval == 0 or self.iteration == max_iterations:
                t_log = time.perf_counter()
                host_metrics = {
                    k: float(jax.device_get(v)) for k, v in metrics.items()
                }
                # Cross-rank aggregation so EVERY rank holds the global
                # metrics (one host collective per log point; all ranks
                # reach this branch at the same iteration). Rank-0's
                # pretty-print keeps its LOCAL values, unchanged.
                agg = self._obs_agg(host_metrics)
                self.observation = (
                    agg if agg is not None else dict(host_metrics)
                )
                dt = time.perf_counter() - t0
                rate = self.iteration / dt
                pretty = " ".join(f"{k}={v:.4f}" for k, v in host_metrics.items())
                self._log(
                    f"iter {self.iteration}/{max_iterations} {pretty} "
                    f"({rate:.1f} it/s)"
                )
                log_s = time.perf_counter() - t_log

            # Window accumulation BEFORE extensions run, so a straggler
            # monitor firing as an extension sees this step included.
            phases = {
                "data_wait": data_wait,
                "h2d": h2d,
                "compute": compute,
                "logging": log_s,
            }
            for k, v in phases.items():
                self._phase_sums[k] = self._phase_sums.get(k, 0.0) + v
            self._phase_steps += 1

            t_ext = time.perf_counter()
            for interval, ext in self._extensions:
                if self.iteration % interval == 0:
                    ext(self)
            ext_s = time.perf_counter() - t_ext
            self._phase_sums["extensions"] = (
                self._phase_sums.get("extensions", 0.0) + ext_s
            )

            if rec is not None:
                rec.event(
                    "step", iteration=self.iteration,
                    phases={k: round(v, 6)
                            for k, v in {**phases,
                                         "extensions": ext_s}.items()},
                )
        return self.state

    def consume_phase_window(self) -> dict[str, float]:
        """Mean seconds per step-timeline phase (data_wait / h2d /
        compute / logging / extensions) since the last call, then reset —
        the straggler monitor's per-window input. Local, no collective."""
        n = max(1, self._phase_steps)
        out = {k: v / n for k, v in self._phase_sums.items()}
        self._phase_sums = {}
        self._phase_steps = 0
        return out
