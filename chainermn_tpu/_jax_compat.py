"""Compatibility gates for the baked-in jax version.

The codebase targets the current jax surface (top-level
``jax.shard_map`` with the ``check_vma`` kwarg); the image may carry an
older jax (0.4.x) where ``shard_map`` lives in ``jax.experimental`` and
the kwarg is ``check_rep``. Per the no-new-deps rule the gap is gated
here, in one place: :func:`install` publishes a compatible
``jax.shard_map`` so the 25+ ``from jax import shard_map`` sites (library,
tests, examples, bench) keep one spelling whichever jax is present.

Imported for its side effect by ``chainermn_tpu/__init__.py`` (and by
``tests/conftest.py``, which imports jax before the package).
"""

from __future__ import annotations

import functools


def install() -> None:
    """Idempotently ensure ``jax.shard_map(f, mesh=..., in_specs=...,
    out_specs=..., check_vma=...)`` works on this jax."""
    import jax

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _experimental

    @functools.wraps(_experimental)
    def shard_map(f, /, *args, **kwargs):
        # Old spelling of the replication-check kwarg.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental(f, *args, **kwargs)

    jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # psum of a literal 1 constant-folds to the static axis size
            # (and raises the same NameError on an unbound axis that the
            # real ``lax.axis_size`` does — ``axes_bound`` relies on it).
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size


def pallas_paged_decode_supported() -> bool:
    """True when this jax's Pallas carries scalar-prefetch grid specs
    (``pltpu.PrefetchScalarGridSpec`` — the fused paged-decode kernel's
    table-indexed gather rides them, :mod:`chainermn_tpu.ops.
    paged_decode`). The serving engine consults this before cloning a
    ``decode_attend_impl='fused'`` model and falls back to the XLA
    attend with provenance ``forced:jax-compat`` when absent — the same
    one-place gating the shard_map shim above applies to the no-new-deps
    rule."""
    try:
        from chainermn_tpu.ops.paged_decode import fused_supported
    except Exception:
        return False
    try:
        return bool(fused_supported())
    except Exception:
        return False


install()
