"""Communicator factory.

Reference: ``chainermn/communicators/__init__.py`` (dagger)
``create_communicator(name, mpi_comm, allreduce_grad_dtype)`` with the
string registry ``'naive' | 'flat' | 'hierarchical' | 'two_dimensional' |
'single_node' | 'non_cuda_aware' | 'pure_nccl'`` (SURVEY.md section 2.1).

All historical names resolve to TPU-native communicators; names that only
differed in GPU transport details (flat buffers, CUDA-awareness) are aliases,
since XLA owns those concerns on TPU. The new primary name is ``'xla'``
(BASELINE.json north star).
"""

from __future__ import annotations

from chainermn_tpu.communicators.base import ANY_SOURCE, CommunicatorBase
from chainermn_tpu.communicators.xla_communicator import (
    HierarchicalCommunicator,
    NaiveCommunicator,
    SingleNodeCommunicator,
    TwoDimensionalCommunicator,
    XlaCommunicator,
)

_REGISTRY = {
    # TPU-native primary
    "xla": XlaCommunicator,
    # reference-parity names
    "naive": NaiveCommunicator,
    "flat": XlaCommunicator,            # flat fused buffer == what XLA emits
    "pure_nccl": XlaCommunicator,       # all-ranks single collective == psum
    "hierarchical": HierarchicalCommunicator,
    # explicit intra-RS -> inter-AR -> intra-AG pipeline (reference algo)
    "two_dimensional": TwoDimensionalCommunicator,
    "non_cuda_aware": HierarchicalCommunicator,   # host staging is moot on TPU
    "single_node": SingleNodeCommunicator,
}


def create_communicator(
    communicator_name: str = "xla", **kwargs
) -> CommunicatorBase:
    """Create a communicator by registry name.

    Args:
      communicator_name: one of ``xla, naive, flat, hierarchical,
        two_dimensional, single_node, non_cuda_aware, pure_nccl``.
      **kwargs: ``mesh=`` (pre-built :class:`jax.sharding.Mesh`),
        ``devices=``, ``axis_name=``, and ``allreduce_grad_dtype=``
        (e.g. ``'bfloat16'`` — the TPU analog of the reference's fp16
        compressed allreduce).
    """
    try:
        cls = _REGISTRY[communicator_name]
    except KeyError:
        raise ValueError(
            f"unknown communicator {communicator_name!r}; "
            f"available: {sorted(_REGISTRY)}"
        ) from None
    return cls(**kwargs)


__all__ = [
    "create_communicator",
    "ANY_SOURCE",
    "CommunicatorBase",
    "XlaCommunicator",
    "NaiveCommunicator",
    "HierarchicalCommunicator",
    "TwoDimensionalCommunicator",
    "SingleNodeCommunicator",
]
