"""Concrete communicators — the TPU-native counterparts of the reference's
communicator zoo (``chainermn/communicators/*.py`` (dagger), SURVEY.md
section 2.1).

On GPU the zoo existed because the composition of transports (NCCL vs MPI,
CUDA-aware or not, intra- vs inter-node) was the user's problem. On TPU, XLA
owns transport selection: every communicator here lowers to the same XLA
collectives, and the subclasses differ only in *mesh topology* (flat vs
hierarchical factorisation) and device selection. The historical names are
kept as registry aliases so reference users find what they expect
(``create_communicator('pure_nccl')`` still works and does the right thing).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.parallel.mesh import make_mesh

PyTree = Any


class XlaCommunicator(CommunicatorBase):
    """The production communicator: one flat ``('data',)`` axis over every
    device in the pod slice; gradient allreduce lowers to a single
    ``lax.psum`` over ICI (+DCN when multi-slice). Plays the role of
    ``PureNcclCommunicator`` (``pure_nccl_communicator.py`` (dagger)) — the
    communicator the reference's benchmarks name."""

    name = "xla"

    def __init__(
        self,
        *,
        mesh: Mesh | None = None,
        devices: Sequence[jax.Device] | None = None,
        axis_name: str = "data",
        allreduce_grad_dtype=None,
    ) -> None:
        if mesh is None:
            mesh = make_mesh((axis_name,), devices=devices)
        super().__init__(mesh, allreduce_grad_dtype=allreduce_grad_dtype)


class NaiveCommunicator(XlaCommunicator):
    """CPU-mesh communicator for tests/CI — the role of the reference's
    ``NaiveCommunicator`` (``naive_communicator.py`` (dagger)): works with no
    accelerator at all. Uses the host-platform XLA backend, which honours
    ``--xla_force_host_platform_device_count`` for multi-"rank" testing
    (SURVEY.md section 4)."""

    name = "naive"

    def __init__(self, **kwargs) -> None:
        if kwargs.get("mesh") is None and kwargs.get("devices") is None:
            self._pin_cpu_platform_if_uninitialized()
            kwargs["devices"] = jax.devices("cpu")
        super().__init__(**kwargs)

    @staticmethod
    def _pin_cpu_platform_if_uninitialized() -> None:
        """Pin jax to the CPU platform before first backend init.

        ``jax.devices('cpu')`` initialises EVERY registered backend, and an
        externally injected accelerator plugin whose transport is dead can
        hang that discovery forever (observed live: a wedged tunnelled TPU
        plugin froze every example run). The naive communicator is
        hermetic-CPU *by contract*, so creating one FIRST in a fresh
        process deliberately OVERRIDES any pre-set platform list
        (environment-injected plugin shims set ``JAX_PLATFORMS``
        themselves, so a pre-set value does not imply user intent). The
        pin is process-wide: mixing a first ``naive`` communicator with a
        later accelerator communicator in one process requires opting out
        via ``CHAINERMN_TPU_NAIVE_NO_PIN=1``. No-op once any backend is
        live (then discovery already succeeded)."""
        import os
        import warnings

        if os.environ.get("CHAINERMN_TPU_NAIVE_NO_PIN"):
            return
        try:
            from jax._src import xla_bridge as xb

            if xb._backends:  # discovery already done and healthy
                return
            preset = os.environ.get("JAX_PLATFORMS")
            if preset and preset != "cpu":
                # The pre-set value may be the user's or an injected plugin
                # shim's — either way, a later accelerator communicator in
                # this process will find no devices unless the pin is
                # opted out of. Say so instead of failing silently there.
                warnings.warn(
                    f"NaiveCommunicator is pinning JAX_PLATFORMS=cpu for "
                    f"this process, overriding the pre-set "
                    f"JAX_PLATFORMS={preset!r}. If you need an accelerator "
                    f"communicator in the same process, set "
                    f"CHAINERMN_TPU_NAIVE_NO_PIN=1 before creating the "
                    f"naive communicator.",
                    stacklevel=3,
                )
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # best-effort: fall through to normal discovery


class HierarchicalCommunicator(CommunicatorBase):
    """Two-level ``('inter', 'intra')`` mesh: ``inter`` spans processes
    (DCN), ``intra`` spans each process's local devices (ICI). Gradient
    reduction over both axes reproduces — declaratively — the reference's
    intra-node-NCCL-then-inter-node-MPI pipeline
    (``hierarchical_communicator.py`` (dagger),
    ``two_dimensional_communicator.py`` (dagger)): XLA emits the
    topology-aware 2-level collective itself."""

    name = "hierarchical"

    def __init__(
        self,
        *,
        mesh: Mesh | None = None,
        devices: Sequence[jax.Device] | None = None,
        allreduce_grad_dtype=None,
    ) -> None:
        if mesh is None:
            if devices is None:
                devices = jax.devices()
            devices = list(devices)
            n_proc = jax.process_count()
            per_proc = len(devices) // max(n_proc, 1)
            if n_proc > 1 and per_proc * n_proc == len(devices):
                ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
                arr = np.array(ordered).reshape(n_proc, per_proc)
            else:
                # Single process: degenerate inter axis (the same degeneracy
                # the reference's single-host MPI tests exercised —
                # ``inter_size == 1``, SURVEY.md section 4).
                arr = np.array(devices).reshape(1, len(devices))
            mesh = Mesh(arr, ("inter", "intra"))
        super().__init__(mesh, allreduce_grad_dtype=allreduce_grad_dtype)

    @property
    def axis_name(self) -> str:  # primary axis for data parallelism
        return "inter"


class TwoDimensionalCommunicator(HierarchicalCommunicator):
    """Hierarchical mesh with the EXPLICIT bandwidth-optimal reduction: the
    gradient pipeline is intra ``psum_scatter`` → inter allreduce of the
    1/n shard → intra ``all_gather``, pinned in the program rather than
    left to XLA's schedule derivation — the reference's
    ``TwoDimensionalCommunicator`` algorithm
    (``two_dimensional_communicator.py`` (dagger): intra
    ``ncclReduceScatter`` → inter MPI allreduce → intra ``ncclAllGather``).
    Numerically identical to the hierarchical pmean (tested)."""

    name = "two_dimensional"

    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        if len(self.grad_axes) != 2:
            raise ValueError(
                "two_dimensional requires a 2-axis (inter, intra) mesh; "
                f"got grad_axes={self.grad_axes!r} from mesh axes "
                f"{tuple(self.mesh.axis_names)!r}"
            )

    @functools.cached_property
    def bucket_bytes(self) -> int:
        """Gradient-pack bucket size (autotuned, resolved once per
        communicator so the pipeline's layout is stable for the
        process lifetime). The resolution's provenance is kept for the
        observability layer's pack events."""
        from chainermn_tpu.communicators.base import _latest_decision
        from chainermn_tpu.parallel.collectives import tuned_bucket_bytes

        out = tuned_bucket_bytes(self.device_kind, self.size)
        self._bucket_provenance = _latest_decision("allreduce_bucket_mb")
        return out

    @property
    def two_level_axes(self):
        """``(intra_axis, inter_axis)`` names of the pinned two-level
        reduction — the capability flag the shard-level EF path keys on
        (``MultiNodeOptimizer._reduce_with_feedback``): quantization
        happens only at the inter stage here, so the EF residual is
        kept at shard shape and fed back exactly where the error
        arises."""
        inter_ax, intra_ax = self.grad_axes
        return intra_ax, inter_ax

    def reduce_gradients_in_jit(
        self, grads: PyTree, *, compress_dtype=None
    ) -> PyTree:
        """The pinned two-level pipeline, via the SHARED schedule layer
        (:func:`chainermn_tpu.parallel.reduction_schedule.reduce_tree`,
        ``schedule='two_level'``): the whole gradient tree packs into
        ~``bucket_bytes`` flat buffers per dtype group (the reference's
        ``_memory_utility.pack_params`` (dagger) discipline, in-jit so
        XLA owns the copies — per-leaf collectives would leave the slow
        inter/DCN level latency-bound on tiny bias/scale leaves), and
        each bucket crosses as intra ``psum_scatter`` -> inter allreduce
        of the shard -> intra ``all_gather``. An int8 compress dtype
        selects the quantized wire at the ONLY stage where compression
        pays — the shard crossing inter/DCN — with the intra reduction
        exact. Trace-time ``pack`` + per-bucket ``wire`` events record
        the layout and the bucket decision's provenance."""
        from chainermn_tpu.parallel.collectives import axes_bound
        from chainermn_tpu.parallel.reduction_schedule import reduce_tree

        if compress_dtype is None:
            compress_dtype = self.allreduce_grad_dtype
        # Probe ONLY the axis-context question (unbound axis = auto-SPMD
        # jit / single-device eager) — a genuine error inside the
        # pipeline must propagate, not silently degrade to the fused
        # pmean fallback (numerically identical, nothing would notice).
        inter_ax, intra_ax = self.grad_axes
        if not axes_bound((intra_ax, inter_ax)):
            return super().reduce_gradients_in_jit(
                grads, compress_dtype=compress_dtype
            )
        bucket_bytes = self.bucket_bytes  # resolves provenance too
        return reduce_tree(
            grads,
            schedule="two_level",
            axes=self.grad_axes,
            compress_dtype=compress_dtype,
            bucket_bytes=bucket_bytes,
            provenance=getattr(self, "_bucket_provenance", None),
            op="two_level_allreduce",
            size=self.size,
        )


class SingleNodeCommunicator(XlaCommunicator):
    """Asserts a single process — reference ``single_node_communicator.py``
    (dagger) asserted ``inter_size == 1`` (NCCL-only, one node)."""

    name = "single_node"

    def __init__(self, **kwargs) -> None:
        if jax.process_count() != 1:
            raise ValueError(
                "SingleNodeCommunicator requires a single-process runtime "
                "(reference parity: inter_size == 1)"
            )
        super().__init__(**kwargs)
