"""Concrete communicators — the TPU-native counterparts of the reference's
communicator zoo (``chainermn/communicators/*.py`` (dagger), SURVEY.md
section 2.1).

On GPU the zoo existed because the composition of transports (NCCL vs MPI,
CUDA-aware or not, intra- vs inter-node) was the user's problem. On TPU, XLA
owns transport selection: every communicator here lowers to the same XLA
collectives, and the subclasses differ only in *mesh topology* (flat vs
hierarchical factorisation) and device selection. The historical names are
kept as registry aliases so reference users find what they expect
(``create_communicator('pure_nccl')`` still works and does the right thing).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.parallel.mesh import make_mesh

PyTree = Any


class XlaCommunicator(CommunicatorBase):
    """The production communicator: one flat ``('data',)`` axis over every
    device in the pod slice; gradient allreduce lowers to a single
    ``lax.psum`` over ICI (+DCN when multi-slice). Plays the role of
    ``PureNcclCommunicator`` (``pure_nccl_communicator.py`` (dagger)) — the
    communicator the reference's benchmarks name."""

    name = "xla"

    def __init__(
        self,
        *,
        mesh: Mesh | None = None,
        devices: Sequence[jax.Device] | None = None,
        axis_name: str = "data",
        allreduce_grad_dtype=None,
    ) -> None:
        if mesh is None:
            mesh = make_mesh((axis_name,), devices=devices)
        super().__init__(mesh, allreduce_grad_dtype=allreduce_grad_dtype)


class NaiveCommunicator(XlaCommunicator):
    """CPU-mesh communicator for tests/CI — the role of the reference's
    ``NaiveCommunicator`` (``naive_communicator.py`` (dagger)): works with no
    accelerator at all. Uses the host-platform XLA backend, which honours
    ``--xla_force_host_platform_device_count`` for multi-"rank" testing
    (SURVEY.md section 4)."""

    name = "naive"

    def __init__(self, **kwargs) -> None:
        if kwargs.get("mesh") is None and kwargs.get("devices") is None:
            self._pin_cpu_platform_if_uninitialized()
            kwargs["devices"] = jax.devices("cpu")
        super().__init__(**kwargs)

    @staticmethod
    def _pin_cpu_platform_if_uninitialized() -> None:
        """Pin jax to the CPU platform before first backend init.

        ``jax.devices('cpu')`` initialises EVERY registered backend, and an
        externally injected accelerator plugin whose transport is dead can
        hang that discovery forever (observed live: a wedged tunnelled TPU
        plugin froze every example run). The naive communicator is
        hermetic-CPU *by contract*, so creating one FIRST in a fresh
        process deliberately OVERRIDES any pre-set platform list
        (environment-injected plugin shims set ``JAX_PLATFORMS``
        themselves, so a pre-set value does not imply user intent). The
        pin is process-wide: mixing a first ``naive`` communicator with a
        later accelerator communicator in one process requires opting out
        via ``CHAINERMN_TPU_NAIVE_NO_PIN=1``. No-op once any backend is
        live (then discovery already succeeded)."""
        import os
        import warnings

        if os.environ.get("CHAINERMN_TPU_NAIVE_NO_PIN"):
            return
        try:
            from jax._src import xla_bridge as xb

            if xb._backends:  # discovery already done and healthy
                return
            preset = os.environ.get("JAX_PLATFORMS")
            if preset and preset != "cpu":
                # The pre-set value may be the user's or an injected plugin
                # shim's — either way, a later accelerator communicator in
                # this process will find no devices unless the pin is
                # opted out of. Say so instead of failing silently there.
                warnings.warn(
                    f"NaiveCommunicator is pinning JAX_PLATFORMS=cpu for "
                    f"this process, overriding the pre-set "
                    f"JAX_PLATFORMS={preset!r}. If you need an accelerator "
                    f"communicator in the same process, set "
                    f"CHAINERMN_TPU_NAIVE_NO_PIN=1 before creating the "
                    f"naive communicator.",
                    stacklevel=3,
                )
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # best-effort: fall through to normal discovery


class HierarchicalCommunicator(CommunicatorBase):
    """Two-level ``('inter', 'intra')`` mesh: ``inter`` spans processes
    (DCN), ``intra`` spans each process's local devices (ICI). Gradient
    reduction over both axes reproduces — declaratively — the reference's
    intra-node-NCCL-then-inter-node-MPI pipeline
    (``hierarchical_communicator.py`` (dagger),
    ``two_dimensional_communicator.py`` (dagger)): XLA emits the
    topology-aware 2-level collective itself."""

    name = "hierarchical"

    def __init__(
        self,
        *,
        mesh: Mesh | None = None,
        devices: Sequence[jax.Device] | None = None,
        allreduce_grad_dtype=None,
    ) -> None:
        if mesh is None:
            if devices is None:
                devices = jax.devices()
            devices = list(devices)
            n_proc = jax.process_count()
            per_proc = len(devices) // max(n_proc, 1)
            if n_proc > 1 and per_proc * n_proc == len(devices):
                ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
                arr = np.array(ordered).reshape(n_proc, per_proc)
            else:
                # Single process: degenerate inter axis (the same degeneracy
                # the reference's single-host MPI tests exercised —
                # ``inter_size == 1``, SURVEY.md section 4).
                arr = np.array(devices).reshape(1, len(devices))
            mesh = Mesh(arr, ("inter", "intra"))
        super().__init__(mesh, allreduce_grad_dtype=allreduce_grad_dtype)

    @property
    def axis_name(self) -> str:  # primary axis for data parallelism
        return "inter"


class TwoDimensionalCommunicator(HierarchicalCommunicator):
    """Hierarchical mesh with the EXPLICIT bandwidth-optimal reduction: the
    gradient pipeline is intra ``psum_scatter`` → inter allreduce of the
    1/n shard → intra ``all_gather``, pinned in the program rather than
    left to XLA's schedule derivation — the reference's
    ``TwoDimensionalCommunicator`` algorithm
    (``two_dimensional_communicator.py`` (dagger): intra
    ``ncclReduceScatter`` → inter MPI allreduce → intra ``ncclAllGather``).
    Numerically identical to the hierarchical pmean (tested)."""

    name = "two_dimensional"

    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        if len(self.grad_axes) != 2:
            raise ValueError(
                "two_dimensional requires a 2-axis (inter, intra) mesh; "
                f"got grad_axes={self.grad_axes!r} from mesh axes "
                f"{tuple(self.mesh.axis_names)!r}"
            )

    @functools.cached_property
    def bucket_bytes(self) -> int:
        """Gradient-pack bucket size (autotuned, resolved once per
        communicator so the pipeline's layout is stable for the
        process lifetime). The resolution's provenance is kept for the
        observability layer's pack events."""
        from chainermn_tpu.communicators.base import _latest_decision
        from chainermn_tpu.parallel.collectives import tuned_bucket_bytes

        out = tuned_bucket_bytes(self.device_kind, self.size)
        self._bucket_provenance = _latest_decision("allreduce_bucket_mb")
        return out

    @property
    def two_level_axes(self):
        """``(intra_axis, inter_axis)`` names of the pinned two-level
        reduction — the capability flag the shard-level EF path keys on
        (``MultiNodeOptimizer._reduce_with_feedback``): quantization
        happens only at the inter stage here, so the EF residual is
        kept at shard shape and fed back exactly where the error
        arises."""
        inter_ax, intra_ax = self.grad_axes
        return intra_ax, inter_ax

    def reduce_gradients_in_jit(
        self, grads: PyTree, *, compress_dtype=None
    ) -> PyTree:
        import jax.numpy as jnp

        from chainermn_tpu.parallel.collectives import two_level_allreduce

        if compress_dtype is None:
            compress_dtype = self.allreduce_grad_dtype
        # int8 selects the quantized wire (summing int8 through the
        # two-level pipeline would overflow): float buckets PACK in f32
        # and reduce via int8_two_level_allreduce_mean — exact over
        # intra, int8 only over inter — keeping the flat-buffer
        # discipline, so tiny bias/scale leaves still ride one
        # collective per ~64 MB bucket instead of one per leaf.
        int8_wire = (compress_dtype is not None
                     and jnp.dtype(compress_dtype) == jnp.dtype(jnp.int8))
        # Axes come from the mesh (a custom mesh= names them differently).
        inter_ax, intra_ax = self.grad_axes

        # Probe ONLY the axis-context question (unbound axis = auto-SPMD
        # jit / single-device eager), then run the real reduction outside
        # any try — a genuine error inside two_level_allreduce must
        # propagate, not silently degrade to the fused-pmean fallback
        # (which is numerically identical, so nothing would ever notice).
        from chainermn_tpu.parallel.collectives import axes_bound

        if not axes_bound((intra_ax, inter_ax)):
            return super().reduce_gradients_in_jit(
                grads, compress_dtype=compress_dtype
            )

        # Pack the whole gradient tree into one flat buffer per dtype group
        # before reducing — the reference's ``_memory_utility.pack_params``
        # flat-buffer discipline (dagger), here inside jit so XLA owns the
        # copies. Per-leaf collectives would issue 3 ops per parameter
        # tensor, leaving the slow inter (DCN) level latency-bound on tiny
        # bias/scale leaves instead of bandwidth-bound on one big buffer.
        leaves, treedef = jax.tree.flatten(grads)
        if not leaves:
            return grads

        def cast_dtype(g):
            if compress_dtype is not None and jnp.issubdtype(
                g.dtype, jnp.floating
            ):
                # int8 wire: buckets pack in f32; quantization happens
                # inside int8_two_level_allreduce_mean per bucket.
                return (jnp.dtype(jnp.float32) if int8_wire
                        else jnp.dtype(compress_dtype))
            return jnp.dtype(g.dtype)

        groups: dict = {}
        for i, g in enumerate(leaves):
            groups.setdefault(cast_dtype(g), []).append(i)
        out: list = [None] * len(leaves)
        # Pack into buckets rather than one whole-model buffer: the
        # concatenated flat copy is a TRANSIENT extra full gradient in HBM;
        # bucketing bounds that transient while each bucket stays large
        # enough to keep the inter (DCN) level bandwidth-bound. (A single
        # leaf bigger than the bucket gets its own bucket, unsplit.)
        # Size via the autotune registry (~64 MB table default; a cache
        # entry seeded from an on-chip busbw curve can move it — see
        # chainermn_tpu.tuning).
        bucket_bytes = self.bucket_bytes
        n_buckets_total = 0
        for dt, idxs in groups.items():
            itemsize = jnp.dtype(dt).itemsize
            buckets: list[list[int]] = []
            cur: list[int] = []
            cur_bytes = 0
            for i in idxs:
                nbytes = leaves[i].size * itemsize
                if cur and cur_bytes + nbytes > bucket_bytes:
                    buckets.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(i)
                cur_bytes += nbytes
            if cur:
                buckets.append(cur)
            n_buckets_total += len(buckets)
            for bidx in buckets:
                flat = jnp.concatenate(
                    [leaves[i].astype(dt).ravel() for i in bidx]
                )
                if int8_wire and jnp.issubdtype(dt, jnp.floating):
                    # Topology-aware: exact over intra (ICI), the int8
                    # wire's two rounding stages only over inter (DCN)
                    # — compression where bandwidth is scarce, no
                    # quantization noise from the intra reduction.
                    from chainermn_tpu.parallel.collectives import (
                        int8_two_level_allreduce_mean,
                    )

                    red = int8_two_level_allreduce_mean(
                        flat, intra_ax, inter_ax
                    )
                else:
                    red = two_level_allreduce(flat, intra_ax, inter_ax)
                off = 0
                for i in bidx:
                    n = leaves[i].size
                    out[i] = (
                        red[off : off + n]
                        .reshape(leaves[i].shape)
                        .astype(leaves[i].dtype)
                    )
                    off += n
        # Pack provenance into the trace (fires at TRACE time — once per
        # compilation, pure host-side Python, so the lowered program is
        # untouched): the bucket layout this program committed to and
        # the autotune decision behind it.
        from chainermn_tpu.observability import trace as _trace

        rec = _trace.active()
        if rec is not None:
            def wire_itemsize(g):
                # int8 wire: float buckets PACK in f32 but cross the
                # inter wire as 1 byte/elem — nbytes must describe the
                # wire the wire_dtype names, not the pack staging dtype
                # (a 4x overstatement otherwise).
                if int8_wire and jnp.issubdtype(g.dtype, jnp.floating):
                    return 1
                return jnp.dtype(cast_dtype(g)).itemsize

            rec.event(
                "pack", op="two_level_allreduce",
                nbytes=sum(g.size * wire_itemsize(g) for g in leaves),
                bucket_bytes=bucket_bytes,
                n_buckets=n_buckets_total,
                wire_dtype=("int8" if int8_wire else
                            (jnp.dtype(compress_dtype).name
                             if compress_dtype is not None else "none")),
                provenance=getattr(self, "_bucket_provenance", None),
                size=self.size,
            )
        return jax.tree.unflatten(treedef, out)


class SingleNodeCommunicator(XlaCommunicator):
    """Asserts a single process — reference ``single_node_communicator.py``
    (dagger) asserted ``inter_size == 1`` (NCCL-only, one node)."""

    name = "single_node"

    def __init__(self, **kwargs) -> None:
        if jax.process_count() != 1:
            raise ValueError(
                "SingleNodeCommunicator requires a single-process runtime "
                "(reference parity: inter_size == 1)"
            )
        super().__init__(**kwargs)
