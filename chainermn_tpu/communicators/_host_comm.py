"""Host-plane (process-level) object collectives.

TPU-native replacement for the reference's pickled-object MPI collectives
(``MpiCommunicatorBase.send_obj/recv_obj/bcast_obj/gather_obj/allreduce_obj``
in ``mpi_communicator_base.py`` (dagger), SURVEY.md section 2.1). There, every
communicator inherited object transport from mpi4py. On TPU there is no MPI:
object collectives ride DCN through ``jax.experimental.multihost_utils``
(which rendezvouses through the JAX distributed runtime), with objects
pickled into padded uint8 arrays (the reference pickled into MPI byte
messages with a ``_MessageType`` header; same idea, different transport).

A native C++ TCP backend (chainermn_tpu/native) can replace this transport
for point-to-point sends; the collective API stays identical.

Single-process (the common TPU-slice-per-process and all test cases) is a
fast path with no communication at all.
"""

from __future__ import annotations

import functools
import os
import pickle
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np

from chainermn_tpu.observability import flight as _flight
from chainermn_tpu.observability import trace as _trace


def _traced_obj(op: str, payload: str | None = "arg"):
    """Wire-counter instrumentation for the obj-plane collectives: when
    tracing is active, record op, pickled payload bytes, and the TRUE
    blocking duration (host-plane calls complete synchronously — no
    async-dispatch caveat here). ``payload``: ``"arg"`` measures the
    first positional argument, ``"result"`` the return value (receives),
    ``None`` skips bytes (barrier). Disabled cost: one global read plus
    the flight recorder's in-flight marker (ISSUE 6) — these BLOCKING
    host collectives are exactly where a distributed hang parks (one
    rank in a barrier whose peers never arrive), so the marker is
    unconditional: the hang dump then names the op a wedged process was
    inside, tracing on or off."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            token = _flight.collective_entered(
                op, plane="host", size=self.size
            )
            try:
                rec = _trace.active()
                if rec is None:
                    return fn(self, *args, **kwargs)
                t0 = time.perf_counter()
                out = fn(self, *args, **kwargs)
                obj = (args[0] if args else None) if payload == "arg" else (
                    out if payload == "result" else None
                )
                rec.collective(
                    op, plane="host",
                    nbytes=(_trace.obj_nbytes(obj) if payload else None),
                    dur_s=time.perf_counter() - t0, size=self.size,
                )
                return out
            finally:
                _flight.collective_exited(token)

        return wrapper

    return deco


def _is_multiprocess() -> bool:
    return jax.process_count() > 1


def _jax_distributed_world():
    """``(process_id, num_processes)`` of the JAX distributed runtime, or
    ``(None, None)`` when the distributed client isn't initialised — read
    from ``jax._src.distributed`` state so asking never triggers XLA
    backend discovery."""
    try:
        from jax._src import distributed

        state = distributed.global_state
        if state.client is None:
            return None, None
        return state.process_id, state.num_processes
    except Exception:
        return None, None


def _obj_to_padded(obj: Any, pad_to: int | None = None) -> np.ndarray:
    """Pickle ``obj`` into a uint8 vector ``[8-byte length | payload | pad]``.

    The length header plays the role of the reference's ``_MessageType``
    preamble (shape/dtype descriptor sent via ``send_obj`` before the
    payload, ``mpi_communicator_base.py`` (dagger)).
    """
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    header = np.frombuffer(np.uint64(payload.size).tobytes(), dtype=np.uint8)
    buf = np.concatenate([header, payload])
    if pad_to is not None:
        if pad_to < buf.size:
            raise ValueError("pad_to smaller than pickled object")
        buf = np.pad(buf, (0, pad_to - buf.size))
    return buf


def _padded_to_obj(buf: np.ndarray) -> Any:
    buf = np.asarray(buf)
    if buf.dtype != np.uint8:
        # Older jax host collectives (0.4.x gloo) upcast uint8 payloads
        # to int32: the VALUES survive but ``bytes()`` would widen each
        # to 4 bytes and the pickle stream would read as garbage.
        buf = buf.astype(np.uint8)
    size = int(np.frombuffer(bytes(buf[:8]), dtype=np.uint64)[0])
    return pickle.loads(bytes(buf[8 : 8 + size]))


class HostComm:
    """Process-level collectives. ``rank``/``size`` are process index/count —
    the host-plane analog of the reference's MPI world.

    Transport selection (the reference selected MPI flavors per
    communicator; here it is per host-plane):
      - ``CHAINERMN_TPU_{RANK,SIZE,COORD}`` set → the native C++ TCP mesh
        (:mod:`chainermn_tpu.native.tcp_comm`), which also enables true
        point-to-point ``send_obj``/``recv_obj``;
      - otherwise multi-process JAX → ``multihost_utils`` over DCN;
      - single process → no-op fast paths.
    """

    def __init__(self) -> None:
        env_keys = (
            "CHAINERMN_TPU_RANK",
            "CHAINERMN_TPU_SIZE",
            "CHAINERMN_TPU_COORD",
        )
        set_keys = [k for k in env_keys if os.environ.get(k)]
        if set_keys and len(set_keys) < len(env_keys):
            # A partial set is a launcher bug, not a fallback condition.
            raise RuntimeError(
                f"native TCP backend partially configured: {set_keys} set "
                f"but {sorted(set(env_keys) - set(set_keys))} missing"
            )
        if set_keys:
            # The operator explicitly asked for the native TCP backend:
            # bootstrap failure must PROPAGATE. A silent fallback would make
            # every process rank 0 / size 1 and scatter/checkpoint agreement
            # would diverge instead of erroring.
            from chainermn_tpu.native.tcp_comm import TcpHostComm

            self.tcp = TcpHostComm.from_env()
        else:
            self.tcp = None
        if self.tcp is not None:
            self.rank = self.tcp.rank
            self.size = self.tcp.size
            # Rooted object collectives translate mesh-slot roots through
            # jax process indices; a launcher that numbers the TCP world
            # differently would silently target the wrong process. Checked
            # WITHOUT touching jax backend init (this path must stay usable
            # before/without jax — distributed.global_state is populated by
            # jax.distributed.initialize, not by backend discovery).
            jax_pid, jax_nproc = _jax_distributed_world()
            if jax_pid is not None and (
                self.rank != jax_pid or self.size != jax_nproc
            ):
                raise RuntimeError(
                    f"native TCP world (rank {self.rank}/{self.size}) "
                    f"disagrees with the JAX distributed world (process "
                    f"{jax_pid}/{jax_nproc}); the TCP host plane requires "
                    "identical numbering and size"
                )
        else:
            self.rank = jax.process_index()
            self.size = jax.process_count()

    # -- point-to-point (native transport only) ----------------------------

    @_traced_obj("send_obj")
    def send_obj(self, obj: Any, dest: int) -> None:
        if self.tcp is None:
            raise NotImplementedError(
                "point-to-point host sends need the native TCP backend: set "
                "CHAINERMN_TPU_RANK/SIZE/COORD (see chainermn_tpu.native)"
            )
        self.tcp.send_obj(obj, dest)

    @_traced_obj("recv_obj", payload="result")
    def recv_obj(self, source: int) -> Any:
        if self.tcp is None:
            raise NotImplementedError(
                "point-to-point host recvs need the native TCP backend: set "
                "CHAINERMN_TPU_RANK/SIZE/COORD (see chainermn_tpu.native)"
            )
        return self.tcp.recv_obj(source)

    def probe(self, source: int) -> bool:
        """Non-blocking check for a pending message from ``source``
        (MPI_Iprobe parity — the reference's eager transport offered
        probing via mpi4py)."""
        if self.tcp is None:
            raise NotImplementedError(
                "probe needs the native TCP backend: set "
                "CHAINERMN_TPU_RANK/SIZE/COORD (see chainermn_tpu.native)"
            )
        return self.tcp.probe(source)

    # -- collectives -------------------------------------------------------

    @_traced_obj("barrier", payload=None)
    def barrier(self, tag: str = "barrier") -> None:
        if self.tcp is not None:
            return self.tcp.barrier()
        if not _is_multiprocess():
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)

    @_traced_obj("bcast_obj", payload="result")
    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        # payload="result": the usual call shape is ``bcast_obj(obj if
        # rank == 0 else None)`` — measuring the argument would record a
        # few pickled-None bytes on every non-root rank; the RETURN is
        # the broadcast payload on all ranks.
        if self.tcp is not None:
            return self.tcp.bcast_obj(obj, root)
        if not _is_multiprocess():
            return obj
        from jax.experimental import multihost_utils

        # Round 1: agree on buffer size (max over processes).
        local = _obj_to_padded(obj) if self.rank == root else np.zeros(8, np.uint8)
        sizes = multihost_utils.process_allgather(np.int64(local.size))
        pad = int(np.max(sizes))
        buf = _obj_to_padded(obj, pad) if self.rank == root else np.zeros(pad, np.uint8)
        out = multihost_utils.broadcast_one_to_all(buf, is_source=(self.rank == root))
        return _padded_to_obj(np.asarray(out))

    @_traced_obj("allgather_obj")
    def allgather_obj(self, obj: Any) -> list[Any]:
        if self.tcp is not None:
            return self.tcp.allgather_obj(obj)
        if not _is_multiprocess():
            return [obj]
        from jax.experimental import multihost_utils

        local = _obj_to_padded(obj)
        sizes = multihost_utils.process_allgather(np.int64(local.size))
        pad = int(np.max(sizes))
        stacked = multihost_utils.process_allgather(_obj_to_padded(obj, pad))
        return [_padded_to_obj(np.asarray(row)) for row in stacked]

    def gather_obj(self, obj: Any, root: int = 0) -> list[Any] | None:
        """Gather to ``root``; non-root processes get ``None`` (MPI parity)."""
        everyone = self.allgather_obj(obj)
        return everyone if self.rank == root else None

    @_traced_obj("scatter_obj", payload="result")
    def scatter_obj(self, objs: Sequence[Any] | None, root: int = 0) -> Any:
        if self.tcp is not None:
            return self.tcp.scatter_obj(objs, root)
        if not _is_multiprocess():
            assert objs is not None
            return objs[0]
        objs = self.bcast_obj(objs, root)
        return objs[self.rank]

    def allreduce_obj(self, obj: Any, op: Callable[[Any, Any], Any] | None = None) -> Any:
        """Reduce python objects across processes.

        Default op mirrors the reference's multi-node evaluator usage
        (``chainermn/evaluators.py`` (dagger)): element-wise sum of numeric
        values / dicts of numerics.
        """
        items = self.allgather_obj(obj)
        if op is None:
            op = _default_sum
        out = items[0]
        for it in items[1:]:
            out = op(out, it)
        return out

    # -- subgroups (reference: MPI_Comm_split) -----------------------------

    @property
    def world_members(self) -> list[int]:
        """World process indices backing this comm's ranks, in rank order.
        Identity for the world comm; group-ordered subset after :meth:`split`."""
        return getattr(self, "_world_members", None) or list(range(self.size))

    def split(self, color: int, key: int = 0) -> "HostComm":
        """Partition processes by ``color`` into independent sub-host-planes.

        Requires the native TCP backend: ``multihost_utils`` collectives are
        *globally* collective (every process of the JAX world must call
        them), so two color groups issuing independent operations through it
        would deadlock — the per-pair TCP channels have no such coupling.
        """
        if self.size == 1:
            return self
        if self.tcp is None:
            raise NotImplementedError(
                "multihost split() requires the native TCP host backend "
                "(set CHAINERMN_TPU_RANK/SIZE/COORD): multihost_utils "
                "collectives are global and cannot serve independent groups"
            )
        group = self.tcp.split(color, key)
        sub = HostComm.__new__(HostComm)
        sub.tcp = group
        sub.rank = group.rank
        sub.size = group.size
        parents = self.world_members
        sub._world_members = [parents[m] for m in group.members]
        return sub


def _default_sum(a: Any, b: Any) -> Any:
    if isinstance(a, dict):
        return {k: _default_sum(a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        return type(a)(_default_sum(x, y) for x, y in zip(a, b))
    return a + b
