"""``CommunicatorBase`` — the heart of the framework.

Mirrors the reference's ``chainermn/communicators/communicator_base.py``
(dagger) API surface (SURVEY.md section 2.1): ``rank / size / intra_rank /
inter_rank / inter_size``, array collectives, ``*_obj`` object collectives,
and the model-level ``bcast_data`` / ``allreduce_grad`` pair — but the
execution model is TPU-native SPMD:

- The *device plane* is a ``jax.sharding.Mesh``. A "rank" of the reference
  (one MPI process per GPU) corresponds to one mesh slot. Eager array
  collectives take a **stacked** array whose leading axis enumerates per-rank
  contributions (shape ``[size, ...]``), shard it over the mesh, and run one
  jitted XLA collective — semantically identical to "every rank passes its
  local array", with the stacking making the SPMD single-controller model
  explicit. Inside a jitted train step, use the named-axis forms
  (:mod:`chainermn_tpu.parallel.collectives` or ``comm.axis_name`` with
  ``jax.lax.psum``) instead; that is the hot path.

- The *host plane* is the set of JAX processes; ``*_obj`` collectives ride
  :mod:`chainermn_tpu.communicators._host_comm` (multihost_utils / native
  backend) the way the reference's rode mpi4py.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators._host_comm import HostComm
from chainermn_tpu.observability import flight as _flight
from chainermn_tpu.observability import trace as _trace
from chainermn_tpu.parallel import collectives
from chainermn_tpu.parallel.mesh import MeshTopology

PyTree = Any

#: Wildcard source for :meth:`CommunicatorBase.recv` /
#: :meth:`CommunicatorBase.recv_obj` / :meth:`CommunicatorBase.probe`
#: (reference parity: ``MPI.ANY_SOURCE``).
ANY_SOURCE = -1


def _latest_decision(name: str) -> dict | None:
    """Most recent autotune decision record for ``name`` — the tuning
    provenance a communicator attaches to the wire events of a
    configuration it resolved via ``'auto'``."""
    try:
        from chainermn_tpu import tuning

        for d in reversed(tuning.decisions_taken()):
            if d.get("name") == name:
                return d
    except Exception:
        pass
    return None


class CommunicatorBase:
    """Base communicator over a device mesh.

    Subclasses pick the mesh construction (all-devices flat, hierarchical
    (inter, intra) factorisation, CPU-only, ...) the way the reference's
    subclasses picked NCCL/MPI compositions.
    """

    #: name used by :func:`chainermn_tpu.create_communicator`
    name: str = "base"

    def __init__(
        self, mesh: Mesh, *, allreduce_grad_dtype=None, _host: HostComm | None = None
    ) -> None:
        self.mesh = mesh
        # The lazy provider keeps topology.intra_rank/intra_size truthful
        # AND mutually consistent on multi-process-per-host runtimes
        # (hostname discovery, deferred so construction stays
        # non-collective). Single-process returns None: the topology then
        # keeps its devices-per-process intra_size semantics.
        self.topology = MeshTopology(
            mesh,
            host_intra_provider=(
                lambda: self._intra if self.host.size > 1 else None
            ),
        )
        self.host = _host if _host is not None else HostComm()
        self._flat_axes = tuple(mesh.axis_names)
        self._flat_spec = P(self._flat_axes)
        #: dtype for compressed gradient allreduce
        #: (reference: ``allreduce_grad_dtype='float16'`` on
        #: ``PureNcclCommunicator`` (dagger); bf16 is the TPU-native
        #: choice). ``"auto"`` resolves the wire variant device-aware
        #: through the autotune registry (decision ``allreduce_wire``
        #: keyed on this mesh's device kind + size — table default
        #: bf16; an int8 cache entry must earn its rounding stages with
        #: a measured busbw win; see chainermn_tpu.tuning).
        #: autotune decision record behind an ``'auto'`` wire resolution
        #: (name/winner/source/key) — attached to this communicator's
        #: ``allreduce_grad`` wire events so every auto collective in a
        #: trace carries its dispatch provenance. None for explicit dtypes.
        self._wire_provenance: dict | None = None
        if isinstance(allreduce_grad_dtype, str) \
                and allreduce_grad_dtype == "auto":
            from chainermn_tpu.parallel.collectives import (
                resolve_allreduce_wire,
            )

            allreduce_grad_dtype = resolve_allreduce_wire(
                self.device_kind, self.topology.size
            )
            self._wire_provenance = _latest_decision("allreduce_wire")
        self.allreduce_grad_dtype = (
            jnp.dtype(allreduce_grad_dtype) if allreduce_grad_dtype else None
        )

    @functools.cached_property
    def _intra(self) -> tuple[int, int]:
        """(intra_rank, processes-on-this-host) — the reference's hostname
        exchange (``_communication_utility.init_ranks`` (dagger), which ran
        ``MPI_Comm_split_type(SHARED)``). Lazy so that *construction* stays
        a local, non-collective act (safe to do asymmetrically); the first
        ``intra_rank``/``intra_size`` access on a multi-process runtime is a
        host-plane allgather and must happen on every process."""
        if self.host.size == 1:
            return 0, 1
        import socket

        me = (socket.gethostname(), self.host.rank)
        infos = self.host.allgather_obj(me)
        same_host = sorted(r for h, r in infos if h == me[0])
        return same_host.index(self.host.rank), len(same_host)

    # ------------------------------------------------------------------
    # Topology properties (reference: communicator_base.py (dagger))
    # ------------------------------------------------------------------

    @property
    def size(self) -> int:
        """World size = number of mesh slots (reference: #MPI processes)."""
        return self.topology.size

    @functools.cached_property
    def device_kind(self) -> str:
        """``device_kind`` of this mesh's devices (``"cpu"``,
        ``"TPU v5 lite"``, ...) — the device-aware dispatch key the
        autotune registry (chainermn_tpu.tuning) resolves against.
        Cached: the mesh is immutable, and the wire-trace layer stamps
        this onto every collective event."""
        try:
            return next(iter(self.mesh.devices.flat)).device_kind
        except Exception:
            return "unknown"

    @contextlib.contextmanager
    def _mark(self, op: str, nbytes=None):
        """Flight-recorder entry marker (ISSUE 6): one lock-free slot
        store naming the collective this process is ABOUT to dispatch —
        what the hang watchdog's dump reports when peers never arrive.
        The sites call :meth:`_wire_event` INSIDE the marked region, so
        the marker covers the full dispatch including any sync wait in
        the event; the ``finally`` removes THIS entry by identity
        exactly once whether the body returns, the body raises (a
        caller that catches a bad-dtype/socket error and carries on
        healthy must not leave a phantom marker for the fire-once
        watchdog), or the event itself raises after recording (sync
        mode surfacing a deferred XLA error must not pop an ENCLOSING
        composite's marker — review finding). Always on (the cost is
        one tuple build); host-side only, so the lowered HLO is
        untouched (structural test in tests/test_metrics.py)."""
        token = _flight.collective_entered(
            op, nbytes=nbytes, axes=list(self._flat_axes), size=self.size,
        )
        try:
            yield
        finally:
            _flight.collective_exited(token)

    def _wire_event(
        self, op: str, t0: float, *, payload=None, nbytes=None,
        result=None, **extra,
    ) -> None:
        """Record one collective-wire counter event (no-op when tracing
        is off — one global read). Host-side only: never called from
        inside a jitted program, so instrumentation cannot change the
        lowered HLO (structural test in tests/test_trace.py).
        ``result`` is blocked on only in the recorder's sync mode (true
        wall durations); default durations are dispatch-to-return. The
        flight recorder's in-flight marker is NOT cleared here — the
        enclosing :meth:`_mark` owns its entry and removes it by
        identity on the way out."""
        rec = _trace.active()
        if rec is None:
            return
        if result is not None:
            _trace.sync_point(result)
        if nbytes is None and payload is not None:
            nbytes = _trace.tree_nbytes(payload)
        rec.collective(
            op, nbytes=nbytes, dur_s=time.perf_counter() - t0,
            size=self.size, device=self.device_kind, **extra,
        )

    @property
    def rank(self) -> int:
        """Host-plane rank (process index). Inside a jitted program use
        :func:`chainermn_tpu.parallel.collectives.axis_index` instead — in
        SPMD one controller drives many mesh slots."""
        return self.topology.rank

    @property
    def intra_rank(self) -> int:
        """Position of this process among the processes sharing its host
        (hostname-discovered, the reference's ``init_ranks``); 0 for a
        single process. Multihost: first access is a host-plane collective
        (see ``_intra``)."""
        return self._intra[0]

    @property
    def intra_size(self) -> int:
        """Single process: devices this process drives (the mesh slots of
        one controller). Multi-process: processes sharing this host (the
        reference's GPUs-per-node count, one process per accelerator)."""
        if self.host.size == 1:
            return self.topology.intra_size
        return self._intra[1]

    @property
    def inter_rank(self) -> int:
        return self.topology.inter_rank

    @property
    def inter_size(self) -> int:
        return self.topology.inter_size

    @property
    def axis_name(self) -> str:
        """Primary data-parallel mesh axis for gradient reduction."""
        return self.mesh.axis_names[0]

    @property
    def grad_axes(self) -> tuple[str, ...]:
        """All mesh axes gradients are averaged over. For a hierarchical
        communicator this is ``('inter', 'intra')`` — XLA performs the
        2-level reduction the reference hand-built (SURVEY.md section 2.2)."""
        return self._flat_axes

    @property
    def bn_axis_name(self):
        """Axis-name argument for flax-style ``axis_name`` parameters
        (sync-BN and friends): the single axis, or the tuple when gradients
        reduce over a factorised mesh."""
        axes = self.grad_axes
        return axes if len(axes) > 1 else axes[0]

    # ------------------------------------------------------------------
    # Eager array collectives over stacked per-rank contributions
    # ------------------------------------------------------------------

    def _shard_stacked(self, x: jax.Array) -> jax.Array:
        x = jnp.asarray(x)
        if x.shape[0] != self.size:
            raise ValueError(
                f"stacked collective input must have leading dim == size "
                f"({self.size}), got shape {x.shape}"
            )
        spec = P(self._flat_axes, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    @functools.cached_property
    def _jitted(self):
        """Jitted shard_map'd collective kernels, built once per communicator
        so jax.jit's trace cache is keyed stably."""
        mesh, axes = self.mesh, self._flat_axes

        def smap(fn, out_stacked: bool):
            def wrapper(x, *args):
                in_spec = P(axes, *([None] * (x.ndim - 1)))
                out_spec = in_spec if out_stacked else P(None, *([None] * (x.ndim - 1)))

                def body(xs, *a):
                    # xs: [1, ...] local shard; collapse the stack dim.
                    return fn(xs[0], *a)[None]

                return shard_map(
                    body, mesh=mesh, in_specs=(in_spec,) + tuple(P() for _ in args),
                    out_specs=out_spec,
                )(x, *args)

            return jax.jit(wrapper, static_argnums=())

        def _reduce(op):
            def fn(x):
                return collectives.allreduce(x, axes, op=op)
            return fn

        def _alltoall(x):
            # Local view is this rank's send row [size, ...]; piece j goes to
            # rank j, received pieces concatenate back along axis 0 — the MPI
            # alltoall exchange as ONE XLA collective over the (possibly
            # factorised) mesh axes.
            return collectives.alltoall(
                x, axes, split_axis=0, concat_axis=0, tiled=True
            )

        return {
            "sum": smap(_reduce("sum"), out_stacked=False),
            "mean": smap(_reduce("mean"), out_stacked=False),
            "max": smap(_reduce("max"), out_stacked=False),
            "min": smap(_reduce("min"), out_stacked=False),
            "alltoall": smap(_alltoall, out_stacked=True),
        }

    def allreduce(self, x: jax.Array, op: str = "sum") -> jax.Array:
        """Eager allreduce of stacked per-rank values ``x[size, ...]`` →
        reduced array ``[...]`` (replicated)."""
        t0 = time.perf_counter()
        x = self._shard_stacked(x)
        with self._mark("allreduce", nbytes=int(x.nbytes)):
            out = self._jitted[op](x)
            self._wire_event("allreduce", t0, nbytes=int(x.nbytes),
                             result=out, reduce_op=op)
        return out[0]

    def _root_process(self, root: int) -> int:
        """Host-plane rank owning mesh slot ``root`` — roots are *mesh-slot*
        ranks (the reference's MPI ranks), not process indices; on a
        multi-process runtime the two differ. For the world communicator the
        host rank IS the process index (asserted at HostComm bootstrap);
        split communicators translate through their member list."""
        pid = list(self.mesh.devices.flat)[root].process_index
        members = self.host.world_members
        return members.index(pid) if members != list(range(len(members))) else pid

    def _agree_value(self, tree: PyTree, root_host_rank: int) -> PyTree:
        """Every process of this communicator gets the root process's value
        of ``tree``.

        World communicators prefer ``multihost_utils.broadcast_one_to_all``
        (device-plane broadcast, scales to big param pytrees); subgroup
        communicators from :meth:`split` — and TCP worlds running without
        the JAX distributed runtime — ride the host plane instead, because
        ``multihost_utils`` collectives are world-global and would deadlock
        or over-synchronise a color group."""
        if self.host.size == 1:
            return tree
        is_subgroup = getattr(self.host, "_world_members", None) is not None
        if not is_subgroup and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return multihost_utils.broadcast_one_to_all(
                tree, is_source=(self.host.rank == root_host_rank)
            )
        payload = None
        if self.host.rank == root_host_rank:
            payload = jax.tree.map(lambda a: np.asarray(a), tree)
        return self.host.bcast_obj(payload, root_host_rank)

    def bcast(self, x: jax.Array, root: int = 0, *, stacked: bool = False) -> jax.Array:
        """Broadcast ``x`` to a mesh-replicated value (the common
        "replicate rank-0 data" use). With ``stacked=True``, ``x`` holds
        per-rank contributions ``[size, ...]`` and ``x[root]`` is broadcast —
        the eager-parity form the stacked-collective tests use. Explicit flag
        rather than shape sniffing: a plain batch whose leading dim happens
        to equal world size must not be silently sliced."""
        t0 = time.perf_counter()
        x = jnp.asarray(x)
        if stacked:
            if x.ndim < 1 or x.shape[0] != self.size:
                raise ValueError(
                    f"stacked bcast input must have leading dim == size "
                    f"({self.size}), got shape {x.shape}"
                )
            x = x[root]
        # Cross-process agreement: every process must end up with the
        # *root process's* value, not its own local one.
        with self._mark("bcast", nbytes=int(x.nbytes)):
            x = self._agree_value(x, self._root_process(root))
            out = jax.device_put(x, NamedSharding(self.mesh, P()))
            self._wire_event("bcast", t0, nbytes=int(out.nbytes),
                             result=out, root=root)
        return out

    def allgather(self, x: jax.Array) -> jax.Array:
        """Identity on the stacked representation (every rank gets all
        contributions), placed replicated — mirrors ``allgather`` semantics."""
        t0 = time.perf_counter()
        x = jnp.asarray(x)
        if x.shape[0] != self.size:
            raise ValueError("allgather expects stacked [size, ...] input")
        with self._mark("allgather", nbytes=int(x.nbytes)):
            out = jax.device_put(x, NamedSharding(self.mesh, P()))
            self._wire_event("allgather", t0, nbytes=int(out.nbytes),
                             result=out)
        return out

    def alltoall(self, x: jax.Array) -> jax.Array:
        """Eager all-to-all on ``x[size, size, ...]`` (rank i's row i is its
        send buffer): returns the transposed exchange, matching
        ``MPI_Alltoall`` on the stacked view. Shards the stack over the mesh
        and runs a real ``lax.all_to_all`` — the bytes move device-to-device
        over ICI, not through a host transpose."""
        t0 = time.perf_counter()
        x = jnp.asarray(x)
        if x.ndim < 2 or x.shape[0] != self.size or x.shape[1] != self.size:
            raise ValueError("alltoall expects [size, size, ...] input")
        x = self._shard_stacked(x)
        with self._mark("alltoall", nbytes=int(x.nbytes)):
            out = self._jitted["alltoall"](x)
            self._wire_event("alltoall", t0, nbytes=int(x.nbytes),
                             result=out)
        return out

    def scatter(self, x: jax.Array, root: int = 0) -> jax.Array:
        """Scatter root's ``[size, ...]`` buffer: shard i receives ``x[i]``,
        returned as the stacked sharded array. Multihost: the root process's
        buffer is broadcast first so every process shards the same data."""
        t0 = time.perf_counter()
        x = jnp.asarray(x)
        with self._mark("scatter", nbytes=int(x.nbytes)):
            x = self._agree_value(x, self._root_process(root))
            out = self._shard_stacked(x)
            self._wire_event("scatter", t0, nbytes=int(x.nbytes),
                             result=out, root=root)
        return out

    # ------------------------------------------------------------------
    # Model-level operations (the reference's hot pair)
    # ------------------------------------------------------------------

    def bcast_data(self, params: PyTree, root: int = 0) -> PyTree:
        """Replicate a parameter pytree across the mesh (and across
        processes when multihost), so all ranks start from rank-``root``'s
        weights — reference ``bcast_data(model)`` called on the first
        optimizer update (``optimizers.py`` (dagger))."""
        t0 = time.perf_counter()
        with self._mark("bcast_data"):
            params = self._agree_value(params, self._root_process(root))
            repl = NamedSharding(self.mesh, P())
            out = jax.tree.map(
                lambda x: jax.device_put(jnp.asarray(x), repl), params
            )
            self._wire_event("bcast_data", t0, payload=out, result=out,
                             root=root)
        return out

    def reduce_gradients_in_jit(
        self, grads: PyTree, *, compress_dtype=None, schedule: str | None = None
    ) -> PyTree:
        """The IN-JIT gradient reduction this communicator's strategy uses —
        called from the train step / optimizer wrapper inside the named-axis
        context. Base strategy: one fused ``pmean`` over ``grad_axes`` (XLA
        derives the topology-aware schedule). Subclasses may pin an explicit
        algorithm (:class:`TwoDimensionalCommunicator`).

        ``schedule`` overrides the strategy with a pinned one from
        :mod:`chainermn_tpu.parallel.reduction_schedule` (``'flat'`` =
        bucketed packed allreduce, ``'two_level'`` = reduce-scatter ->
        shard allreduce -> allgather per bucket); the optimizer wrapper's
        ``reduction_schedule=`` is the normal front door — this knob
        exists for hand-rolled steps that call the communicator directly.
        Outside the named-axis context both forms degrade identically."""
        from chainermn_tpu.optimizers import allreduce_gradients

        if compress_dtype is None:
            compress_dtype = self.allreduce_grad_dtype
        if schedule is not None:
            from chainermn_tpu.parallel.collectives import axes_bound
            from chainermn_tpu.parallel.reduction_schedule import (
                reduce_tree,
            )

            if axes_bound(self.grad_axes):
                return reduce_tree(
                    grads, schedule=schedule, axes=self.grad_axes,
                    compress_dtype=compress_dtype, size=self.size,
                )
        return allreduce_gradients(
            grads, axis_names=self.grad_axes, compress_dtype=compress_dtype
        )

    def allreduce_grad(self, grads: PyTree, op: str = "mean") -> PyTree:
        """Eager gradient allreduce of *stacked* per-rank grads
        (leaves shaped ``[size, ...]``) → averaged pytree ``[...]``.

        This is the eager/debugging form. The production path is in-jit:
        ``optax``-wrapped via :func:`chainermn_tpu.create_multi_node_optimizer`
        which lowers to ``lax.pmean(grads, comm.grad_axes)`` inside the train
        step — XLA fuses the reference's pack → cast → ncclAllReduce → scale →
        unpack pipeline (``pure_nccl_communicator.py`` (dagger), SURVEY.md
        section 3.2) into its collective scheduling.
        """
        dtype = self.allreduce_grad_dtype
        int8_wire = (dtype is not None
                     and jnp.dtype(dtype) == jnp.dtype(jnp.int8))

        def quantize_roundtrip(g, *, per_member: bool):
            # One quantization stage of the int8 wire (the in-jit path's
            # two stages live in _int8_core): max-abs scale, round,
            # dequantize. Stage 1 gets PER-MEMBER scales — the stacked
            # dim-0 slices here ARE the per-rank buffers, and _int8_core
            # has each member scale by its OWN amax (a global scale over
            # the stack would truncate small-magnitude ranks to zero —
            # the very failure a bare astype(int8) has). Stage 2 (the
            # reduced buffer, no rank dim) gets one global scale, like
            # the wire's requantize-the-shard. A 1-D stacked leaf means
            # scalar per-rank buffers, whose roundtrip is exact — the
            # wire's own behaviour on 1-element buffers, not a bug
            # (the in-jit path quantizes per leaf: a scalar per-rank
            # buffer dequantizes exactly there as well).
            if per_member:
                amax = jnp.max(jnp.abs(g), axis=tuple(range(1, g.ndim)),
                               keepdims=True)
            else:
                amax = jnp.max(jnp.abs(g))
            scale = jnp.maximum(amax, 1e-30) / 127.0
            return jnp.clip(jnp.round(g / scale), -127, 127) * scale

        def reduce_leaf(g):
            g = jnp.asarray(g)
            orig = g.dtype
            if int8_wire and jnp.issubdtype(orig, jnp.floating):
                # Eager approximation of the quantized wire: per-rank
                # quantize-dequantize (stage 1), exact mean, one final
                # quantize-dequantize (stage 2) — same two-rounding
                # noise model as the in-jit scheme without its chunking.
                g = quantize_roundtrip(g.astype(jnp.float32),
                                       per_member=True)
                out = self.allreduce(g, op=op)
                return quantize_roundtrip(out, per_member=False).astype(orig)
            if dtype is not None and jnp.issubdtype(orig, jnp.floating):
                g = g.astype(dtype)
            out = self.allreduce(g, op=op)
            return out.astype(orig)

        t0 = time.perf_counter()
        with self._mark("allreduce_grad"):
            out = jax.tree.map(reduce_leaf, grads)
            # The top-level wire event (the per-leaf allreduces above
            # record their own nested events): payload bytes of the whole
            # tree, the wire dtype, and — when this communicator's wire
            # came from ``allreduce_grad_dtype='auto'`` — the autotune
            # provenance.
            self._wire_event(
                "allreduce_grad", t0, payload=grads, result=out,
                wire_dtype=(jnp.dtype(dtype).name if dtype is not None
                            else "none"),
                provenance=self._wire_provenance, reduce_op=op,
            )
        return out

    # ------------------------------------------------------------------
    # Host-plane object collectives (reference: *_obj via mpi4py)
    # ------------------------------------------------------------------

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        # Roots are mesh-slot ranks everywhere in this API; map to the owning
        # process for the host plane (same rule as the array collectives).
        return self.host.bcast_obj(obj, self._root_process(root))

    def gather_obj(self, obj: Any, root: int = 0):
        return self.host.gather_obj(obj, self._root_process(root))

    def allgather_obj(self, obj: Any) -> list[Any]:
        return self.host.allgather_obj(obj)

    def scatter_obj(self, objs: Sequence[Any] | None, root: int = 0) -> Any:
        return self.host.scatter_obj(objs, self._root_process(root))

    def allreduce_obj(self, obj: Any, op: Callable[[Any, Any], Any] | None = None) -> Any:
        return self.host.allreduce_obj(obj, op)

    def send(self, x, dest: int, tag: int = 0) -> None:
        """Eager point-to-point ndarray send (reference:
        ``MpiCommunicatorBase.send`` — an ndarray or tuple of ndarrays,
        preceded by a ``_MessageType`` header describing tuple-ness, shapes
        and dtypes, ``mpi_communicator_base.py`` (dagger)).

        Cross-process transport rides the native TCP host plane (the
        reference's non-CUDA-aware staging: device → host → wire). The
        in-jit production path for model parallelism is
        :mod:`chainermn_tpu.functions.point_to_point` (ppermute); this eager
        form exists for parity and host-driven control flows, not the hot
        loop."""
        t0 = time.perf_counter()
        # p2p counts for the flight marker too — a send into a
        # vanished peer blocks exactly like a collective.
        with self._mark("send"):
            is_tuple = isinstance(x, (tuple, list))
            parts = list(x) if is_tuple else [x]
            header = []
            payloads = []
            for p in parts:
                arr = np.asarray(p)
                header.append((arr.shape, str(arr.dtype)))
                payloads.append(arr.tobytes())
            self.send_obj(("ndarray", is_tuple, header, payloads),
                          dest, tag)
            self._wire_event("send", t0, plane="host",
                             nbytes=sum(len(b) for b in payloads),
                             dest=dest)

    def recv(self, source: int, tag: int = 0):
        """Eager point-to-point ndarray receive; returns NumPy array(s)
        matching the sender's shapes and dtypes EXACTLY (including 64-bit —
        ``jax.device_put`` would canonicalise int64→int32 under the default
        x64-off config, silently corrupting large values). Callers place on
        device with their own sharding/dtype choice."""
        t0 = time.perf_counter()
        # See send: a recv whose sender never shows is the canonical
        # p2p hang — marked like the collectives.
        with self._mark("recv"):
            kind, is_tuple, header, payloads = self.recv_obj(source, tag)
            if kind != "ndarray":
                # Recoverable contract error; the _mark context balances
                # the marker on the raise (callers may catch and carry on).
                raise RuntimeError(
                    f"recv expected an ndarray message, got {kind!r} "
                    "(interleaved send_obj/send on one channel must match "
                    "recv_obj/recv order)"
                )
            self._wire_event("recv", t0, plane="host",
                             nbytes=sum(len(b) for b in payloads),
                             source=source)
        arrays = tuple(
            # .copy(): frombuffer views the wire bytes read-only; MPI recv
            # hands back a writable buffer, so match that contract.
            np.frombuffer(buf, dtype=np.dtype(dt)).reshape(shape).copy()
            for (shape, dt), buf in zip(header, payloads)
        )
        return arrays if is_tuple else arrays[0]

    @functools.cached_property
    def _self_p2p(self) -> dict:
        """FIFO mailboxes for same-process p2p (MPI permits self send/recv;
        mesh-slot ranks sharing one process land here — including all
        single-process use). Keyed ``(slot, tag)`` where the slot is the one
        NAMED IN THE CALL (``dest`` on send, ``source`` on recv), so
        messages to different local slots never cross-deliver.

        Semantics caveat: in single-controller eager mode the caller has no
        rank identity, so MPI's "recv names the SENDER" cannot be expressed
        for co-located pairs — a ring-style ``send(x, next); recv(prev)``
        only pairs up when next/prev live on different processes. For
        cross-slot exchanges inside one process, use the in-jit
        differentiable p2p (:mod:`chainermn_tpu.functions.point_to_point`),
        which has real per-slot identity via ``axis_index``."""
        import collections

        return collections.defaultdict(collections.deque)

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        """Point-to-point host send (reference: ``send_obj`` via MPI). Rides
        the native TCP backend (:mod:`chainermn_tpu.native`); the channel is
        per-pair FIFO, so ``tag`` is carried in-band and matched on receive
        (device-plane p2p lives in :mod:`chainermn_tpu.functions`). Sends to
        mesh slots owned by THIS process are buffered locally (MPI self-send
        parity); the matching ``recv`` must name the same slot."""
        dest_proc = self._root_process(dest)
        if dest_proc == self.host.rank:
            self._self_p2p[(dest, tag)].append(obj)
            return
        self.host.send_obj((tag, obj), dest_proc)

    @functools.cached_property
    def _pending_remote(self) -> dict:
        """Messages pulled off a peer socket while waiting for a different
        tag, keyed ``(src_proc, tag)`` — the receive-side buffering that
        turns the per-pair FIFO wire into MPI-style tag matching (a
        mismatched arrival is stashed, never destroyed)."""
        import collections

        return collections.defaultdict(collections.deque)

    def recv_obj(self, source: int, tag: int = 0) -> Any:
        if source == ANY_SOURCE:
            return self.recv_any_obj(tag)[1]
        src_proc = self._root_process(source)
        if src_proc == self.host.rank:
            box = self._self_p2p.get((source, tag))
            if not box:
                raise RuntimeError(
                    f"recv_obj from local slot {source} (tag {tag}) with no "
                    "buffered self-send — same-process p2p requires a prior "
                    "send addressed to THAT slot/tag"
                )
            return box.popleft()
        pend = self._pending_remote.get((src_proc, tag))
        if pend:
            return pend.popleft()
        while True:
            got_tag, obj = self.host.recv_obj(src_proc)
            if got_tag == tag:
                return obj
            # Other-tag arrival: buffer for its own receiver (MPI matching
            # semantics; blocks here until the wanted tag arrives).
            self._pending_remote[(src_proc, got_tag)].append(obj)

    def _slot_of_process(self, proc: int) -> int:
        """Lowest-numbered mesh slot owned by host-plane rank ``proc`` —
        the source identity reported for cross-process ANY_SOURCE receives
        (a single-controller process has no finer sender identity on the
        eager plane)."""
        for slot in range(self.size):
            if self._root_process(slot) == proc:
                return slot
        raise RuntimeError(f"no mesh slot owned by process {proc}")

    def probe(self, source: int, tag: int = 0) -> bool:
        """Non-blocking pending-message check (reference parity:
        ``MPI_Iprobe`` via mpi4py on the eager transport).

        Same-process slots and already-buffered cross-process messages
        match ``(source, tag)`` exactly. A cross-process SOCKET probe is
        tag-agnostic (the wire is a per-pair FIFO; the tag is read with
        the message), so ``probe(src, tag) == True`` guarantees a message
        from ``src`` is pending but not its tag — the matching ``recv``
        buffers any other-tag arrivals rather than losing them, and
        blocks until the wanted tag arrives. ``source=ANY_SOURCE`` checks
        all peers.

        Ordering constraint (differs from full MPI matching): host-plane
        COLLECTIVES (barrier, bcast_obj, ...) share the per-pair p2p
        channels, so wildcard probes/receives must not run concurrently
        with other ranks' collectives — sequence all p2p before entering
        a collective."""
        def _pending_remote_tag():
            return any(t == tag and dq for (_, t), dq
                       in self._pending_remote.items())

        if source == ANY_SOURCE:
            if any(t == tag and dq
                   for (_, t), dq in self._self_p2p.items()):
                return True
            if _pending_remote_tag():
                return True
            return self.host.size > 1 and any(
                self.host.probe(p)
                for p in range(self.host.size) if p != self.host.rank
            )
        src_proc = self._root_process(source)
        if src_proc == self.host.rank:
            return bool(self._self_p2p.get((source, tag)))
        if self._pending_remote.get((src_proc, tag)):
            return True
        return self.host.probe(src_proc)

    def recv_any_obj(self, tag: int = 0, *,
                     poll_interval: float = 1e-3) -> tuple[int, Any]:
        """Blocking receive from ANY source (reference parity:
        ``recv(source=MPI.ANY_SOURCE)``); returns ``(source, obj)``.
        Same-process mailboxes are served first, then already-buffered
        cross-process messages, then the peer sockets round-robin
        (other-tag arrivals are buffered for their own receivers, never
        dropped). The reported source for a cross-process message is the
        sending process's lowest-numbered mesh slot."""
        import time as _time

        while True:
            for (slot, t), dq in list(self._self_p2p.items()):
                if t == tag and dq:
                    return slot, dq.popleft()
            for (proc, t), dq in list(self._pending_remote.items()):
                if t == tag and dq:
                    return self._slot_of_process(proc), dq.popleft()
            if self.host.size == 1:
                raise RuntimeError(
                    "recv_any_obj with no buffered self-send and no other "
                    "process — nothing can ever arrive"
                )
            progressed = False
            for proc in range(self.host.size):
                if proc == self.host.rank:
                    continue
                if self.host.probe(proc):
                    got_tag, obj = self.host.recv_obj(proc)
                    if got_tag == tag:
                        return self._slot_of_process(proc), obj
                    self._pending_remote[(proc, got_tag)].append(obj)
                    progressed = True
            if not progressed:
                _time.sleep(poll_interval)

    def barrier(self) -> None:
        self.host.barrier()

    # ------------------------------------------------------------------
    # Sub-communicators (reference: ``split()`` via MPI_Comm_split)
    # ------------------------------------------------------------------

    def split(self, color: int, key: int = 0) -> "CommunicatorBase":
        """Group *processes* by ``color`` into sub-communicators (reference:
        ``split()`` via ``MPI_Comm_split``). Single-process: returns self
        (there is nothing to split at host granularity; use
        :meth:`sub_communicator` to subset the mesh).

        Multihost: requires the native TCP host backend (per-pair channels
        serve independent groups; ``multihost_utils`` collectives are
        world-global and would deadlock). The returned communicator's host
        plane is the color group and its mesh covers the group processes'
        devices, so both ``*_obj`` collectives and eager array collectives
        run group-locally."""
        if self.host.size == 1:
            return self
        sub_host = self.host.split(color, key)
        members = sub_host.world_members  # world process ids, group order
        by_pid: dict[int, list] = {}
        for d in self.mesh.devices.flat:
            by_pid.setdefault(d.process_index, []).append(d)
        devices = [d for pid in members for d in by_pid.get(pid, [])]
        sub_mesh = Mesh(np.array(devices).reshape(len(devices)), (self.axis_name,))
        return _SplitCommunicator(
            sub_mesh, _host=sub_host,
            allreduce_grad_dtype=self.allreduce_grad_dtype,
        )

    def sub_communicator(self, device_indices: Sequence[int]) -> "CommunicatorBase":
        """Device-plane split: a communicator over a subset of mesh slots
        (flat indices). This is how single-controller SPMD expresses the
        reference's ``split`` in tests."""
        flat = list(self.mesh.devices.flat)
        devices = [flat[i] for i in device_indices]
        sub_mesh = Mesh(np.array(devices).reshape(len(devices)), (self.axis_name,))
        return CommunicatorBase(sub_mesh, allreduce_grad_dtype=self.allreduce_grad_dtype)

    def __repr__(self) -> str:
        return (
            f"<{type(self).__name__} name={self.name!r} size={self.size} "
            f"axes={dict(self.mesh.shape)} processes={self.host.size}>"
        )


class _SplitCommunicator(CommunicatorBase):
    """Communicator over one color group of a multihost :meth:`split`.

    ``rank``/``size`` are group-relative (MPI parity: the communicator you
    get back from ``MPI_Comm_split`` renumbers you); the host plane is the
    subgroup TCP comm and the mesh holds only group processes' devices."""

    name = "split"

    @property
    def rank(self) -> int:  # group rank, not world process index
        return self.host.rank
