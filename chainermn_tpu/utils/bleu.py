"""Corpus BLEU with distributable sufficient statistics.

The reference's seq2seq example evaluated translations with BLEU
(``examples/seq2seq/seq2seq.py`` (dagger), SURVEY.md §2.8). For multi-node
eval the right aggregation is NOT averaging per-rank BLEU scores — corpus
BLEU is a ratio of summed counts, so each rank computes clipped n-gram
match/total counts and lengths over its shard, the counts are summed across
ranks (``allreduce_obj``), and the score is computed once from the totals.
This module provides exactly that split: :func:`bleu_stats` (per-shard,
summable dict) and :func:`bleu_from_stats` (final score).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Iterable, Sequence

MAX_N = 4


def _ngrams(seq: Sequence[int], n: int) -> Counter:
    return Counter(tuple(seq[i : i + n]) for i in range(len(seq) - n + 1))


def bleu_stats(
    hypothesis: Sequence[int], reference: Sequence[int], max_n: int = MAX_N
) -> dict[str, int]:
    """Sufficient statistics of one sentence pair: clipped n-gram matches and
    totals for n = 1..max_n, plus hypothesis/reference lengths. Dicts from
    many pairs (and many ranks) sum element-wise into corpus statistics."""
    stats = {"hyp_len": len(hypothesis), "ref_len": len(reference)}
    for n in range(1, max_n + 1):
        hyp_ngrams = _ngrams(hypothesis, n)
        ref_ngrams = _ngrams(reference, n)
        match = sum(min(c, ref_ngrams[g]) for g, c in hyp_ngrams.items())
        stats[f"match_{n}"] = match
        stats[f"total_{n}"] = max(len(hypothesis) - n + 1, 0)
    return stats


def empty_stats(max_n: int = MAX_N) -> dict[str, int]:
    """Zero-valued statistics with the full key set — the identity element
    of :func:`sum_stats`. Ranks whose eval shard is empty must contribute
    this (not ``{}``) so cross-rank summation sees identical keys."""
    out = {"hyp_len": 0, "ref_len": 0}
    for n in range(1, max_n + 1):
        out[f"match_{n}"] = 0
        out[f"total_{n}"] = 0
    return out


def sum_stats(
    many: Iterable[dict[str, int]], max_n: int = MAX_N
) -> dict[str, int]:
    """Element-wise sum of stats dicts (what ``allreduce_obj`` does across
    ranks; this is the in-rank reduction over a shard). Seeded with
    :func:`empty_stats` so an empty iterable still yields the full key set."""
    out = empty_stats(max_n)
    for s in many:
        for k, v in s.items():
            out[k] = out.get(k, 0) + v
    return out


def bleu_from_stats(stats: dict[str, int], max_n: int = MAX_N) -> float:
    """Corpus BLEU from summed statistics: geometric mean of n-gram
    precisions times the brevity penalty. Any zero match count → 0.0
    (standard uncased corpus BLEU, no smoothing)."""
    log_precisions = []
    for n in range(1, max_n + 1):
        match, total = stats.get(f"match_{n}", 0), stats.get(f"total_{n}", 0)
        if match == 0 or total == 0:
            return 0.0
        log_precisions.append(math.log(match / total))
    hyp_len, ref_len = stats["hyp_len"], stats["ref_len"]
    if hyp_len == 0:
        return 0.0
    bp = 1.0 if hyp_len > ref_len else math.exp(1.0 - ref_len / hyp_len)
    return bp * math.exp(sum(log_precisions) / max_n)


def corpus_bleu(
    hypotheses: Sequence[Sequence[int]],
    references: Sequence[Sequence[int]],
    max_n: int = MAX_N,
) -> float:
    """Single-process convenience: BLEU over aligned hypothesis/reference
    token-id lists."""
    assert len(hypotheses) == len(references)
    return bleu_from_stats(
        sum_stats(bleu_stats(h, r, max_n) for h, r in zip(hypotheses, references)),
        max_n,
    )


def truncate_at_eos(tokens: Sequence[int], eos: int) -> list[int]:
    """Cut a decoded row at the first ``eos`` (exclusive) — recovers the
    ragged sentence from the static-shape greedy decode."""
    out = []
    for t in tokens:
        if t == eos:
            break
        out.append(int(t))
    return out
