"""Utilities: observability and debug checks.

The reference had no tracing subsystem (SURVEY.md section 5 — users reached
for nvprof and Chainer hooks); the TPU build ships one: ``jax.profiler``
wrappers, the rank-0 logging gate (the pattern every reference example
hand-coded), and the cross-host divergence check that replaces the
collective-ordering deadlock discipline (XLA schedules collectives
statically, so the remaining distributed hazard is *different jitted
programs per host* — caught here, not hung on).
"""

from chainermn_tpu.utils.observability import (
    annotate,
    assert_same_on_all_hosts,
    log0,
    profile,
    rank_zero_only,
)

__all__ = [
    "annotate",
    "assert_same_on_all_hosts",
    "log0",
    "profile",
    "rank_zero_only",
]
