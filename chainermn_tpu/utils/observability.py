"""Profiling, logging and cross-host consistency checks.

SURVEY.md section 5 mappings:
  - tracing/profiling: none in the reference → ``jax.profiler`` here
    (strictly more than the reference had);
  - metrics/observability: the reference's rank-0-gating *pattern*
    (``if comm.rank == 0`` in every example (dagger)) → :func:`log0` /
    :func:`rank_zero_only`;
  - race detection: the reference prevented collective-ordering deadlock by
    API design (delegate variables); under XLA that bug class is gone and
    the remaining hazard is cross-host program divergence (different
    shapes/dtypes traced on different hosts) → :func:`assert_same_on_all_hosts`.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Optional

import jax


@contextlib.contextmanager
def profile(logdir: str):
    """Trace everything inside the block into ``logdir`` (view with
    TensorBoard's profile plugin / xprof). Device memory events are part of
    the standard trace; there is no separate toggle.

    Start/stop are also recorded into the structured event stream
    (:mod:`chainermn_tpu.observability`) when a recorder is active, so a
    JSONL trace shows where the xprof window sat in the step timeline.
    A ``stop_trace`` failure while the block itself is raising must not
    MASK the block's exception (the old bare ``finally`` did exactly
    that); when the block succeeds, a stop failure propagates — the
    trace really wasn't written."""
    import time as _time

    from chainermn_tpu.observability import trace as _trace

    rec = _trace.active()
    t0 = _time.perf_counter()
    if rec is not None:
        rec.event("profile_start", logdir=str(logdir))
    jax.profiler.start_trace(logdir)
    try:
        yield
    except BaseException:
        # The block's own exception is in flight: a failing stop_trace
        # is secondary evidence, not the error the caller needs.
        try:
            jax.profiler.stop_trace()
        except Exception as stop_err:
            if rec is not None:
                rec.event("profile_stop_error",
                          error=f"{type(stop_err).__name__}: {stop_err}")
        raise
    else:
        jax.profiler.stop_trace()
    finally:
        if rec is not None:
            rec.event("profile_stop", logdir=str(logdir),
                      dur_s=round(_time.perf_counter() - t0, 9))


def annotate(name: str):
    """Named span in the device trace — wrap hot regions to find them in
    xprof. Usable as context manager."""
    return jax.profiler.TraceAnnotation(name)


def log0(comm, *args, **kwargs) -> None:
    """``print`` gated on the lead rank (the reference examples' ubiquitous
    ``if comm.rank == 0: print(...)``)."""
    if comm is None or comm.rank == 0:
        print(*args, **kwargs)


def rank_zero_only(comm) -> Callable:
    """Decorator: run the function on rank 0 only (reporter extensions,
    snapshot writers); other ranks get ``None``."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if comm is None or comm.rank == 0:
                return fn(*args, **kwargs)
            return None

        return wrapper

    return deco


def assert_same_on_all_hosts(value: Any, name: str = "value") -> None:
    """Debug-mode agreement check: every JAX process must hold an equal
    ``value`` (shape tuple, program fingerprint, resume step, batch spec).

    Divergence across hosts produces *different* compiled programs and a
    silent hang at the next collective; this turns that hang into an
    immediate error. No-op in single-process runtimes.
    """
    if jax.process_count() == 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils

    if isinstance(value, (int, float, bool)):
        arr = np.asarray([value], dtype=np.float64)
        multihost_utils.assert_equal(arr, f"chainermn_tpu:{name}")
        return
    # Generic objects: compare a stable hash. int32 words, NOT int64:
    # the comparison value round-trips through a device broadcast, and
    # under the default x64-off config jax canonicalises int64 -> int32
    # with silent truncation — the receiving side would then compare its
    # full 64-bit words against truncated ones and "divergence"-fail on
    # AGREEING hosts (caught by tests/mp_worker.py case_assert_same).
    import hashlib
    import pickle

    digest = hashlib.sha256(
        pickle.dumps(value, protocol=4)
    ).digest()[:8]
    arr = np.frombuffer(digest, dtype=np.int32).copy()
    multihost_utils.assert_equal(arr, f"chainermn_tpu:{name}")
