"""Preemption-aware training: catch the eviction signal, agree across
ranks, checkpoint, exit clean.

The reference's only fault story was restart-based: the global except hook
turned crashes into whole-job aborts and the checkpointer resumed from the
newest common snapshot (``global_except_hook.py`` (dagger),
``extensions/checkpoint.py`` (dagger), SURVEY.md §5 "failure detection").
TPU pods add a *forewarned* failure mode — slice preemption delivers
SIGTERM with a grace window — so the TPU-native build upgrades the story:
catch the signal, have every rank agree a checkpoint is due (one rank may
be signalled before the others), save at the same iteration, exit 0. On
restart, ``maybe_load`` resumes from that snapshot — no work lost beyond
the current step.

Usage::

    guard = install_preemption_guard()
    for it in range(start, steps):
        state, metrics = step(state, batch)
        if guard.should_checkpoint(comm, every=50):
            ckpt.save(state, it)
            guard.exit_if_preempted(comm)
"""

from __future__ import annotations

import os
import signal
from typing import Any, Sequence


class PreemptionGuard:
    """Signal-flag holder; see module docstring for the loop protocol."""

    def __init__(self, signals: Sequence[Any]) -> None:
        self._flag = False
        self._installed = []
        for sig in signals:
            prev = signal.signal(sig, self._handler)
            self._installed.append((sig, prev))

    def _handler(self, signum, frame):  # noqa: ARG002 (signal API)
        self._flag = True

    @property
    def triggered(self) -> bool:
        """This process received a preemption signal (local view only —
        use :meth:`should_checkpoint` for the cross-rank decision)."""
        return self._flag

    def should_checkpoint(self, comm, *, every: int | None = None,
                          iteration: int | None = None) -> bool:
        """True when ANY rank has been signalled (host-plane agreement, so
        every rank checkpoints the same iteration). With ``every``, the
        agreement collective only runs on that cadence — signal latency is
        bounded by ``every`` steps and the common case costs nothing.
        ``iteration`` supplies the cadence position explicitly; omitted, an
        internal per-guard call counter is used (every call = one step)."""
        if every is not None:
            if iteration is None:
                iteration = self._auto_iter = getattr(
                    self, "_auto_iter", -1
                ) + 1
            if iteration % every != 0:
                return False
        if comm.host.size == 1:
            return self._flag
        return bool(comm.allreduce_obj(int(self._flag)))

    def exit_if_preempted(self, comm) -> None:
        """After a preemption-triggered save: barrier (everyone's snapshot
        is on disk) then exit 0 — a clean teardown the scheduler reads as
        graceful, unlike the except hook's abort path."""
        if not self.should_checkpoint(comm):
            return
        comm.barrier()
        os._exit(0)

    def uninstall(self) -> None:
        for sig, prev in self._installed:
            signal.signal(sig, prev)
        self._installed = []


def install_preemption_guard(
    signals: Sequence[Any] = (signal.SIGTERM,),
) -> PreemptionGuard:
    """Install handlers for the preemption ``signals`` (default SIGTERM —
    what TPU slice eviction delivers) and return the guard."""
    return PreemptionGuard(signals)
