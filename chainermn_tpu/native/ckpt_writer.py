"""ctypes wrapper over the native async checkpoint writer
(``src/ckpt_writer.cpp``).

TPU train steps take milliseconds; fsync-durable snapshot writes take much
longer. The writer moves the write → fsync → atomic-rename sequence onto a
C++ worker thread with a bounded queue, so :meth:`submit` returns as soon
as the bytes are copied and training continues while the snapshot becomes
durable. Failures are collected and surfaced at :meth:`wait` (the point
where durability is actually needed — e.g. before reporting an iteration as
checkpointed, or inside the preemption guard's exit path).
"""

from __future__ import annotations

import ctypes

from chainermn_tpu.native import lib_path

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(lib_path("ckpt_writer")))
        lib.cw_init.restype = ctypes.c_void_p
        lib.cw_init.argtypes = [ctypes.c_int]
        lib.cw_submit.restype = ctypes.c_int
        lib.cw_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_longlong,
        ]
        lib.cw_pending.restype = ctypes.c_int
        lib.cw_pending.argtypes = [ctypes.c_void_p]
        lib.cw_wait.restype = ctypes.c_int
        lib.cw_wait.argtypes = [ctypes.c_void_p]
        lib.cw_finalize.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class AsyncCheckpointWriter:
    """Background durable-file writer (see module docstring).

    ``queue_depth`` bounds buffered snapshots; a full queue makes
    :meth:`submit` block (backpressure beats unbounded host memory when the
    disk can't keep up with the snapshot cadence).
    """

    def __init__(self, queue_depth: int = 2) -> None:
        self._h = _load().cw_init(queue_depth)

    def _handle(self):
        # finalize() frees the C Writer; a NULL handle into the library
        # would segfault, so the liveness check lives here in Python.
        if not self._h:
            raise RuntimeError("AsyncCheckpointWriter used after finalize()")
        return self._h

    def submit(self, path: str, data: bytes) -> None:
        """Enqueue ``data`` to become the durable content of ``path``
        (written to a temp file, fsynced, atomically renamed)."""
        rc = _load().cw_submit(self._handle(), str(path).encode(), data,
                               len(data))
        if rc != 0:
            raise RuntimeError("submit rejected (writer shutting down)")

    @property
    def pending(self) -> int:
        """Snapshots accepted but not yet durable."""
        return _load().cw_pending(self._handle())

    def wait(self) -> None:
        """Block until every submitted snapshot is durable; raise if any
        write failed since the last wait."""
        failures = _load().cw_wait(self._handle())
        if failures:
            raise RuntimeError(
                f"{failures} async checkpoint write(s) failed "
                "(disk full / permissions / path removed?)"
            )

    def finalize(self) -> None:
        if self._h:
            _load().cw_finalize(self._h)
            self._h = None

    def __del__(self):  # best-effort
        try:
            self.finalize()
        except Exception:
            pass
