"""ctypes wrapper over the native TCP host communicator
(``src/host_comm.cpp``) plus object collectives built on its framed
point-to-point sends.

This is the MPI stand-in for the host plane (SURVEY.md section 5
"distributed communication backend"): pickled-object transport for dataset
scatter, checkpoint agreement and the ``*_obj`` API, with per-pair FIFO
ordering (the guarantee the reference's delegate-variable deadlock
discipline was built on).

Bootstrap (environment, mirroring the reference's mpiexec-provided world):
  CHAINERMN_TPU_RANK / CHAINERMN_TPU_SIZE — this process's rank and world
  size; CHAINERMN_TPU_COORD — ``host:port`` of rank 0's listener.
"""

from __future__ import annotations

import ctypes
import os
import pickle
from typing import Any, Callable, Optional, Sequence

from chainermn_tpu.native import lib_path

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(lib_path()))
        lib.hc_init.restype = ctypes.c_void_p
        lib.hc_init.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.hc_rank.argtypes = [ctypes.c_void_p]
        lib.hc_size.argtypes = [ctypes.c_void_p]
        lib.hc_send.restype = ctypes.c_int
        lib.hc_send.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.hc_recv_size.restype = ctypes.c_int64
        lib.hc_recv_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.hc_recv_body.restype = ctypes.c_int
        lib.hc_recv_body.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.hc_probe.restype = ctypes.c_int
        lib.hc_probe.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.hc_barrier.restype = ctypes.c_int
        lib.hc_barrier.argtypes = [ctypes.c_void_p]
        lib.hc_finalize.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class _LinearObjCollectives:
    """Object collectives as rooted linear exchanges over ``send_obj`` /
    ``recv_obj`` + ``rank``/``size``. Payloads are small (metrics dicts,
    dataset indices, checkpoint manifests), so simplicity beats tree
    algorithms here; the bulk data path is XLA's. Shared by the world
    communicator and by subgroup communicators from :meth:`split` — the
    reference got the same reuse from ``MPI_Comm_split`` returning another
    plain MPI communicator."""

    rank: int
    size: int

    def send_obj(self, obj: Any, dest: int) -> None:
        raise NotImplementedError

    def recv_obj(self, source: int) -> Any:
        raise NotImplementedError

    def barrier(self) -> None:
        """Linear p2p barrier: gather a token to group rank 0, then release.
        (The world communicator overrides this with the native in-library
        barrier.)"""
        if self.size == 1:
            return
        if self.rank == 0:
            for r in range(1, self.size):
                self.recv_obj(r)
            for r in range(1, self.size):
                self.send_obj(None, r)
        else:
            self.send_obj(None, 0)
            self.recv_obj(0)

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        if self.size == 1:
            return obj
        if self.rank == root:
            for r in range(self.size):
                if r != root:
                    self.send_obj(obj, r)
            return obj
        return self.recv_obj(root)

    def gather_obj(self, obj: Any, root: int = 0):
        if self.size == 1:
            return [obj]
        if self.rank == root:
            out: list[Any] = [None] * self.size
            out[root] = obj
            for r in range(self.size):
                if r != root:
                    out[r] = self.recv_obj(r)
            return out
        self.send_obj(obj, root)
        return None

    def allgather_obj(self, obj: Any) -> list[Any]:
        gathered = self.gather_obj(obj, 0)
        return self.bcast_obj(gathered, 0)

    def scatter_obj(self, objs: Sequence[Any] | None, root: int = 0) -> Any:
        if self.size == 1:
            assert objs is not None
            return objs[0]
        if self.rank == root:
            assert objs is not None and len(objs) == self.size
            for r in range(self.size):
                if r != root:
                    self.send_obj(objs[r], r)
            return objs[root]
        return self.recv_obj(root)

    def alltoall_obj(self, objs: Sequence[Any]) -> list[Any]:
        """objs[j] goes to rank j; returns what every rank sent here.

        Ring schedule: round ``d`` sends to ``rank+d`` and receives from
        ``rank-d``. The send runs on a helper thread while this thread
        receives, so the collective never depends on kernel socket
        buffering to avoid deadlock (payloads larger than the socket
        buffer are fine; each round's send/recv touch different sockets —
        or opposite directions of the same full-duplex socket when the
        partners coincide at round size/2)."""
        import threading

        assert len(objs) == self.size
        out: list[Any] = [None] * self.size
        out[self.rank] = objs[self.rank]
        for d in range(1, self.size):
            to = (self.rank + d) % self.size
            frm = (self.rank - d) % self.size
            err: list[BaseException] = []

            def _send():
                try:
                    self.send_obj(objs[to], to)
                except BaseException as e:  # surfaced after join
                    err.append(e)

            t = threading.Thread(target=_send, daemon=True)
            t.start()
            try:
                out[frm] = self.recv_obj(frm)
            except BaseException:
                # Bounded join: if the peer is wedged, propagate the recv
                # error rather than hanging on the stuck send forever (the
                # daemon thread cannot block interpreter exit).
                t.join(timeout=10.0)
                raise
            t.join(timeout=120.0)
            if t.is_alive():
                raise RuntimeError(
                    f"alltoall_obj send to rank {to} stalled >120s "
                    "(peer accepted the connection but stopped reading)"
                )
            if err:
                raise err[0]
        return out

    def allreduce_obj(
        self, obj: Any, op: Callable[[Any, Any], Any] | None = None
    ) -> Any:
        items = self.allgather_obj(obj)
        if op is None:
            from chainermn_tpu.communicators._host_comm import _default_sum

            op = _default_sum
        out = items[0]
        for it in items[1:]:
            out = op(out, it)
        return out

    # -- subgroups (the reference's MPI_Comm_split) ------------------------

    def split(self, color: int, key: int = 0) -> "TcpGroupComm":
        """Partition this communicator's processes by ``color`` into
        independent subgroup communicators; ``key`` orders ranks within a
        group (ties broken by parent rank — exactly ``MPI_Comm_split``).

        Collective over *this* communicator (every member must call it).
        The subgroup rides the parent's per-pair FIFO p2p channels, so
        different groups' collectives proceed independently (disjoint rank
        pairs); interleaving parent-level and group-level collectives on the
        same pairs concurrently is the caller's responsibility to avoid,
        same as MPI's per-communicator ordering rule.
        """
        info = self.allgather_obj((color, key, self.rank))
        members = [r for c, k, r in sorted(
            (c, k, r) for c, k, r in info) if c == color]
        return TcpGroupComm(self, members)


class TcpHostComm(_LinearObjCollectives):
    """Full-mesh TCP communicator over processes (the world)."""

    def __init__(self, rank: int, size: int, coord: str) -> None:
        lib = _load()
        host, port = coord.rsplit(":", 1)
        self._h = lib.hc_init(rank, size, host.encode(), int(port))
        if not self._h:
            raise RuntimeError(
                f"TcpHostComm bootstrap failed (rank {rank}/{size} @ {coord})"
            )
        self.rank = rank
        self.size = size

    @classmethod
    def from_env(cls) -> Optional["TcpHostComm"]:
        """Build from CHAINERMN_TPU_{RANK,SIZE,COORD}; None when unset."""
        rank = os.environ.get("CHAINERMN_TPU_RANK")
        size = os.environ.get("CHAINERMN_TPU_SIZE")
        coord = os.environ.get("CHAINERMN_TPU_COORD")
        if rank is None or size is None or coord is None:
            return None
        return cls(int(rank), int(size), coord)

    # -- point-to-point (the reference's send_obj/recv_obj) ----------------

    def send_obj(self, obj: Any, dest: int) -> None:
        payload = pickle.dumps(obj)
        rc = _load().hc_send(self._h, dest, payload, len(payload))
        if rc != 0:
            raise RuntimeError(f"send_obj to {dest} failed")

    def recv_obj(self, source: int) -> Any:
        lib = _load()
        n = lib.hc_recv_size(self._h, source)
        if n < 0:
            raise RuntimeError(f"recv_obj from {source} failed")
        buf = ctypes.create_string_buffer(int(n))
        if lib.hc_recv_body(self._h, source, buf, n) != 0:
            raise RuntimeError(f"recv_obj from {source} failed")
        return pickle.loads(buf.raw[:n])

    def probe(self, source: int) -> bool:
        """Non-blocking: True when a message from ``source`` is pending
        (the MPI_Iprobe analog; per-pair channels are FIFO, so the pending
        message is the next one ``recv_obj(source)`` would return)."""
        rc = _load().hc_probe(self._h, source)
        if rc < 0:
            raise RuntimeError(f"probe of {source} failed")
        return bool(rc)

    def barrier(self) -> None:
        if self.size == 1:
            return
        if _load().hc_barrier(self._h) != 0:
            raise RuntimeError("barrier failed")

    def finalize(self) -> None:
        if self._h:
            _load().hc_finalize(self._h)
            self._h = None

    def __del__(self):  # best-effort
        try:
            self.finalize()
        except Exception:
            pass


class TcpGroupComm(_LinearObjCollectives):
    """Subgroup communicator from :meth:`_LinearObjCollectives.split`.

    A rank-translated view over the parent's p2p transport: group rank ``i``
    is world rank ``members[i]``. All collective algorithms come from the
    mixin; the barrier is the p2p one (the native in-library barrier is
    world-wide). Nested ``split`` works — ``members`` always refers to the
    *immediate* parent's rank space and translation composes.
    """

    def __init__(self, parent: _LinearObjCollectives, members: Sequence[int]) -> None:
        if parent.rank not in members:
            raise ValueError(
                f"rank {parent.rank} not in its own split group {members}"
            )
        self.parent = parent
        self.members = list(members)
        self.rank = self.members.index(parent.rank)
        self.size = len(self.members)

    def send_obj(self, obj: Any, dest: int) -> None:
        self.parent.send_obj(obj, self.members[dest])

    def recv_obj(self, source: int) -> Any:
        return self.parent.recv_obj(self.members[source])

    def probe(self, source: int) -> bool:
        return self.parent.probe(self.members[source])
