"""Native (C++) runtime components.

The reference's native layer was its transport binding (Cython NCCL,
``chainermn/nccl/nccl.pyx`` (dagger), plus mpi4py's C MPI — SURVEY.md
section 2.1). The TPU build needs no hand-written *device* transport (XLA
collectives own ICI/DCN), so the native layer lives where native still
matters on TPU:

- :mod:`chainermn_tpu.native.tcp_comm` — full-mesh TCP host-plane
  communicator (``src/host_comm.cpp``): the MPI-replacement byte transport
  for pickled-object collectives, point-to-point ``send_obj``/``recv_obj``,
  and rendezvous, with rank 0 as coordinator (the role of MPI_Init + the
  NCCL-unique-id broadcast, SURVEY.md section 3.1).

The shared library is compiled on demand with ``g++`` (no build step needed
at install time) and cached under ``native/build/``.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

_SRC_DIR = Path(__file__).parent / "src"
_BUILD_DIR = Path(__file__).parent / "build"

#: component name -> (source file, extra compile flags)
_COMPONENTS = {
    "host_comm": ("host_comm.cpp", []),
    "data_loader": ("data_loader.cpp", ["-pthread"]),
    "ckpt_writer": ("ckpt_writer.cpp", ["-pthread"]),
}


class NativeBuildError(RuntimeError):
    pass


def _build_dir() -> Path:
    """Writable build-cache directory: the package's own ``build/`` when the
    install is writable (dev checkouts), else a per-user cache — a root-
    installed wheel in read-only site-packages must still compile on demand
    for unprivileged users."""
    try:
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        if os.access(_BUILD_DIR, os.W_OK):
            return _BUILD_DIR
    except OSError:
        pass
    cache = Path(
        os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")
    ) / "chainermn_tpu" / "native"
    try:
        cache.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        raise NativeBuildError(
            f"no writable build dir ({_BUILD_DIR} and {cache} both failed: {e})"
        ) from e
    return cache


def lib_path(name: str = "host_comm", rebuild: bool = False) -> Path:
    """Path to a compiled native component, building it on demand."""
    src_name, flags = _COMPONENTS[name]
    src = _SRC_DIR / src_name
    build_dir = _build_dir()
    lib = build_dir / f"lib{name.replace('_', '')}.so"
    if lib.exists() and not rebuild and lib.stat().st_mtime >= src.stat().st_mtime:
        return lib
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2", "-shared", "-fPIC", "-Wall", *flags,
        "-o", str(lib), str(src),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"building {lib.name} failed: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"building {lib.name} failed:\n{proc.stderr[-2000:]}"
        )
    return lib


def available(name: str = "host_comm") -> bool:
    """True when the native component is present or buildable."""
    try:
        lib_path(name)
        return True
    except NativeBuildError:
        return False


__all__ = [
    "NativeBuildError",
    "lib_path",
    "available",
]
