"""Python wrapper for the native threaded data loader
(``src/data_loader.cpp``): fixed-record binary datasets -> shuffled,
prefetched numpy batches, assembled by C++ worker threads off the GIL.

The TPU-native answer to the reference's MultiprocessIterator usage
(``examples/imagenet/train_imagenet.py`` (dagger), SURVEY.md section 2.8):
same prefetch-ahead-of-device behaviour, no fork (the SPMD controller must
stay single-process), no pickling per batch.

Record layout: a record is the concatenation of the fields' bytes in order
(C-contiguous), e.g. ``[image u8 64*64*3 | label i32]``. Use
:func:`write_fixed_records` to produce files from numpy arrays.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence, Tuple

import numpy as np

from chainermn_tpu.native import lib_path

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(lib_path("data_loader")))
        lib.dl_open.restype = ctypes.c_void_p
        lib.dl_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.dl_num_records.restype = ctypes.c_int64
        lib.dl_num_records.argtypes = [ctypes.c_void_p]
        lib.dl_batches_per_epoch.restype = ctypes.c_int64
        lib.dl_batches_per_epoch.argtypes = [ctypes.c_void_p]
        lib.dl_next.restype = ctypes.c_int64
        lib.dl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.dl_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


Field = Tuple[str, np.dtype, Tuple[int, ...]]


def write_fixed_records(path: str, *arrays: np.ndarray) -> None:
    """Interleave ``arrays`` (same leading dim) into a fixed-record file:
    record i = concat of each array's row i bytes."""
    n = arrays[0].shape[0]
    assert all(a.shape[0] == n for a in arrays)
    # One bulk write: interleave per-record field bytes in numpy.
    rows = [
        np.ascontiguousarray(a).reshape(n, -1).view(np.uint8)
        for a in arrays
    ]
    np.concatenate(rows, axis=1).tofile(path)


class NativeDataLoader:
    """Iterate shuffled prefetched batches from a fixed-record file.

    Args:
      fields: ``(name, dtype, shape)`` per record field, in file order.
      shard: ``(begin, end)`` record range for this process (the dataset
        scatter, SURVEY.md section 3.3); ``None`` = whole file.

    Drop-last semantics: an epoch yields ``floor(n / batch)`` batches; the
    ``n % batch`` tail records of each epoch's shuffle order are skipped
    (static batch shapes are what keep the consuming XLA program cache-hot
    — size your shards accordingly, or pad the record file to a multiple
    of the batch size to see every record each epoch).
    """

    def __init__(
        self,
        path: str,
        fields: Sequence[Field],
        batch_size: int,
        *,
        threads: int = 2,
        prefetch: int = 4,
        seed: int = 0,
        shuffle: bool = True,
        shard: Optional[Tuple[int, int]] = None,
    ) -> None:
        self.fields = [
            (name, np.dtype(dt), tuple(shape)) for name, dt, shape in fields
        ]
        self.record_bytes = sum(
            int(dt.itemsize * np.prod(shape)) if shape else dt.itemsize
            for _, dt, shape in self.fields
        )
        self.batch_size = batch_size
        begin, end = shard if shard is not None else (0, 0)
        self._h = _load().dl_open(
            path.encode(), self.record_bytes, batch_size, threads, prefetch,
            seed, int(shuffle), begin, end,
        )
        if not self._h:
            raise RuntimeError(
                f"dl_open failed for {path!r} (record_bytes="
                f"{self.record_bytes}, batch={batch_size}, shard={shard}) — "
                f"check the file size is a record multiple and the shard "
                f"holds at least one batch"
            )
        self._buf = np.empty(batch_size * self.record_bytes, np.uint8)
        self.epoch = 0

    @property
    def num_records(self) -> int:
        return _load().dl_num_records(self._h)

    @property
    def batches_per_epoch(self) -> int:
        return _load().dl_batches_per_epoch(self._h)

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        ep = _load().dl_next(
            self._h, self._buf.ctypes.data_as(ctypes.c_void_p)
        )
        if ep == -2:
            raise RuntimeError(
                "native loader read failure (dataset file truncated or "
                "unreadable)"
            )
        if ep < 0:
            raise StopIteration
        self.epoch = int(ep)
        out = {}
        rec = self._buf.reshape(self.batch_size, self.record_bytes)
        off = 0
        for name, dt, shape in self.fields:
            nbytes = int(dt.itemsize * np.prod(shape)) if shape else dt.itemsize
            chunk = rec[:, off : off + nbytes]
            # .copy(): the internal buffer is reused by the next __next__;
            # returned arrays must own their data (a single-field layout
            # would otherwise alias self._buf).
            arr = chunk.copy().view(dt)
            out[name] = arr.reshape((self.batch_size,) + shape)
            off += nbytes
        return out

    def close(self) -> None:
        if self._h:
            _load().dl_close(self._h)
            self._h = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass
