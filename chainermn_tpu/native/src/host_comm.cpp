// Native host-plane communicator: full-mesh TCP point-to-point transport.
//
// Role in the framework (SURVEY.md section 2.1-2.2): the reference's only
// native component was its transport binding (Cython NCCL + mpi4py's C MPI).
// On TPU the *device* plane needs no hand-written transport (XLA collectives
// own ICI/DCN), but the *host* plane — pickled-object collectives, dataset
// scatter, checkpoint agreement, the things the reference ran over MPI —
// still needs a process-to-process byte transport. This file is that
// transport: a dependency-free TCP mesh with the same bootstrap role
// MPI_Init + ncclCommInitRank played (rank 0 is the rendezvous, like the
// reference's NCCL-unique-id broadcast, SURVEY.md section 3.1).
//
// Framing: every message is [int64 length | payload]. Ordering: one socket
// per rank pair, so per-pair FIFO, matching MPI's per-channel ordering that
// the reference's delegate-variable discipline relied on.
//
// Build: g++ -O2 -shared -fPIC (see build.py); loaded via ctypes.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

struct Comm {
  int rank = -1;
  int size = 0;
  int listen_fd = -1;
  std::vector<int> peer;  // fd per rank; own slot = -1
};

bool send_all(int fd, const void* buf, int64_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, static_cast<size_t>(n), MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= w;
  }
  return true;
}

bool recv_all(int fd, void* buf, int64_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, static_cast<size_t>(n), 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // peer closed
    p += r;
    n -= r;
  }
  return true;
}

int make_listen_socket(int port, int backlog) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  if (::listen(fd, backlog) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

int get_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) < 0)
    return -1;
  return ntohs(addr.sin_port);
}

int connect_to(const char* host, int port, int retries_ms) {
  for (int waited = 0;; waited += 50) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (waited >= retries_ms) return -1;
    ::usleep(50 * 1000);
  }
}

struct PeerInfo {
  char host[64];
  int32_t port;
};

}  // namespace

extern "C" {

// Bootstrap a full-mesh communicator. rank 0 listens on coord_port of
// coord_host; everyone else rendezvouses there (the MPI_Init /
// nccl-unique-id role). Returns an opaque handle or nullptr.
void* hc_init(int rank, int size, const char* coord_host, int coord_port) {
  auto* c = new Comm;
  c->rank = rank;
  c->size = size;
  c->peer.assign(static_cast<size_t>(size), -1);
  if (size == 1) return c;
  std::vector<PeerInfo> table(static_cast<size_t>(size));

  if (rank == 0) {
    c->listen_fd = make_listen_socket(coord_port, size + 8);
    if (c->listen_fd < 0) goto fail;
    // Registration: collect every rank's (host, listen port).
    std::strncpy(table[0].host, "127.0.0.1", sizeof(table[0].host));
    table[0].port = coord_port;
    for (int i = 1; i < size; ++i) {
      sockaddr_in peer_addr{};
      socklen_t len = sizeof(peer_addr);
      int fd = ::accept(c->listen_fd,
                        reinterpret_cast<sockaddr*>(&peer_addr), &len);
      if (fd < 0) goto fail;
      int32_t peer_rank, peer_port;
      if (!recv_all(fd, &peer_rank, 4) || !recv_all(fd, &peer_port, 4))
        goto fail;
      if (peer_rank < 1 || peer_rank >= size || c->peer[peer_rank] != -1)
        goto fail;
      c->peer[peer_rank] = fd;
      PeerInfo& info = table[static_cast<size_t>(peer_rank)];
      ::inet_ntop(AF_INET, &peer_addr.sin_addr, info.host, sizeof(info.host));
      info.port = peer_port;
    }
    // Broadcast the table; registrant connections stay as the 0<->r links.
    for (int i = 1; i < size; ++i)
      if (!send_all(c->peer[i], table.data(),
                    static_cast<int64_t>(sizeof(PeerInfo)) * size))
        goto fail;
  } else {
    c->listen_fd = make_listen_socket(0, size + 8);
    if (c->listen_fd < 0) goto fail;
    int fd0 = connect_to(coord_host, coord_port, /*retries_ms=*/30000);
    if (fd0 < 0) goto fail;
    int32_t my_rank = rank, my_port = get_port(c->listen_fd);
    if (!send_all(fd0, &my_rank, 4) || !send_all(fd0, &my_port, 4)) goto fail;
    c->peer[0] = fd0;
    if (!recv_all(fd0, table.data(),
                  static_cast<int64_t>(sizeof(PeerInfo)) * size))
      goto fail;
    // Deterministic pairing (no accept/connect deadlock): rank r initiates
    // to ranks 1..r-1 and accepts from ranks r+1..size-1.
    for (int j = 1; j < rank; ++j) {
      int fd = connect_to(table[j].host, table[j].port, 30000);
      if (fd < 0) goto fail;
      int32_t my = rank;
      if (!send_all(fd, &my, 4)) goto fail;
      c->peer[j] = fd;
    }
    for (int j = rank + 1; j < size; ++j) {
      int fd = ::accept(c->listen_fd, nullptr, nullptr);
      if (fd < 0) goto fail;
      int32_t who;
      if (!recv_all(fd, &who, 4)) goto fail;
      if (who <= rank || who >= size || c->peer[who] != -1) goto fail;
      c->peer[who] = fd;
    }
  }
  return c;

fail:
  for (int fd : c->peer)
    if (fd >= 0) ::close(fd);
  if (c->listen_fd >= 0) ::close(c->listen_fd);
  delete c;
  return nullptr;
}

int hc_rank(void* h) { return static_cast<Comm*>(h)->rank; }
int hc_size(void* h) { return static_cast<Comm*>(h)->size; }

// Framed send: [int64 length | payload]. Per-pair FIFO ordering.
int hc_send(void* h, int dst, const void* buf, int64_t n) {
  auto* c = static_cast<Comm*>(h);
  if (dst < 0 || dst >= c->size || dst == c->rank) return -1;
  if (!send_all(c->peer[dst], &n, 8)) return -1;
  if (n > 0 && !send_all(c->peer[dst], buf, n)) return -1;
  return 0;
}

// Blocking: reads the next message's length header from src (the payload
// must then be consumed with hc_recv_body).
int64_t hc_recv_size(void* h, int src) {
  auto* c = static_cast<Comm*>(h);
  if (src < 0 || src >= c->size || src == c->rank) return -1;
  int64_t n = -1;
  if (!recv_all(c->peer[src], &n, 8)) return -1;
  return n;
}

int hc_recv_body(void* h, int src, void* buf, int64_t n) {
  auto* c = static_cast<Comm*>(h);
  if (src < 0 || src >= c->size || src == c->rank) return -1;
  if (n > 0 && !recv_all(c->peer[src], buf, n)) return -1;
  return 0;
}

// Non-blocking probe: 1 = at least one byte of a message (its length
// header) is readable from src, 0 = nothing pending, -1 = invalid peer or
// poll error. The MPI_Iprobe analog for the per-pair FIFO channels.
int hc_probe(void* h, int src) {
  auto* c = static_cast<Comm*>(h);
  if (src < 0 || src >= c->size || src == c->rank) return -1;
  struct pollfd pfd;
  pfd.fd = c->peer[src];
  pfd.events = POLLIN;
  pfd.revents = 0;
  int r = ::poll(&pfd, 1, 0);
  if (r < 0) return -1;
  return (r > 0 && (pfd.revents & POLLIN)) ? 1 : 0;
}

// Dissemination barrier: log2(size) rounds of token exchange.
int hc_barrier(void* h) {
  auto* c = static_cast<Comm*>(h);
  for (int dist = 1; dist < c->size; dist <<= 1) {
    int to = (c->rank + dist) % c->size;
    int from = (c->rank - dist % c->size + c->size) % c->size;
    int64_t token = 0;
    if (hc_send(h, to, nullptr, 0) != 0) return -1;
    if (hc_recv_size(h, from) != 0) return -1;
    (void)token;
  }
  return 0;
}

void hc_finalize(void* h) {
  auto* c = static_cast<Comm*>(h);
  for (int fd : c->peer)
    if (fd >= 0) ::close(fd);
  if (c->listen_fd >= 0) ::close(c->listen_fd);
  delete c;
}

}  // extern "C"
