// Asynchronous checkpoint writer: a background worker thread that makes
// snapshot bytes durable (write -> fsync -> atomic rename) off the training
// thread's critical path. The reference's checkpointer serialized on the
// trainer thread (extensions/checkpoint.py (dagger)); on TPU the step cadence
// is milliseconds and disk syncs are not, so snapshot IO must overlap
// training. Bounded queue => backpressure instead of unbounded memory.
//
// C API (ctypes-friendly, mirrors host_comm.cpp conventions):
//   cw_init(queue_depth)              -> opaque handle
//   cw_submit(h, path, data, len)     -> 0 (blocks while queue is full)
//   cw_pending(h)                     -> jobs not yet durable
//   cw_wait(h)                        -> drain; returns #failures since last
//   cw_finalize(h)                    -> drain, join, free

#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Job {
  std::string path;
  std::vector<char> data;
};

struct Writer {
  std::deque<Job> queue;
  std::mutex mu;
  std::condition_variable cv_push;  // worker waits for work
  std::condition_variable cv_done;  // producers wait for space / drain
  size_t max_depth = 4;
  int in_flight = 0;  // queued + currently being written
  int failures = 0;
  bool stop = false;
  std::thread worker;
};

bool write_durable(const Job& job) {
  // tmp file + fsync + rename: a crash mid-write never corrupts an existing
  // snapshot (same protocol as the Python .tmp/os.replace path).
  std::string tmp = job.path + ".tmp_native";
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  const char* p = job.data.data();
  size_t left = job.data.size();
  bool ok = true;
  while (left > 0) {
    ssize_t n = ::write(fd, p, left);
    if (n < 0) {
      ok = false;
      break;
    }
    p += n;
    left -= static_cast<size_t>(n);
  }
  if (ok && ::fsync(fd) != 0) ok = false;
  ::close(fd);
  if (ok && ::rename(tmp.c_str(), job.path.c_str()) != 0) ok = false;
  if (!ok) ::unlink(tmp.c_str());
  return ok;
}

void run(Writer* w) {
  for (;;) {
    Job job;
    {
      std::unique_lock<std::mutex> lk(w->mu);
      w->cv_push.wait(lk, [&] { return w->stop || !w->queue.empty(); });
      if (w->queue.empty()) return;  // stop requested and drained
      job = std::move(w->queue.front());
      w->queue.pop_front();
    }
    // A queue slot just freed: release any backpressured submit NOW, not
    // after the (multi-second) durable write below.
    w->cv_done.notify_all();
    bool ok = write_durable(job);
    {
      std::lock_guard<std::mutex> lk(w->mu);
      if (!ok) w->failures++;
      w->in_flight--;
    }
    w->cv_done.notify_all();
  }
}

}  // namespace

extern "C" {

void* cw_init(int queue_depth) {
  Writer* w = new Writer();
  if (queue_depth > 0) w->max_depth = static_cast<size_t>(queue_depth);
  w->worker = std::thread(run, w);
  return w;
}

int cw_submit(void* h, const char* path, const char* data, long long len) {
  Writer* w = static_cast<Writer*>(h);
  std::unique_lock<std::mutex> lk(w->mu);
  if (w->stop) return -1;
  w->cv_done.wait(lk, [&] { return w->queue.size() < w->max_depth; });
  Job job;
  job.path = path;
  job.data.assign(data, data + len);
  w->queue.push_back(std::move(job));
  w->in_flight++;
  w->cv_push.notify_one();
  return 0;
}

int cw_pending(void* h) {
  Writer* w = static_cast<Writer*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  return w->in_flight;
}

int cw_wait(void* h) {
  Writer* w = static_cast<Writer*>(h);
  std::unique_lock<std::mutex> lk(w->mu);
  w->cv_done.wait(lk, [&] { return w->in_flight == 0; });
  int f = w->failures;
  w->failures = 0;
  return f;
}

void cw_finalize(void* h) {
  Writer* w = static_cast<Writer*>(h);
  {
    std::unique_lock<std::mutex> lk(w->mu);
    w->cv_done.wait(lk, [&] { return w->in_flight == 0; });
    w->stop = true;
  }
  w->cv_push.notify_all();
  w->worker.join();
  delete w;
}

}  // extern "C"
