// Native threaded data loader: memory-mapped fixed-record dataset ->
// shuffled, prefetched batches on a bounded queue.
//
// Role in the framework: the reference's ImageNet example leaned on
// Chainer's MultiprocessIterator (worker processes decoding/batching ahead
// of the GPU — SURVEY.md section 2.8 notes its fork-before-MPI hazards).
// The TPU equivalent must keep one host process (the SPMD controller) and
// still hide host-side batch assembly behind device compute: C++ worker
// THREADS (no GIL, no fork) pread record ranges from a flat file, assemble
// batches, and park them on a condition-variable queue the Python side pops.
//
// File format: raw concatenation of equal-size records (see
// native/data_loader.py for the numpy writer). Sharding: [begin, end)
// record range per loader — the dataset-scatter index arithmetic
// (SURVEY.md section 3.3) applied to files.
//
// Build: g++ -O2 -shared -fPIC -pthread (see native/__init__.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <random>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Batch {
  int64_t epoch;
  std::vector<char> data;
};

struct Loader {
  int fd = -1;
  int64_t record_bytes = 0;
  int64_t batch = 0;
  int64_t begin = 0, end = 0;  // record shard [begin, end)
  bool shuffle = true;
  uint64_t seed = 0;
  int depth = 4;

  std::vector<std::thread> workers;
  std::deque<Batch> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::atomic<bool> stop{false};
  std::atomic<bool> failed{false};

  // epoch state (guarded by mu)
  int64_t epoch = 0;
  int64_t cursor = 0;  // next batch index within epoch
  std::vector<int64_t> order;

  int64_t n() const { return end - begin; }
  int64_t batches_per_epoch() const { return n() / batch; }

  void reshuffle() {  // call with mu held
    order.resize(static_cast<size_t>(n()));
    for (int64_t i = 0; i < n(); ++i) order[static_cast<size_t>(i)] = begin + i;
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch) * 0x9E3779B97F4A7C15ULL);
      for (int64_t i = n() - 1; i > 0; --i) {
        int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
        std::swap(order[static_cast<size_t>(i)], order[static_cast<size_t>(j)]);
      }
    }
  }

  void worker() {
    std::vector<int64_t> ids(static_cast<size_t>(batch));
    while (!stop.load()) {
      int64_t my_epoch;
      {
        std::unique_lock<std::mutex> lk(mu);
        if (cursor >= batches_per_epoch()) {
          ++epoch;
          cursor = 0;
          reshuffle();
        }
        my_epoch = epoch;
        int64_t b = cursor++;
        for (int64_t i = 0; i < batch; ++i)
          ids[static_cast<size_t>(i)] =
              order[static_cast<size_t>(b * batch + i)];
      }
      Batch out;
      out.epoch = my_epoch;
      out.data.resize(static_cast<size_t>(batch * record_bytes));
      bool ok = true;
      for (int64_t i = 0; i < batch && ok; ++i) {
        int64_t off = ids[static_cast<size_t>(i)] * record_bytes;
        char* dst = out.data.data() + i * record_bytes;
        int64_t got = 0;
        while (got < record_bytes) {
          ssize_t r = ::pread(fd, dst + got,
                              static_cast<size_t>(record_bytes - got),
                              off + got);
          if (r <= 0) { ok = false; break; }
          got += r;
        }
      }
      if (!ok) {
        // Unreadable record (truncated/corrupt file): fail the loader
        // loudly — a silently shrunken epoch would break the
        // every-record-once invariant, and retrying would spin.
        failed.store(true);
        stop.store(true);
        cv_pop.notify_all();
        cv_push.notify_all();
        return;
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_push.wait(lk, [&] {
        return stop.load() || static_cast<int>(queue.size()) < depth;
      });
      if (stop.load()) return;
      queue.push_back(std::move(out));
      cv_pop.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* dl_open(const char* path, int64_t record_bytes, int64_t batch,
              int threads, int prefetch_depth, uint64_t seed, int shuffle,
              int64_t shard_begin, int64_t shard_end) {
  auto* L = new Loader;
  L->fd = ::open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (::fstat(L->fd, &st) != 0 || record_bytes <= 0 ||
      st.st_size % record_bytes != 0) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  int64_t total = st.st_size / record_bytes;
  L->record_bytes = record_bytes;
  L->batch = batch;
  L->begin = shard_begin;
  L->end = (shard_end <= 0 || shard_end > total) ? total : shard_end;
  L->shuffle = shuffle != 0;
  L->seed = seed;
  L->depth = prefetch_depth > 0 ? prefetch_depth : 4;
  if (L->begin < 0 || L->begin >= L->end || L->batch <= 0 ||
      L->n() < L->batch) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  L->reshuffle();
  int nthreads = threads > 0 ? threads : 2;
  for (int i = 0; i < nthreads; ++i)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

int64_t dl_num_records(void* h) { return static_cast<Loader*>(h)->n(); }

int64_t dl_batches_per_epoch(void* h) {
  return static_cast<Loader*>(h)->batches_per_epoch();
}

// Blocking pop: copies batch*record_bytes into out; returns the batch's
// epoch number, -1 after dl_close, or -2 after a read failure.
int64_t dl_next(void* h, void* out) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_pop.wait(lk, [&] { return L->stop.load() || !L->queue.empty(); });
  if (L->failed.load()) return -2;
  if (L->queue.empty()) return -1;
  Batch b = std::move(L->queue.front());
  L->queue.pop_front();
  L->cv_push.notify_one();
  lk.unlock();
  std::memcpy(out, b.data.data(), b.data.size());
  return b.epoch;
}

void dl_close(void* h) {
  auto* L = static_cast<Loader*>(h);
  L->stop.store(true);
  L->cv_push.notify_all();
  L->cv_pop.notify_all();
  for (auto& t : L->workers) t.join();
  ::close(L->fd);
  delete L;
}

}  // extern "C"
