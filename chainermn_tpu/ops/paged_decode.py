"""Fused paged-decode Pallas kernel: one HBM pass per decode tick.

The XLA decode hot path is three programs' worth of HBM traffic per
layer per tick — ``paged_update`` scatter → ``paged_lookup`` gather →
dense attend — and the gather materializes the ENTIRE
``[B, max_blocks * block_size]`` dense KV view regardless of how many
tokens are live (:meth:`~chainermn_tpu.models.transformer.
TransformerBlock._slot_decode_attend`). This module is the ROADMAP's
"fused paged-decode Pallas kernel" item: a flash-decoding-style kernel
over the vLLM paged layout (``vllm/core/block_manager.py`` †, the same
provenance :mod:`chainermn_tpu.ops.paged_kv` cites) that reads each
LIVE block exactly once and never materializes a dense view — the
reference's signature hide-the-phase-cost move
(``double_buffering_optimizer.py`` †) applied to the serving engine's
innermost loop.

Kernel shape, per grid cell ``(b, h, j)`` (slot × kv head × KV-block
slot):

- **Table-indexed in-kernel gather.** The block table and the per-row
  positions ride as SCALAR-PREFETCH operands
  (``pltpu.PrefetchScalarGridSpec``), so the K/V BlockSpec index maps
  read ``tables[b, j]`` directly: the pipeline DMAs physical block
  ``tables[b, j]``'s ``bs × D`` head slice straight from the pool.
  Block slots past the row's live horizon are redirected to one fixed
  block; consecutive revisits of an unchanged block index skip the
  copy, so dead table width costs O(1) reads, not O(max_blocks).
- **Split-K online softmax.** The ``j`` axis is the sequential
  (``arbitrary``) grid dim carrying running max / denominator and an
  fp32 accumulator in VMEM scratch — the partial-combine pass is the
  standard flash recurrence (:mod:`chainermn_tpu.ops.flash_attention`);
  the final slot rescales once and writes O(1) output bytes per row.
- **Masking.** Per-row live-length mask from ``positions`` (query row
  ``t`` of slot ``b`` admits keys at ``kpos <= positions[b] + t``),
  optional sliding-window band (the same band the XLA path applies),
  and explicit scratch-block masking: any table entry equal to
  ``scratch_block`` (id 0 in the serving pool — where beyond-horizon
  writes are redirected, :func:`~chainermn_tpu.ops.paged_kv.
  paged_update`) contributes NOTHING, so a released slot's scratch
  garbage can never leak into a live row.
- **GQA head mapping.** Grid runs over KV heads; the ``group`` query
  heads sharing kv head ``h`` ride as extra query rows in the same
  block (rows ``t * group + g``), so grouped queries share one K/V
  block read — no repeated kv heads, in-kernel or out.
- **``T >= 1`` query rows per slot.** Plain decode (``T = 1``), the
  speculative verify span (``T = K + 1``), the chunked mixed step and
  the prefill tail all ride this ONE kernel; and
  :func:`dense_flash_decode` serves the dense ring cache through the
  same program by viewing ``[B, L, kvh, dh]`` as ``L / bs`` implicit
  blocks per row with an identity table — the way
  :func:`~chainermn_tpu.ops.paged_kv.copy_block` serves plain pools
  and TP stacks with one program. TP-stacked pools (leading stack
  axis) unroll into per-shard calls; there are zero collectives inside.

CPU tests run interpret mode per convention (``interpret=None`` auto-
detects, same rule as flash attention); ALWAYS compile-check on a real
chip before trusting a change — Mosaic rejects layouts interpret mode
accepts (``tools/on_chip_capture.sh`` runs the check mechanically).
Numerics: fp32 accumulation throughout, so outputs are allclose (not
bitwise) to the XLA paged path's fp32 softmax.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chainermn_tpu.ops.attention import NEG_INF
from chainermn_tpu.ops.flash_attention import _pick_block, _use_interpret

_LANES = 128

# (slot, kv head, KV-block slot): the first two produce disjoint output
# rows (any order), the LAST carries the online-softmax accumulators and
# must stay sequential. Interpret mode ignores this; the on-chip compile
# check is what keeps the declaration honest.
_GRID_SEMANTICS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)(
    dimension_semantics=("parallel", "parallel", "arbitrary"),
)


def fused_supported() -> bool:
    """True when this image's Pallas carries the scalar-prefetch grid
    specs the table-indexed gather rides on. The serving engine's
    ``forced:jax-compat`` fallback (via
    :func:`chainermn_tpu._jax_compat.pallas_paged_decode_supported`)
    consults this before cloning a ``fused`` decode model."""
    return (hasattr(pltpu, "PrefetchScalarGridSpec")
            and _GRID_SEMANTICS is not None)


def _decode_body(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                 acc_ref, m_ref, l_ref, *, scale: float, bs: int,
                 group: int, T: int, num_block_slots: int,
                 window: Optional[int], scratch_block: Optional[int]):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    pos0 = pos_ref[b]
    # Whole-block liveness: any key position in logical block j inside
    # the union of the rows' causal bands [pos0 - W + 1, pos0 + T - 1].
    live = j * bs <= pos0 + (T - 1)
    if window is not None:
        live &= (j + 1) * bs - 1 > pos0 - window
    if scratch_block is not None:
        # Scratch entries (beyond-horizon redirects, released rows)
        # carry garbage by contract — the whole block is dead.
        live &= tables_ref[b, j] != scratch_block

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]          # [R, D] query rows for kv head h
        k = k_ref[0, :, 0, :]    # [bs, D] the gathered physical block
        v = v_ref[0, :, 0, :]

        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [R, bs]

        row = lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kpos = j * bs + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        qpos = pos0 + row // group  # row t*group+g queries position pos0+t
        mask = (kpos <= qpos) & (row < T * group)  # causal + row padding
        if window is not None:
            mask &= kpos > qpos - window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]  # [R, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Guard fully-masked ROWS: with every score NEG_INF,
        # exp(s - m_new) would be exp(0) = 1 per entry.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_block_slots - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        # Fully-masked rows (padding, never-admitted spans) emit exact 0.
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[...] / jnp.maximum(l, 1e-37), 0.0
        ).astype(o_ref.dtype)


def paged_flash_decode(q, k_pool, v_pool, block_tables, positions, *,
                       window: Optional[int] = None,
                       scale: Optional[float] = None,
                       scratch_block: Optional[int] = 0,
                       interpret: Optional[bool] = None):
    """Fused attention of ``T >= 1`` fresh query rows per slot against a
    paged KV pool — one HBM pass, no dense view.

    Args:
      q: ``[B, T, Hq, D]`` query rows for the slots' NEWEST positions
        (row ``(b, t)`` sits at absolute position ``positions[b] + t``).
        The caller has already written the matching K/V into the pool
        (:func:`~chainermn_tpu.ops.paged_kv.paged_update` — write and
        attend stay two steps so the write path is IDENTICAL between
        the xla and fused impls).
      k_pool / v_pool: ``[num_blocks, bs, Hkv, D]`` shared pools, or
        ``[S, num_blocks, bs, Hkv, D]`` TP-stacked pools (then ``q`` is
        ``[S, B, T, Hq_local, D]``; tables/positions are shared across
        the stack and there are zero collectives inside).
      block_tables: ``[B, max_blocks]`` int32 — row ``b``'s logical →
        physical block map. Rides as a scalar-prefetch operand; the
        kernel gathers each live block once, in-kernel.
      positions: ``[B]`` int32 first-new-token position per row — the
        live-length mask (and the dead-block DMA cutoff) derive from it.
      window: optional causal sliding-window width (same band as the
        XLA decode mask: ``qpos - window < kpos <= qpos``).
      scale: score scale (default ``D ** -0.5``).
      scratch_block: physical block id whose table entries are fully
        masked (the serving pool's block 0); ``None`` disables the mask
        (the dense view, where every block is slot-owned).
      interpret: Pallas interpret mode; ``None`` auto-detects like
        flash attention (CPU tests interpret; Mosaic on TPU).

    Returns:
      ``[B, T, Hq, D]`` (or ``[S, B, T, Hq_local, D]``) attention
      output in ``q.dtype``; fp32 accumulation inside.
    """
    if k_pool.ndim == 5:
        # TP-stacked pools: per-shard calls unrolled over the (small,
        # static) stack axis — one program text, zero collectives.
        outs = [
            paged_flash_decode(
                q[s], k_pool[s], v_pool[s], block_tables, positions,
                window=window, scale=scale, scratch_block=scratch_block,
                interpret=interpret,
            )
            for s in range(k_pool.shape[0])
        ]
        return jnp.stack(outs)
    if not fused_supported():  # pragma: no cover - gated in the engine
        raise NotImplementedError(
            "paged_flash_decode needs pltpu.PrefetchScalarGridSpec — "
            "this jax's Pallas lacks it (the serving engine falls back "
            "to decode_attend_impl='xla' with forced:jax-compat)"
        )

    B, T, Hq, D = q.shape
    nb, bs, Hkv, Dk = k_pool.shape
    if Dk != D:
        raise ValueError(f"head_dim mismatch: q {D}, pool {Dk}")
    if Hq % Hkv:
        raise ValueError(
            f"q heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    if block_tables.shape[0] != B or positions.shape != (B,):
        raise ValueError(
            f"block_tables {block_tables.shape} / positions "
            f"{positions.shape} must lead with q's batch {B}"
        )
    group = Hq // Hkv
    M = block_tables.shape[1]
    scale = float(D ** -0.5 if scale is None else scale)
    if interpret is None:
        interpret = _use_interpret()

    # Query-row layout: [B, Hkv, R, D] with row t*group+g = (token t,
    # grouped head g) — GQA shares each K/V block read across its whole
    # q-head group. Rows padded to the f32 sublane tile; padded rows are
    # masked to an exact 0 and sliced off.
    R = T * group
    R_pad = max(8, -(-R // 8) * 8)
    q_rows = q.reshape(B, T, Hkv, group, D).transpose(0, 2, 1, 3, 4)
    q_rows = q_rows.reshape(B, Hkv, R, D)
    if R_pad != R:
        q_rows = jnp.pad(q_rows, ((0, 0), (0, 0), (0, R_pad - R), (0, 0)))

    tables = block_tables.astype(jnp.int32)
    pos = positions.astype(jnp.int32)

    def kv_index(b, h, j, tables_ref, pos_ref):
        # Dead slots (past the row's horizon / below its window band)
        # re-target one fixed block: consecutive unchanged block indices
        # revisit the resident copy, so the DMA bill is live blocks
        # only — the "one live-KV read" in byte_audit's decode floor.
        live = j * bs <= pos_ref[b] + (T - 1)
        if window is not None:
            live &= (j + 1) * bs - 1 > pos_ref[b] - window
        dead = (jnp.int32(scratch_block)
                if scratch_block is not None else tables_ref[b, 0])
        return jnp.where(live, tables_ref[b, j], dead), 0, h, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, M),
        in_specs=[
            pl.BlockSpec((1, 1, R_pad, D),
                         lambda b, h, j, tables_ref, pos_ref: (b, h, 0, 0)),
            pl.BlockSpec((1, bs, 1, D), kv_index),
            pl.BlockSpec((1, bs, 1, D), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, R_pad, D),
            lambda b, h, j, tables_ref, pos_ref: (b, h, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((R_pad, D), jnp.float32),       # acc
            pltpu.VMEM((R_pad, _LANES), jnp.float32),  # running max
            pltpu.VMEM((R_pad, _LANES), jnp.float32),  # denominator
        ],
    )

    import functools

    out = pl.pallas_call(
        functools.partial(
            _decode_body, scale=scale, bs=bs, group=group, T=T,
            num_block_slots=M, window=window, scratch_block=scratch_block,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, R_pad, D), q.dtype),
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(tables, pos, q_rows, k_pool, v_pool)

    out = out[:, :, :R].reshape(B, Hkv, T, group, D)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, T, Hq, D)


def dense_flash_decode(q, cache_k, cache_v, positions, slots=None, *,
                       window: Optional[int] = None,
                       scale: Optional[float] = None,
                       interpret: Optional[bool] = None):
    """The dense ring cache through the SAME kernel: ``[B, L, kvh, dh]``
    reshapes (zero-copy) into ``L / bs`` implicit blocks per row and an
    identity block table — per-slot prefill passes ``slots`` (``[B]``
    cache-row ids) and the table simply indexes those rows' blocks, so
    the prefill-tail view needs no gather either. No scratch block:
    every dense block is slot-owned, and the causal mask alone bounds
    the live span (exactly the XLA dense path's masking argument)."""
    Bc, L, Hkv, D = cache_k.shape
    bs = _pick_block(128, L)
    M = L // bs
    pool_k = cache_k.reshape(Bc * M, bs, Hkv, D)
    pool_v = cache_v.reshape(Bc * M, bs, Hkv, D)
    rows = (jnp.arange(Bc, dtype=jnp.int32) if slots is None
            else slots.astype(jnp.int32))
    tables = rows[:, None] * M + jnp.arange(M, dtype=jnp.int32)[None, :]
    return paged_flash_decode(
        q, pool_k, pool_v, tables, positions, window=window, scale=scale,
        scratch_block=None, interpret=interpret,
    )
