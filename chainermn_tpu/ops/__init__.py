"""Compute ops — attention primitives and (Pallas) fused kernels.

The reference had no op library (Chainer supplied the math; its only custom
kernels were the fused cast/scale CuPy kernels on the allreduce path,
``pure_nccl_communicator.py`` (dagger)). Here the op layer exists because the
TPU build adds long-context capability (SURVEY.md section 5): blockwise /
flash attention locals that the sequence-parallel layer
(:mod:`chainermn_tpu.parallel.ring_attention`,
:mod:`chainermn_tpu.parallel.ulysses`) composes with XLA collectives.
"""

from chainermn_tpu.ops.attention import (
    attention,
    dot_product_attention,
    blockwise_attention,
    resolve_attention_impl,
)
from chainermn_tpu.ops.flash_attention import flash_attention

__all__ = [
    "attention",
    "dot_product_attention",
    "blockwise_attention",
    "flash_attention",
    "resolve_attention_impl",
]
