"""Pallas TPU flash-attention kernels: forward AND backward.

The hot local attention op: online-softmax accumulation entirely in VMEM, so
the ``[Tq, Tk]`` score matrix never touches HBM — HBM traffic drops from
O(T^2) to O(T * D), which is the difference between VPU-bound and MXU-bound
attention on TPU. This is one of the "native" components of the build: where
the reference's only custom kernels were fused CuPy cast/scale on the
allreduce path (``pure_nccl_communicator.py`` (dagger), SURVEY.md section
2.1), the TPU build's equivalent hand-written layer is Pallas (SURVEY.md
section 2.1 native-component note).

Forward emits the per-row logsumexp (LSE) alongside the output; backward is
the standard flash recurrence re-deriving probabilities from LSE — two
Pallas kernels (dq; dk+dv), no O(T^2) HBM tensor anywhere. The same block
kernels power the sequence-parallel ring attention
(:mod:`chainermn_tpu.parallel.ring_attention`), which rotates K/V blocks via
``ppermute`` and calls them per arriving block.

Layout: BTHD at the API (framework convention), BHTD inside the kernel grid;
LSE/delta rows are ``[B, H, T]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chainermn_tpu.ops.attention import NEG_INF

_LANES = 128


def _causal_mask(iq, ik, block_q, block_k, shape):
    q_pos = iq * block_q + lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = ik * block_k + lax.broadcasted_iota(jnp.int32, shape, 1)
    return q_pos >= k_pos


def _live(ik, iq, block_q, block_k, causal):
    """Causal: blocks strictly above the diagonal contribute nothing — skip
    their matmuls entirely (≈2x for long sequences)."""
    return (ik * block_k <= iq * block_q + block_q - 1) if causal else True


def _pick_block(requested: int, T: int) -> int:
    """Largest block <= requested that divides ``T``: halve until it fits
    (T=768 with a 512 request -> 256), else fall back to one whole-T block.
    Keeps any sequence length runnable under the large default blocks."""
    b = min(requested, T)
    while T % b and b > 8:
        b //= 2
    return b if T % b == 0 else T


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _seg_mask(sq_ref, sk_ref):
    """Segment mask from the per-block segment-id refs ([1, block] each):
    attention is allowed only within the same packed segment."""
    sq = sq_ref[0]  # [block_q]
    sk = sk_ref[0]  # [block_k]
    return sq[:, None] == sk[None, :]


def _fwd_body(q_ref, k_ref, v_ref, seg_refs, o_ref, lse_ref,
              acc_ref, m_ref, l_ref, *,
              scale: float, causal: bool, block_q: int, block_k: int,
              num_k_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(_live(ik, iq, block_q, block_k, causal))
    def _accumulate():
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]

        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, s.shape)
        if seg_refs is not None:
            sm = _seg_mask(*seg_refs)
            mask = sm if mask is None else mask & sm
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]  # [block_q, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Guard fully-masked ROWS: with every score NEG_INF, exp(s - m_new)
        # would be exp(0) = 1 per entry; the mask re-zeroes them.
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[...] / jnp.maximum(l, 1e-37), 0.0
        ).astype(o_ref.dtype)
        # LSE in the scaled-score domain; fully-masked rows stay NEG_INF.
        lse = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, 1e-37)), NEG_INF
        )  # [block_q, 1]
        lse_ref[0, 0] = lse


def _group(Hq: int, Hkv: int) -> int:
    """GQA group size: q heads per kv head (MQA when Hkv == 1)."""
    if Hq % Hkv:
        raise ValueError(
            f"q heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    return Hq // Hkv


def _flash_fwd_bhtd(q, k, v, seg_q=None, seg_k=None, *, causal, scale,
                    block_q, block_k, interpret):
    """BHTD forward → (out [B,H,Tq,D], lse [B,H,Tq]).

    ``k``/``v`` may carry FEWER heads than ``q`` (GQA/MQA): kv head
    ``h // g`` serves q head ``h`` via the BlockSpec index map — no
    materialized ``jnp.repeat``. ``seg_q``/``seg_k`` are optional
    ``[B, T]`` int32 packed-segment ids."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    g = _group(H, k.shape[1])
    block_q = _pick_block(block_q, Tq)
    block_k = _pick_block(block_k, Tk)
    nq, nk = Tq // block_q, Tk // block_k

    params = dict(scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, num_k_blocks=nk)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, iq, ik: (b, h // g, ik, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, iq, ik: (b, h // g, ik, 0)),
    ]
    has_segments = seg_q is not None
    if has_segments:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b, h, iq, ik: (b, iq)),
            pl.BlockSpec((1, block_k), lambda b, h, iq, ik: (b, ik)),
        ]

        def kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref,
                   acc, m, l):
            _fwd_body(q_ref, k_ref, v_ref, (sq_ref, sk_ref), o_ref, lse_ref,
                      acc, m, l, **params)

        args = (q, k, v, seg_q, seg_k)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l):
            _fwd_body(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                      acc, m, l, **params)

        args = (q, k, v)

    return pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),      # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
        ],
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# Backward: dq kernel (iterate K blocks per fixed Q block)
# ---------------------------------------------------------------------------

def _bwd_dq_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seg_refs,
                 dq_ref, dq_acc, *,
                 scale: float, causal: bool, block_q: int, block_k: int,
                 num_k_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(_live(ik, iq, block_q, block_k, causal))
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]    # [block_q, 1]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, s.shape)
        if seg_refs is not None:
            sm = _seg_mask(*seg_refs)
            mask = sm if mask is None else mask & sm
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        # p from the saved LSE: exp(NEG_INF - lse) underflows to exactly 0,
        # so masked/never-attended entries contribute nothing.
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# Backward: dk/dv kernel (iterate Q blocks per fixed K block)
# ---------------------------------------------------------------------------

def _bwd_dkv_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seg_refs,
                  dk_ref, dv_ref, dk_acc, dv_acc, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  num_q_blocks: int):
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_live(ik, iq, block_q, block_k, causal))
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]    # [block_q, 1]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, s.shape)
        if seg_refs is not None:
            sm = _seg_mask(*seg_refs)
            mask = sm if mask is None else mask & sm
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        # dv += p^T @ do
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale  # [block_q, block_k]
        # dk += ds^T @ q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_bhtd(q, k, v, do, lse, delta, seg_q=None, seg_k=None, *,
                    causal, scale, block_q, block_k, interpret):
    """BHTD backward → (dq, dk, dv), each f32, given saved LSE and
    ``delta = rowsum(do * o)``. With GQA (kv heads Hkv < Hq), dk/dv come
    back at the KV head count: the per-q-head contributions are written
    per-head and group-summed outside the kernel."""
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = _group(H, Hkv)
    block_q = _pick_block(block_q, Tq)
    block_k = _pick_block(block_k, Tk)
    nq, nk = Tq // block_q, Tk // block_k
    has_segments = seg_q is not None

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dq_params = dict(scale=scale, causal=causal,
                     block_q=block_q, block_k=block_k, num_k_blocks=nk)
    dq_in_specs = [
        q_spec,
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h // g, j, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h // g, j, 0)),
        q_spec,
        row_spec,
        row_spec,
    ]
    if has_segments:
        dq_in_specs += [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, j)),
        ]

        def dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      sq_ref, sk_ref, dq_ref, dq_acc):
            _bwd_dq_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         (sq_ref, sk_ref), dq_ref, dq_acc, **dq_params)

        dq_args = (q, k, v, do, lse, delta, seg_q, seg_k)
    else:
        def dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_acc):
            _bwd_dq_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         None, dq_ref, dq_acc, **dq_params)

        dq_args = (q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq, nk),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    # dk/dv grid iterates Q heads; with GQA each q head writes its own
    # [B, H, Tk, D] slot (no cross-head accumulation inside the grid) and
    # the group sum happens below.
    k_spec_in = pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // g, i, 0))
    k_spec_out = pl.BlockSpec((1, 1, block_k, D),
                              lambda b, h, i, j: (b, h, i, 0))
    dkv_params = dict(scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, num_q_blocks=nq)
    dkv_in_specs = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, j, 0)),
        k_spec_in,
        k_spec_in,
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, j, 0)),
        pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, j, 0)),
        pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, j, 0)),
    ]
    if has_segments:
        dkv_in_specs += [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, j)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, i)),
        ]

        def dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       sq_ref, sk_ref, dk_ref, dv_ref, dk_acc, dv_acc):
            _bwd_dkv_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          (sq_ref, sk_ref), dk_ref, dv_ref, dk_acc, dv_acc,
                          **dkv_params)

        dkv_args = (q, k, v, do, lse, delta, seg_q, seg_k)
    else:
        def dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc):
            _bwd_dkv_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          None, dk_ref, dv_ref, dk_acc, dv_acc, **dkv_params)

        dkv_args = (q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[k_spec_out, k_spec_out],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)
    if g > 1:
        dk = dk.reshape(B, Hkv, g, Tk, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, g, Tk, D).sum(axis=2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op: BTHD custom_vjp
# ---------------------------------------------------------------------------

def _use_interpret() -> bool:
    """Mosaic-compile only when the computation will actually hit a TPU:
    honour an explicit ``jax_default_device`` override (the test harness
    pins CPU while a TPU plugin is also loaded) before the backend default."""
    default = jax.config.jax_default_device
    if default is not None:
        # May be a Device object or a platform string (both accepted by JAX).
        return getattr(default, "platform", default) != "tpu"
    return jax.default_backend() not in ("tpu",)


def _to_bhtd(x):
    return x.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(out)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(out), (q, k, v, out, lse)  # out saved in BHTD


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out_bhtd, lse = res
    do = _to_bhtd(g)
    # delta_i = sum_d dO_i . O_i — the rowwise correction term of the flash
    # backward (re-derives softmax jacobian contributions without P).
    delta = jnp.sum(do.astype(jnp.float32) * out_bhtd.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B, H, Tq, 1] (kernel layout)
    dq, dk, dv = _flash_bwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), do, lse, delta,
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return (
        _to_bhtd(dq).astype(q.dtype),
        _to_bhtd(dk).astype(k.dtype),
        _to_bhtd(dv).astype(v.dtype),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_seg(q, k, v, seg, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), seg, seg, causal=causal,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(out)


def _flash_seg_fwd(q, k, v, seg, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), seg, seg, causal=causal,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(out), (q, k, v, seg, out, lse)


def _flash_seg_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, seg, out_bhtd, lse = res
    do = _to_bhtd(g)
    delta = jnp.sum(do.astype(jnp.float32) * out_bhtd.astype(jnp.float32),
                    axis=-1, keepdims=True)
    dq, dk, dv = _flash_bwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), do, lse, delta, seg, seg,
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return (
        _to_bhtd(dq).astype(q.dtype),
        _to_bhtd(dk).astype(k.dtype),
        _to_bhtd(dv).astype(v.dtype),
        None,  # integer segment ids carry no gradient
    )


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention on ``[B, T, H, D]`` inputs, Pallas forward AND
    backward (both VMEM-blocked; the score matrix never exists in HBM in
    either direction).

    ``k``/``v`` may carry fewer heads than ``q`` (GQA/MQA — q heads must be
    a multiple of kv heads; kv blocks are shared via the kernel's index map,
    never materialized per-group). ``segment_ids`` is an optional ``[B, T]``
    int array for packed sequences: attention is confined to positions with
    equal ids (composes with ``causal``).

    On TPU the kernels compile via Mosaic; elsewhere (CPU tests) they run in
    Pallas interpreter mode unless ``interpret=False``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    if segment_ids is not None:
        seg = segment_ids.astype(jnp.int32)
        return _flash_seg(q, k, v, seg, causal, scale, block_q, block_k,
                          interpret)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


# ---------------------------------------------------------------------------
# Block-level entry points for ring attention
# ---------------------------------------------------------------------------

def flash_block_fwd(q, k_blk, v_blk, *, causal, scale, block_q, block_k,
                    interpret, seg_q=None, seg_kv=None):
    """One ring step's forward: full flash over the resident Q shard and ONE
    arriving K/V block, returning BTHD output + ``[B, H, Tq]`` LSE. The ring
    merges successive blocks' (out, lse) partials in log space
    (:func:`chainermn_tpu.parallel.ring_attention.merge_partials`).
    ``seg_q``/``seg_kv`` are the per-shard segment-id slices (the kv ids
    travel with their block around the ring)."""
    out, lse = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k_blk), _to_bhtd(v_blk), seg_q, seg_kv,
        causal=causal,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(out), lse[..., 0]


def flash_block_bwd(q, k_blk, v_blk, do, lse, delta, *, causal, scale,
                    block_q, block_k, interpret, seg_q=None, seg_kv=None):
    """One ring step's backward: (dq, dk_blk, dv_blk) contributions for one
    K/V block, f32, BTHD (lse/delta are ``[B, H, Tq]``)."""
    dq, dk, dv = _flash_bwd_bhtd(
        _to_bhtd(q), _to_bhtd(k_blk), _to_bhtd(v_blk), _to_bhtd(do),
        lse[..., None], delta[..., None], seg_q, seg_kv,
        causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(dq), _to_bhtd(dk), _to_bhtd(dv)
