"""Pallas TPU flash-attention kernels: forward AND backward.

The hot local attention op: online-softmax accumulation entirely in VMEM, so
the ``[Tq, Tk]`` score matrix never touches HBM — HBM traffic drops from
O(T^2) to O(T * D), which is the difference between VPU-bound and MXU-bound
attention on TPU. This is one of the "native" components of the build: where
the reference's only custom kernels were fused CuPy cast/scale on the
allreduce path (``pure_nccl_communicator.py`` (dagger), SURVEY.md section
2.1), the TPU build's equivalent hand-written layer is Pallas (SURVEY.md
section 2.1 native-component note).

Forward emits the per-row logsumexp (LSE) alongside the output; backward is
the standard flash recurrence re-deriving probabilities from LSE — two
Pallas kernels (dq; dk+dv), no O(T^2) HBM tensor anywhere. The same block
kernels power the sequence-parallel ring attention
(:mod:`chainermn_tpu.parallel.ring_attention`), which rotates K/V blocks via
``ppermute`` and calls them per arriving block.

Layout: BTHD at the API (framework convention), BHTD inside the kernel grid;
LSE/delta rows are ``[B, H, T]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chainermn_tpu.ops.attention import NEG_INF

_LANES = 128

# All three kernels share the (B, H, space, reduce) grid shape: the first
# three dims produce disjoint output/scratch slices (any iteration order
# is valid — lets Mosaic parallelise/pipeline them), while the LAST dim
# carries the online-softmax / gradient accumulators and must stay
# sequential. Consumed only by the Mosaic lowering; interpret mode
# ignores it, so the bench kernel sweep's on-chip numerics gate is the
# check that this declaration is honest.
_GRID_SEMANTICS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
)


def _causal_mask(iq, ik, block_q, block_k, shape, window=None,
                 q_offset=0):
    """Causal mask, optionally banded to a sliding window: query at
    GLOBAL position ``i + q_offset`` sees keys ``j`` with
    ``i + q_offset - window < j <= i + q_offset`` (``window=None`` → full
    causal). ``q_offset`` aligns Q against a K axis that starts earlier —
    the sequence-parallel neighbour-tail layout."""
    q_pos = q_offset + iq * block_q + lax.broadcasted_iota(
        jnp.int32, shape, 0
    )
    k_pos = ik * block_k + lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = q_pos >= k_pos
    if window is not None:
        mask &= q_pos - k_pos < window
    return mask


def _live(ik, iq, block_q, block_k, causal, window=None, q_offset=0):
    """Causal: blocks strictly above the diagonal contribute nothing — skip
    their matmuls entirely (≈2x for long sequences). A sliding window
    additionally kills blocks entirely BELOW the band (every pair with
    ``q_pos - k_pos >= window``). With the band-narrowed grids
    (``_band_k``/``_band_q``) this predicate only handles the clipped
    edge slots; the grid itself no longer visits far-out-of-band
    blocks."""
    if not causal:
        return True
    q0 = q_offset + iq * block_q  # min global q position in the block
    alive = ik * block_k <= q0 + block_q - 1
    if window is not None:
        # max k_pos in block = (ik+1)·bk - 1.
        alive &= q0 - ((ik + 1) * block_k - 1) < window
    return alive


def _band_k(block_q: int, block_k: int, window: int, nk: int,
            q_offset: int = 0):
    """Banded-grid geometry for a sliding window, iterating K blocks per
    fixed Q block: ``span`` k-block slots suffice to cover any query
    block's band ``[iq·bq - W + 1, iq·bq + bq - 1]``; ``lo(iq)`` is the
    (possibly negative) first candidate k block. Slots outside ``[0, nk)``
    are dead — the body predicates them off; index maps clip them to a
    valid (unused) block.

    ``span`` is EXACT: the k-block count for query block iq depends only
    on the residue ``r = iq·bq mod bk`` (achievable residues are the
    multiples of gcd(bq, bk)); taking the max over them avoids the
    lazy-bound's extra dead slot — at bq=bk=W it is the difference
    between 2 and 3 DMAs per row."""
    import math

    g = math.gcd(block_q, block_k)
    # Achievable start residues: (q_offset + iq*bq) mod bk ≡ q_offset
    # (mod g). Python // floors (also for negative numerators), which is
    # what the band-start index needs.
    span = max(
        (r + block_q - 1) // block_k - ((r - window + 1) // block_k) + 1
        for r in range(q_offset % g, block_k, g)
    )
    span = min(nk, span)

    shift = nk + (abs(q_offset) // block_k + 1)

    def lo(iq):
        # floor((q_offset + iq*bq - (W-1)) / bk): shift the numerator
        # non-negative so truncating traced-int division equals floor.
        return (
            q_offset + iq * block_q - (window - 1) + shift * block_k
        ) // block_k - shift

    return span, lo


def _band_q(block_q: int, block_k: int, window: int, nq: int,
            q_offset: int = 0):
    """Banded-grid geometry iterating Q blocks per fixed K block: the
    queries that can see k block ik lie (in LOCAL q coordinates) in
    ``[ik·bk - q_offset, ik·bk - q_offset + bk + W - 2]`` (causal lower
    edge + window upper edge). With ``q_offset > 0`` the low end can go
    negative and the high end overshoot ``nq`` — both are dead slots.
    ``span`` is exact by the same residue enumeration as
    :func:`_band_k`."""
    import math

    g = math.gcd(block_q, block_k)
    span = max(
        (r + block_k + window - 2) // block_q + 1
        for r in range((-q_offset) % g, block_q, g)
    )
    span = min(nq, span)

    shift = nq + (abs(q_offset) // block_q + 1)

    def lo(ik):
        return (ik * block_k - q_offset + shift * block_q) // block_q - shift

    return span, lo


def _clipped_slot(lo, n):
    """Slot→true-block mapper for index maps: identity when un-banded,
    else ``clip(lo(i) + j, 0, n - 1)`` (dead slots land on a valid,
    unused block — the body's liveness predicate skips them)."""
    if lo is None:
        return lambda i, j: j
    return lambda i, j: jnp.clip(lo(i) + j, 0, n - 1)


def _pick_block(requested: int, T: int) -> int:
    """Largest block <= requested that divides ``T``: halve until it fits
    (T=768 with a 512 request -> 256), else fall back to one whole-T block.
    Keeps any sequence length runnable under the large default blocks."""
    b = min(requested, T)
    while T % b and b > 8:
        b //= 2
    return b if T % b == 0 else T


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _seg_mask(sq_ref, sk_ref):
    """Segment mask from the per-block segment-id refs: attention is
    allowed only within the same packed segment.

    The q-ids ref is ``[1, block_q, 1]`` and the kv-ids ref
    ``[1, 1, block_k]`` — the host side stores ids as ``[B, T, 1]`` /
    ``[B, 1, T]`` so every Mosaic tile is (major divisible-by-8-or-full,
    minor 1-or-divisible-by-128)-legal AND arrives already column/row
    shaped: the mask is one VPU broadcast-compare, no in-kernel
    transpose. A flat ``[B, T]`` layout with ``(1, block)`` tiles is
    rejected by the Mosaic lowering (sublane dim 1 ≠ B) — caught on
    hardware by the bench kernel sweep; interpret mode accepts it."""
    sq = sq_ref[0]  # [block_q, 1]
    sk = sk_ref[0]  # [1, block_k]
    return sq == sk


def _fwd_body(q_ref, k_ref, v_ref, seg_refs, bias_ref, o_ref, lse_ref,
              acc_ref, m_ref, l_ref, *,
              scale: float, causal: bool, block_q: int, block_k: int,
              num_k_blocks: int, window=None, band_lo=None, nk_total=None,
              q_offset: int = 0):
    iq = pl.program_id(2)
    j = pl.program_id(3)
    # Banded grid: slot j covers TRUE k block band_lo(iq) + j; slots
    # falling outside [0, nk_total) are dead padding.
    ik = j if band_lo is None else band_lo(iq) + j

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = _live(ik, iq, block_q, block_k, causal, window, q_offset)
    if band_lo is not None:
        live &= (ik >= 0) & (ik < nk_total)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)

        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, s.shape, window,
                                q_offset)
        if seg_refs is not None:
            sm = _seg_mask(*seg_refs)
            mask = sm if mask is None else mask & sm
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]  # [block_q, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Guard fully-masked ROWS: with every score NEG_INF, exp(s - m_new)
        # would be exp(0) = 1 per entry; the mask re-zeroes them.
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[...] / jnp.maximum(l, 1e-37), 0.0
        ).astype(o_ref.dtype)
        # LSE in the scaled-score domain; fully-masked rows stay NEG_INF.
        lse = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, 1e-37)), NEG_INF
        )  # [block_q, 1]
        lse_ref[0, 0] = lse


def _group(Hq: int, Hkv: int) -> int:
    """GQA group size: q heads per kv head (MQA when Hkv == 1)."""
    if Hq % Hkv:
        raise ValueError(
            f"q heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    return Hq // Hkv


def _split_refs(refs, n_fixed, has_segments, has_bias):
    """Split a kernel's positional refs into (seg_refs, bias_ref, rest)
    after ``n_fixed`` fixed inputs — shared by all three kernels."""
    i = n_fixed
    seg_refs = None
    if has_segments:
        seg_refs = (refs[i], refs[i + 1])
        i += 2
    bias_ref = None
    if has_bias:
        bias_ref = refs[i]
        i += 1
    return seg_refs, bias_ref, refs[i:]


def _bias_spec(bias, block_q, block_k, swap=False, k_of=None, q_of=None):
    """BlockSpec for an additive bias ``[B|1, H|1, Tq, Tk]`` — size-1
    leading dims broadcast via the index map. ``swap=True`` for grids
    whose 3rd/4th program ids are (ik, iq) instead of (iq, ik).
    ``k_of(iq, j)`` / ``q_of(ik, j)`` translate a banded-grid slot to the
    true (clipped) block index."""
    bb = 0 if bias.shape[0] == 1 else None
    bh = 0 if bias.shape[1] == 1 else None

    def idx(b, h, i, j):
        if swap:
            ik = i
            iq = q_of(i, j) if q_of is not None else j
        else:
            iq = i
            ik = k_of(i, j) if k_of is not None else j
        return (bb if bb is not None else b,
                bh if bh is not None else h, iq, ik)

    return pl.BlockSpec((1, 1, block_q, block_k), idx)


def _flash_fwd_bhtd(q, k, v, seg_q=None, seg_k=None, bias=None, *, causal,
                    scale, block_q, block_k, interpret, window=None,
                    q_offset=0):
    """BHTD forward → (out [B,H,Tq,D], lse [B,H,Tq]).

    ``k``/``v`` may carry FEWER heads than ``q`` (GQA/MQA): kv head
    ``h // g`` serves q head ``h`` via the BlockSpec index map — no
    materialized ``jnp.repeat``. ``seg_q``/``seg_k`` are optional
    ``[B, T]`` int32 packed-segment ids; ``bias`` an optional additive
    ``[B|1, H|1, Tq, Tk]`` score bias (ALiBi etc.), tiled per block."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    g = _group(H, k.shape[1])
    block_q = _pick_block(block_q, Tq)
    block_k = _pick_block(block_k, Tk)
    nq, nk = Tq // block_q, Tk // block_k

    # Banded grid: with a sliding window, only `span` k-block slots per
    # query block can intersect the band — iterate those instead of all
    # nk, making DMA traffic and grid steps O(T·W) too (not just matmuls).
    band_lo = None
    grid_k = nk
    if causal and window is not None:
        span, lo = _band_k(block_q, block_k, window, nk, q_offset)
        if span < nk:
            band_lo, grid_k = lo, span

    k_block = _clipped_slot(band_lo, nk)

    params = dict(scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, num_k_blocks=grid_k,
                  window=window, band_lo=band_lo, nk_total=nk,
                  q_offset=q_offset)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, iq, j: (b, h // g, k_block(iq, j), 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, iq, j: (b, h // g, k_block(iq, j), 0)),
    ]
    has_segments = seg_q is not None
    has_bias = bias is not None
    args = (q, k, v)
    if has_segments:
        in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, h, iq, j: (b, 0, k_block(iq, j))),
        ]
        args += (seg_q[:, :, None], seg_k[:, None, :])
    if has_bias:
        in_specs.append(
            _bias_spec(bias, block_q, block_k, k_of=k_block)
        )
        args += (bias,)

    def kernel(*refs):
        seg_refs, bias_ref, rest = _split_refs(
            refs, 3, has_segments, has_bias
        )
        o_ref, lse_ref, acc, m, l = rest
        _fwd_body(refs[0], refs[1], refs[2], seg_refs, bias_ref,
                  o_ref, lse_ref, acc, m, l, **params)

    return pl.pallas_call(
        kernel,
        grid=(B, H, nq, grid_k),
        compiler_params=_GRID_SEMANTICS,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),      # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
        ],
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# Backward: dq kernel (iterate K blocks per fixed Q block)
# ---------------------------------------------------------------------------

def _bwd_dq_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seg_refs,
                 bias_ref, dq_ref, dq_acc, *,
                 scale: float, causal: bool, block_q: int, block_k: int,
                 num_k_blocks: int, window=None, band_lo=None,
                 nk_total=None, q_offset: int = 0):
    iq = pl.program_id(2)
    j = pl.program_id(3)
    ik = j if band_lo is None else band_lo(iq) + j

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = _live(ik, iq, block_q, block_k, causal, window, q_offset)
    if band_lo is not None:
        live &= (ik >= 0) & (ik < nk_total)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]    # [block_q, 1]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, s.shape, window,
                                q_offset)
        if seg_refs is not None:
            sm = _seg_mask(*seg_refs)
            mask = sm if mask is None else mask & sm
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        # p from the saved LSE: exp(NEG_INF - lse) underflows to exactly 0,
        # so masked/never-attended entries contribute nothing.
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# Backward: dk/dv kernel (iterate Q blocks per fixed K block)
# ---------------------------------------------------------------------------

def _bwd_dkv_body(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seg_refs,
                  bias_ref, dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  num_q_blocks: int, window=None, band_lo=None,
                  nq_total=None, q_offset: int = 0):
    ik = pl.program_id(2)
    j = pl.program_id(3)
    iq = j if band_lo is None else band_lo(ik) + j

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = _live(ik, iq, block_q, block_k, causal, window, q_offset)
    if band_lo is not None:
        # With q_offset > 0 the low end can undershoot too.
        live &= (iq >= 0) & (iq < nq_total)

    if dbias_ref is not None and causal:
        # Each (iq, ik) tile is visited exactly once in this grid; dead
        # (causal-skipped) tiles must still write zeros — Pallas outputs
        # are not pre-zeroed.
        @pl.when(jnp.logical_not(live))
        def _zero_dbias():
            dbias_ref[0, 0] = jnp.zeros_like(dbias_ref[0, 0])

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]    # [block_q, 1]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, s.shape, window,
                                q_offset)
        if seg_refs is not None:
            sm = _seg_mask(*seg_refs)
            mask = sm if mask is None else mask & sm
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        # dv += p^T @ do
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_unscaled = p * (dp - delta)  # d loss / d s_total
        if dbias_ref is not None:
            # dbias tile == ds before the qk-scale factor (the bias adds
            # AFTER the scale multiplies q·k).
            dbias_ref[0, 0] = ds_unscaled.astype(dbias_ref.dtype)
        ds = ds_unscaled * scale  # [block_q, block_k]
        # dk += ds^T @ q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_bhtd(q, k, v, do, lse, delta, seg_q=None, seg_k=None,
                    bias=None, want_dbias=False, *,
                    causal, scale, block_q, block_k, interpret, window=None,
                    q_offset=0):
    """BHTD backward → ``(dq, dk, dv[, dbias])``, each f32, given saved
    LSE and ``delta = rowsum(do * o)``. With GQA (kv heads Hkv < Hq),
    dk/dv come back at the KV head count: the per-q-head contributions
    are written per-head and group-summed outside the kernel.
    ``want_dbias`` materializes the full ``[B, H, Tq, Tk]`` f32 bias
    gradient (then reduced to ``bias``'s broadcast shape) — O(B·H·T²)
    regardless of the bias's own broadcast shape; see the public
    docstring's sizing caution."""
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = _group(H, Hkv)
    block_q = _pick_block(block_q, Tq)
    block_k = _pick_block(block_k, Tk)
    nq, nk = Tq // block_q, Tk // block_k
    has_segments = seg_q is not None
    has_bias = bias is not None
    assert not (want_dbias and not has_bias)

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    # Banded grids (see _flash_fwd_bhtd): dq iterates only the k blocks in
    # the window band; dk/dv only the q blocks that can see this k block.
    # want_dbias forces the full grid — its output tiles every (iq, ik).
    k_band_lo = None
    grid_k = nk
    q_band_lo = None
    grid_q = nq
    if causal and window is not None:
        span_k, lo_k = _band_k(block_q, block_k, window, nk, q_offset)
        if span_k < nk:
            k_band_lo, grid_k = lo_k, span_k
        if not want_dbias:
            span_q, lo_q = _band_q(block_q, block_k, window, nq, q_offset)
            if span_q < nq:
                q_band_lo, grid_q = lo_q, span_q

    k_block = _clipped_slot(k_band_lo, nk)
    q_block = _clipped_slot(q_band_lo, nq)

    dq_params = dict(scale=scale, causal=causal,
                     block_q=block_q, block_k=block_k, num_k_blocks=grid_k,
                     window=window, band_lo=k_band_lo, nk_total=nk,
                     q_offset=q_offset)
    dq_in_specs = [
        q_spec,
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, i, j: (b, h // g, k_block(i, j), 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, i, j: (b, h // g, k_block(i, j), 0)),
        q_spec,
        row_spec,
        row_spec,
    ]
    dq_args = (q, k, v, do, lse, delta)
    if has_segments:
        dq_in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, h, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, h, i, j: (b, 0, k_block(i, j))),
        ]
        dq_args += (seg_q[:, :, None], seg_k[:, None, :])
    if has_bias:
        dq_in_specs.append(_bias_spec(bias, block_q, block_k, k_of=k_block))
        dq_args += (bias,)

    def dq_kernel(*refs):
        seg_refs, bias_ref, rest = _split_refs(
            refs, 6, has_segments, has_bias
        )
        dq_ref, dq_acc = rest
        _bwd_dq_body(refs[0], refs[1], refs[2], refs[3], refs[4], refs[5],
                     seg_refs, bias_ref, dq_ref, dq_acc, **dq_params)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq, grid_k),
        compiler_params=_GRID_SEMANTICS,
        in_specs=dq_in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    # dk/dv grid iterates Q heads; with GQA each q head writes its own
    # [B, H, Tk, D] slot (no cross-head accumulation inside the grid) and
    # the group sum happens below. Grid program ids here are (ik, iq).
    k_spec_in = pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // g, i, 0))
    k_spec_out = pl.BlockSpec((1, 1, block_k, D),
                              lambda b, h, i, j: (b, h, i, 0))
    dkv_params = dict(scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, num_q_blocks=grid_q,
                      window=window, band_lo=q_band_lo, nq_total=nq,
                      q_offset=q_offset)
    dkv_in_specs = [
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, h, i, j: (b, h, q_block(i, j), 0)),
        k_spec_in,
        k_spec_in,
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, h, i, j: (b, h, q_block(i, j), 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda b, h, i, j: (b, h, q_block(i, j), 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda b, h, i, j: (b, h, q_block(i, j), 0)),
    ]
    dkv_args = (q, k, v, do, lse, delta)
    if has_segments:
        dkv_in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda b, h, i, j: (b, q_block(i, j), 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, i)),
        ]
        dkv_args += (seg_q[:, :, None], seg_k[:, None, :])
    if has_bias:
        dkv_in_specs.append(
            _bias_spec(bias, block_q, block_k, swap=True, q_of=q_block)
        )
        dkv_args += (bias,)

    out_specs = [k_spec_out, k_spec_out]
    out_shape = [
        jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
        jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
    ]
    if want_dbias:
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, block_k),
                         lambda b, h, i, j: (b, h, j, i))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, Tq, Tk), jnp.float32)
        )

    def dkv_kernel(*refs):
        seg_refs, bias_ref, rest = _split_refs(
            refs, 6, has_segments, has_bias
        )
        if want_dbias:
            dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc = rest
        else:
            dk_ref, dv_ref, dk_acc, dv_acc = rest
            dbias_ref = None
        _bwd_dkv_body(refs[0], refs[1], refs[2], refs[3], refs[4], refs[5],
                      seg_refs, bias_ref, dk_ref, dv_ref, dbias_ref,
                      dk_acc, dv_acc, **dkv_params)

    res = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk, grid_q),
        compiler_params=_GRID_SEMANTICS,
        in_specs=dkv_in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)
    if want_dbias:
        dk, dv, dbias = res
    else:
        dk, dv = res
        dbias = None
    if g > 1:
        dk = dk.reshape(B, Hkv, g, Tk, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, g, Tk, D).sum(axis=2)
    if want_dbias:
        # Reduce to the bias's broadcast shape.
        if bias.shape[1] == 1:
            dbias = dbias.sum(axis=1, keepdims=True)
        if bias.shape[0] == 1:
            dbias = dbias.sum(axis=0, keepdims=True)
        return dq, dk, dv, dbias
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op: BTHD custom_vjp
# ---------------------------------------------------------------------------

def _use_interpret() -> bool:
    """Mosaic-compile only when the computation will actually hit a TPU:
    honour an explicit ``jax_default_device`` override (the test harness
    pins CPU while a TPU plugin is also loaded) before the backend default."""
    default = jax.config.jax_default_device
    if default is not None:
        # May be a Device object or a platform string (both accepted by JAX).
        return getattr(default, "platform", default) != "tpu"
    return jax.default_backend() not in ("tpu",)


def _to_bhtd(x):
    return x.transpose(0, 2, 1, 3)


# One custom_vjp covers every operand combination: seg/bias are always
# passed (zero-size dummies when unused, selected by the static has_*
# flags), which avoids a per-combination class explosion.
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13))
def _flash_core(q, k, v, seg, bias, has_seg, has_bias, bias_grad, causal,
                scale, block_q, block_k, interpret, window):
    # Primal == fwd minus the residuals: ONE body owns the operand
    # plumbing so primal and vjp forwards can never diverge.
    out, _res = _flash_core_fwd(
        q, k, v, seg, bias, has_seg, has_bias, bias_grad, causal, scale,
        block_q, block_k, interpret, window,
    )
    return out


def _flash_core_fwd(q, k, v, seg, bias, has_seg, has_bias, bias_grad,
                    causal, scale, block_q, block_k, interpret, window):
    out, lse = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v),
        seg if has_seg else None, seg if has_seg else None,
        bias if has_bias else None,  # bias is already scores-layout BHQK
        causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window,
    )
    return _to_bhtd(out), (q, k, v, seg, bias, out, lse)  # out in BHTD


def _flash_core_bwd(has_seg, has_bias, bias_grad, causal, scale, block_q,
                    block_k, interpret, window, res, g):
    q, k, v, seg, bias, out_bhtd, lse = res
    do = _to_bhtd(g)
    # delta_i = sum_d dO_i . O_i — the rowwise correction term of the flash
    # backward (re-derives softmax jacobian contributions without P).
    delta = jnp.sum(do.astype(jnp.float32) * out_bhtd.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B, H, Tq, 1] (kernel layout)
    res_bwd = _flash_bwd_bhtd(
        _to_bhtd(q), _to_bhtd(k), _to_bhtd(v), do, lse, delta,
        seg if has_seg else None, seg if has_seg else None,
        bias if has_bias else None, bias_grad,
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, window=window,
    )
    dq, dk, dv = res_bwd[:3]
    if bias_grad:
        dbias = res_bwd[3].astype(bias.dtype)  # already BHQK
    else:
        # No-grad bias (the common ALiBi/static case): a zero cotangent —
        # callers training a bias must pass bias_grad=True.
        dbias = jnp.zeros_like(bias)
    return (
        _to_bhtd(dq).astype(q.dtype),
        _to_bhtd(dk).astype(k.dtype),
        _to_bhtd(dv).astype(v.dtype),
        None,  # integer segment ids carry no gradient
        dbias,
    )


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    bias_grad: bool = False,
    window: Optional[int] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention on ``[B, T, H, D]`` inputs, Pallas forward AND
    backward (both VMEM-blocked; the score matrix never exists in HBM in
    either direction).

    ``k``/``v`` may carry fewer heads than ``q`` (GQA/MQA — q heads must be
    a multiple of kv heads; kv blocks are shared via the kernel's index map,
    never materialized per-group). ``segment_ids`` is an optional ``[B, T]``
    int array for packed sequences: attention is confined to positions with
    equal ids (composes with ``causal``).

    ``bias`` is an optional additive score bias ``[B|1, H|1, Tq, Tk]``
    (BTHD-external layout ``[B|1, Tq, H|1, Tk]`` is NOT used — pass the
    scores layout directly; size-1 batch/head dims broadcast). Applied
    after the qk scale, before masking — the ALiBi/relative-position hook.
    By default the bias gets a ZERO cotangent (static biases); pass
    ``bias_grad=True`` to materialize the true gradient. CAUTION: the
    intermediate dbias buffer is the FULL ``[B, H, Tq, Tk]`` f32 tensor
    (reduced to the bias's broadcast shape only afterwards) — for a
    broadcast bias that is B·H/broadcast-factor times the bias itself;
    size it before asking (e.g. B8·H16·T8192² f32 = 32 GiB). Flash memory
    behaviour is forfeited by request here and nowhere else.

    ``window`` is a causal sliding window (Mistral-style local attention):
    query ``i`` attends to keys ``j`` with ``i - window < j <= i``.
    Requires ``causal=True``. Composes with segment ids, GQA, and bias.
    The kernel grids are BAND-NARROWED: per query block only the k blocks
    that can intersect its window band are visited (and symmetrically for
    dk/dv), so compute, DMA traffic, and grid steps are all O(T·window)
    — true local-attention cost, not just predicated-off matmuls. One
    exception: ``bias_grad=True`` forces the dk/dv kernel back to the
    full grid (its dbias output must tile every (iq, ik)).

    On TPU the kernels compile via Mosaic; elsewhere (CPU tests) they run in
    Pallas interpreter mode unless ``interpret=False``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    has_seg = segment_ids is not None
    has_bias = bias is not None
    if bias_grad and not has_bias:
        raise ValueError("bias_grad=True without a bias")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (the sliding "
                             "window is defined over past positions)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if has_bias:
        if bias.ndim != 4 or bias.shape[0] not in (1, q.shape[0]) \
                or bias.shape[1] not in (1, q.shape[2]) \
                or bias.shape[2] != q.shape[1] or bias.shape[3] != k.shape[1]:
            raise ValueError(
                f"bias must be [B|1, H|1, Tq, Tk] = "
                f"[{q.shape[0]}|1, {q.shape[2]}|1, {q.shape[1]}, "
                f"{k.shape[1]}], got {bias.shape}"
            )
    seg = (segment_ids.astype(jnp.int32) if has_seg
           else jnp.zeros((0,), jnp.int32))
    b = bias if has_bias else jnp.zeros((0,), q.dtype)
    return _flash_core(q, k, v, seg, b, has_seg, has_bias, bias_grad,
                       causal, scale, block_q, block_k, interpret, window)


# ---------------------------------------------------------------------------
# Block-level entry points for ring attention
# ---------------------------------------------------------------------------

def flash_block_fwd(q, k_blk, v_blk, *, causal, scale, block_q, block_k,
                    interpret, seg_q=None, seg_kv=None, window=None,
                    q_offset=0):
    """One ring step's forward: full flash over the resident Q shard and ONE
    arriving K/V block, returning BTHD output + ``[B, H, Tq]`` LSE. The ring
    merges successive blocks' (out, lse) partials in log space
    (:func:`chainermn_tpu.parallel.ring_attention.merge_partials`).
    ``seg_q``/``seg_kv`` are the per-shard segment-id slices (the kv ids
    travel with their block around the ring)."""
    out, lse = _flash_fwd_bhtd(
        _to_bhtd(q), _to_bhtd(k_blk), _to_bhtd(v_blk), seg_q, seg_kv,
        causal=causal, window=window, q_offset=q_offset,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(out), lse[..., 0]


def flash_block_bwd(q, k_blk, v_blk, do, lse, delta, *, causal, scale,
                    block_q, block_k, interpret, seg_q=None, seg_kv=None,
                    window=None, q_offset=0):
    """One ring step's backward: (dq, dk_blk, dv_blk) contributions for one
    K/V block, f32, BTHD (lse/delta are ``[B, H, Tq]``)."""
    dq, dk, dv = _flash_bwd_bhtd(
        _to_bhtd(q), _to_bhtd(k_blk), _to_bhtd(v_blk), _to_bhtd(do),
        lse[..., None], delta[..., None], seg_q, seg_kv,
        causal=causal, scale=scale, window=window, q_offset=q_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return _to_bhtd(dq), _to_bhtd(dk), _to_bhtd(dv)
