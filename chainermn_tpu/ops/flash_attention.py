"""Pallas TPU flash-attention kernel.

The hot local attention op: online-softmax accumulation entirely in VMEM, so
the ``[Tq, Tk]`` score matrix never touches HBM — HBM traffic drops from
O(T^2) to O(T * D), which is the difference between VPU-bound and MXU-bound
attention on TPU. This is one of the "native" components of the build: where
the reference's only custom kernels were fused CuPy cast/scale on the
allreduce path (``pure_nccl_communicator.py`` (dagger), SURVEY.md section
2.1), the TPU build's equivalent hand-written layer is Pallas (SURVEY.md
section 2.1 native-component note).

Backward: a ``jax.custom_vjp`` whose reverse pass rematerialises through the
lax blockwise implementation (:func:`chainermn_tpu.ops.attention.
blockwise_attention`) — flash-style recompute-in-backward, with XLA fusing
the recomputation; numerically identical to differentiating the forward.

Layout: BTHD at the API (framework convention), BHTD inside the kernel grid.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chainermn_tpu.ops.attention import NEG_INF, blockwise_attention

_LANES = 128


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
            scale: float, causal: bool, block_q: int, block_k: int,
            num_k_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: blocks strictly above the diagonal contribute nothing — skip
    # their matmuls entirely (≈2x for long sequences).
    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]

        if causal:
            q_pos = iq * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ik * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]  # [block_q, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[...] / jnp.maximum(l, 1e-37), 0.0
        ).astype(o_ref.dtype)


def _flash_fwd_bhtd(q, k, v, *, causal, scale, block_q, block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k:
        raise ValueError(
            f"flash_attention: seq lens ({Tq}, {Tk}) must be divisible by "
            f"block sizes ({block_q}, {block_k})"
        )
    nq, nk = Tq // block_q, Tk // block_k

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
    )
    return pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, iq, ik: (b, h, ik, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),      # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
        ],
        interpret=interpret,
    )(q, k, v)


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_impl(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    # BTHD -> BHTD for the kernel grid
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_fwd_bhtd(
        qt, kt, vt, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_impl(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res

    def ref(q, k, v):
        return blockwise_attention(
            q, k, v, block_k=block_k, causal=causal, scale=scale
        )

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention on ``[B, T, H, D]`` inputs.

    On TPU the forward runs as a Pallas VMEM kernel; elsewhere (CPU tests)
    it runs in Pallas interpreter mode unless ``interpret=False``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
