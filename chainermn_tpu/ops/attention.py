"""Attention primitives (single-device locals).

Layout convention throughout: ``[batch, seq, heads, head_dim]`` (BTHD).
Softmax statistics are always accumulated in float32 regardless of input
dtype (bf16-safe — the same master-precision discipline as the gradient
allreduce path).

``q_offset`` / ``kv_offset`` express *global* sequence positions so the same
local kernel serves both single-device attention and the sequence-parallel
layers, where each shard sees a slice of the sequence
(:mod:`chainermn_tpu.parallel.ring_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _scale(q, scale: Optional[float]) -> float:
    return scale if scale is not None else q.shape[-1] ** -0.5


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain softmax attention — the correctness reference.

    Args:
      q: ``[B, Tq, H, D]``; k/v: ``[B, Tk, Hkv, D]`` where ``Hkv`` divides
        ``H`` (GQA/MQA: kv heads are repeated across their group).
      causal: mask positions where ``kv_pos > q_pos`` (global positions,
        honouring the offsets).
      segment_ids: optional ``[B, T]`` packed-segment ids (Tq == Tk);
        attention is confined to equal ids. Rows with no visible key
        return zeros.
      bias: optional additive score bias ``[B|1, H|1, Tq, Tk]``, applied
        after the qk scale and before masking.
    """
    s = _scale(q, scale)
    if k.shape[2] != q.shape[2]:
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"q heads ({q.shape[2]}) not a multiple of kv heads "
                f"({k.shape[2]})"
            )
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * s
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    mask = None
    if causal:
        q_pos = q_offset + lax.iota(jnp.int32, q.shape[1])
        kv_pos = kv_offset + lax.iota(jnp.int32, k.shape[1])
        mask = (q_pos[:, None] >= kv_pos[None, :])[None, None]
    if segment_ids is not None:
        seg = (segment_ids[:, :, None] == segment_ids[:, None, :])[:, None]
        mask = seg if mask is None else mask & seg
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if mask is not None:
        # Fully-masked rows: softmax over all-NEG_INF is uniform garbage.
        probs = jnp.where(mask.any(-1, keepdims=True), probs, 0.0)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype)


def online_softmax_block(
    q: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    o: jax.Array,
    m: jax.Array,
    l: jax.Array,
    *,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    scale: Optional[float] = None,
):
    """One online-softmax accumulation step over a K/V block.

    This is the flash-attention inner update — and, run over *remote* K/V
    blocks arriving by ``ppermute`` rotation, the ring-attention inner update
    (SURVEY.md section 5).

    Args:
      q: ``[B, Tq, H, D]`` (any float dtype; accumulation is f32).
      k_blk/v_blk: ``[B, Tk, H, D]`` current block.
      o: ``[B, Tq, H, D]`` f32 running (unnormalised) output.
      m: ``[B, H, Tq]`` f32 running max.
      l: ``[B, H, Tq]`` f32 running normaliser.

    Returns:
      Updated ``(o, m, l)``.
    """
    s = _scale(q, scale)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
    ) * s
    if causal:
        q_pos = q_offset + lax.iota(jnp.int32, q.shape[1])
        kv_pos = kv_offset + lax.iota(jnp.int32, k_blk.shape[1])
        mask = (q_pos[:, None] >= kv_pos[None, :])[None, None]
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    p = jnp.exp(scores - m_new[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    # corr is [B, H, Tq]; o is [B, Tq, H, D] — align layouts for the rescale.
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v_blk, preferred_element_type=jnp.float32
    )
    return o_new, m_new, l_new


def finalize_online_softmax(o: jax.Array, l: jax.Array, dtype) -> jax.Array:
    """Normalise the accumulated output: ``o / l`` with layout fix-up.
    Fully-masked rows (l == 0) return zeros rather than NaN."""
    denom = l.transpose(0, 2, 1)[..., None]
    return jnp.where(denom > 0, o / jnp.maximum(denom, 1e-37), 0.0).astype(dtype)


def resolve_attention_impl(q_shape, dtype, *, windowed: bool = False) -> str:
    """Device-aware attention variant, through the autotune registry
    (:mod:`chainermn_tpu.tuning`), keyed on ``(device_kind,
    bucket(T, H, D), dtype)``.

    The measured inversion the default table encodes (r5 bench
    artifacts, B4xT4096xH8xD128 bf16 causal): the flash kernel is 3.0x
    XLA attention fwd+bwd on TPU v5e but 0.56x under CPU interpret mode
    — so ``flash`` (or ``windowed``, when a sliding window is asked
    for) on accelerators and ``xla`` on CPU, with the persistent cache
    (live-measured or seeded from on-chip captures) overriding per
    shape bucket."""
    from chainermn_tpu import tuning

    B, T, H, D = q_shape
    name = "attention_windowed" if windowed else "attention"
    candidates = ("windowed", "xla") if windowed else ("flash", "xla")
    key = tuning.decision_key(shape=(T, H, D), dtype=dtype)
    return tuning.choice(name, candidates, key)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """The variant-dispatching entry point: one spelling, device-aware
    implementation choice.

    ``impl``: ``'xla'`` (materialised :func:`dot_product_attention`),
    ``'flash'`` / ``'windowed'`` (the Pallas kernel, VMEM-blocked —
    ``'windowed'`` is the banded grid selected when ``window`` is set),
    or ``'auto'`` (default): resolved per device/shape/dtype via
    :func:`resolve_attention_impl`. Every variant computes the same
    attention (the windowed band is reproduced on the xla path as an
    additive score bias), so the choice is pure performance —
    equivalence of both sides is pinned in tests/test_tuning.py.

    ``interpret`` is forwarded to the Pallas kernel (default: interpret
    off-accelerator, the kernel's own rule).
    """
    if window is not None and not causal:
        # The Pallas kernel rejects this; validating HERE keeps the xla
        # path from silently computing different (future-visible) band
        # semantics — the dispatch must never change behaviour.
        raise ValueError("window requires causal=True")
    if impl == "auto":
        impl = resolve_attention_impl(q.shape, q.dtype,
                                      windowed=window is not None)
    if impl == "xla":
        b = bias
        if window is not None:
            # Reproduce the kernel's banded semantics exactly:
            # q_pos - kv_pos < window allowed (composes with causal).
            q_pos = lax.iota(jnp.int32, q.shape[1])
            kv_pos = lax.iota(jnp.int32, k.shape[1])
            band = jnp.where(
                (q_pos[:, None] - kv_pos[None, :]) < window, 0.0, NEG_INF
            )[None, None].astype(jnp.float32)
            b = band if b is None else b.astype(jnp.float32) + band
        return dot_product_attention(
            q, k, v, causal=causal, scale=scale,
            segment_ids=segment_ids, bias=b,
        )
    if impl in ("flash", "windowed"):
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, scale=scale,
            segment_ids=segment_ids, bias=bias, window=window,
            interpret=interpret,
        )
    raise ValueError(
        f"unknown attention impl {impl!r} "
        "(expected auto|xla|flash|windowed)"
    )


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_k: int = 512,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Flash-style blockwise attention via ``lax.scan`` over K/V blocks:
    O(Tq * block_k) live memory instead of materialising ``[Tq, Tk]`` scores.
    Single-device building block; the distributed versions live in
    :mod:`chainermn_tpu.parallel`."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if k.shape[2] != H:
        # GQA in the reference path: materialized repeat (the flash kernel
        # shares kv blocks via its index map instead).
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if Tk % block_k != 0:
        block_k = Tk  # fall back to one block rather than padding
    n_blocks = Tk // block_k

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)

    k_blocks = k.reshape(B, n_blocks, block_k, H, D)
    v_blocks = v.reshape(B, n_blocks, block_k, H, D)

    def body(carry, blk):
        o, m, l = carry
        k_blk, v_blk, idx = blk
        o, m, l = online_softmax_block(
            q, k_blk, v_blk, o, m, l,
            causal=causal, q_offset=0, kv_offset=idx * block_k, scale=scale,
        )
        return (o, m, l), None

    (o, m, l), _ = lax.scan(
        body,
        (o, m, l),
        (
            jnp.moveaxis(k_blocks, 1, 0),
            jnp.moveaxis(v_blocks, 1, 0),
            jnp.arange(n_blocks),
        ),
    )
    return finalize_online_softmax(o, l, q.dtype)
