"""Paged KV-cache primitives: a preallocated block pool + per-slot block
tables (ISSUE 4 tentpole — the serving engine's cache layout).

The dense decode cache reserves ``max_len`` rows for EVERY slot, so HBM
scales with the worst case (``slots x max_len``) while typical requests
use a fraction of it. The paged layout (the vLLM PagedAttention idea,
PAPERS.md) carves one shared pool of fixed-size blocks and maps each
slot's logical positions onto pool blocks through a small int32 table:
HBM scales with the tokens actually resident, and a request join/leave
is a host-side table edit — no device reallocation, no copy.

Pure functions only (the model's decode path and the serving engine
both call them); the host-side allocator that OWNS the tables lives in
:mod:`chainermn_tpu.serving.kv_blocks`.

Layout contract (shared with the allocator):

- ``pool``: ``[num_blocks, block_size, kv_heads, head_dim]``; physical
  block 0 is the SCRATCH block — never handed to a slot, the write
  target for rows whose table has no block (inactive/released slots),
  so a scatter is always in-bounds and collisions only ever trash
  scratch.
- ``block_tables``: ``[B, max_blocks]`` int32 physical ids; logical
  block ``j`` of row ``b`` lives at ``pool[block_tables[b, j]]``.

Both ops are local gathers/scatters — zero collectives, which the
serving suite pins structurally on the tensor-parallel decode program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def paged_update(pool, block_tables, positions, new):
    """Scatter ``new`` token K/V rows into the pool.

    Args:
      pool: ``[num_blocks, block_size, kv_heads, head_dim]``.
      block_tables: ``[B, max_blocks]`` int32.
      positions: ``[B]`` int32 — position of row ``b``'s FIRST new token.
      new: ``[B, T, kv_heads, head_dim]`` — ``T`` consecutive tokens per
        row (``T=1`` steady-state decode, ``T=K+1`` speculative verify
        spans, ``T=bucket`` prefill).

    Returns the updated pool. Rows whose table entries are 0 write into
    the scratch block (see module docstring) — duplicate scatter indices
    there are harmless by construction. Positions BEYOND the table
    horizon (a per-row verify span overhanging ``max_blocks *
    block_size`` — e.g. a slot near the serving horizon, or a released
    slot's stale span) are redirected to scratch explicitly: the naive
    gather would clamp the logical index into the row's LAST table
    entry, which may be a live block.
    """
    block_size = pool.shape[1]
    B, T = new.shape[:2]
    max_blocks = block_tables.shape[1]
    pos = positions[:, None] + jnp.arange(T, dtype=positions.dtype)[None]
    logical = pos // block_size
    offset = pos % block_size
    phys = jnp.take_along_axis(
        block_tables, jnp.minimum(logical, max_blocks - 1), axis=1
    )  # [B, T]
    phys = jnp.where(logical < max_blocks, phys, 0)
    return pool.at[phys.reshape(-1), offset.reshape(-1)].set(
        new.reshape(B * T, *new.shape[2:])
    )


def extract_block(pool, blk):
    """Read one physical block out of the pool: ``pool[blk]`` with the
    block axis kept (``[..., 1, block_size, kv_heads, head_dim]``) —
    the device half of the cross-replica KV handoff
    (:mod:`chainermn_tpu.serving.cluster.kv_transfer`): the serialized
    form a prefill replica streams to a decode replica over the host
    plane. Addressed like :func:`copy_block` at ``ndim - 4``, so one
    program serves the plain pool and the tensor-parallel ``[shards,
    num_blocks, ...]`` stacks (the per-shard slices travel together and
    land shard-for-shard — no cross-shard traffic, zero collectives).
    For a DENSE cache (``[slots, L, kvh, dh]``) axis ``ndim - 4`` is
    the slot axis: the same call extracts a slot's whole row.
    ``blk`` is a traced int32 scalar: one compiled program per engine.
    """
    axis = pool.ndim - 4
    return jax.lax.dynamic_index_in_dim(pool, blk, axis=axis,
                                        keepdims=True)


def inject_block(pool, blk, payload):
    """Write one serialized block back into the pool:
    ``pool[blk] <- payload`` along the block axis (``ndim - 4``) — the
    adopting side of the cross-replica KV handoff. ``payload`` is an
    :func:`extract_block` result (block axis kept), possibly from a
    DIFFERENT process's pool of the same layout. Pure dynamic-update:
    zero collectives, one compiled program per engine (``blk``
    traced); the engine donates the cache through its jit wrapper so
    adoption never reallocates the pool.
    """
    axis = pool.ndim - 4
    return jax.lax.dynamic_update_slice_in_dim(pool, payload, blk,
                                               axis=axis)


def copy_block(pool, src, dst):
    """Copy one physical block: ``pool[dst] <- pool[src]`` along the
    block axis (the copy-on-write primitive behind cross-request prefix
    sharing, :mod:`chainermn_tpu.serving.kv_blocks`).

    The block axis is addressed as ``ndim - 4`` (every pool leaf ends in
    ``[num_blocks, block_size, kv_heads, head_dim]``), so the same call
    serves the plain pool and the engine's tensor-parallel ``[shards,
    num_blocks, ...]`` stacks — a leading-axis-wise copy introduces no
    cross-shard traffic (zero collectives, like the scatter/gather).
    ``src``/``dst`` are traced int32 scalars: one compiled program
    copies any block pair.
    """
    axis = pool.ndim - 4
    blk = jax.lax.dynamic_index_in_dim(pool, src, axis=axis, keepdims=True)
    return jax.lax.dynamic_update_slice_in_dim(pool, blk, dst, axis=axis)


def paged_lookup(pool, block_tables):
    """Gather each row's blocks into a contiguous dense view.

    Returns ``[B, max_blocks * block_size, kv_heads, head_dim]`` — the
    SAME layout the dense cache stores directly, so paged attention is
    the dense attention over this view (identical einsums and masks:
    the paged/dense equivalence the serving tests assert token-for
    -token). Unallocated table entries gather the scratch block;
    position masking excludes them.
    """
    g = pool[block_tables]  # [B, M, bs, kvh, dh]
    B, M, bs = g.shape[:3]
    return g.reshape(B, M * bs, *g.shape[3:])
