"""ZeRO-style optimizer-state sharding over the data-parallel axis.

Absent from the reference (SURVEY.md section 2.2 flags it as the natural
TPU-era extension, hinted by PAPERS.md's automatic cross-replica sharding
retrieval): in plain data parallelism every shard holds the FULL optimizer
state (2x params for Adam). Here each of the ``n`` data shards owns ``1/n``
of every parameter's state:

  1. gradients are ``psum_scatter``-ed — each shard receives the *mean* of
     its own 1/n chunk (same wire bytes as the allreduce it replaces: a
     reduce-scatter is half an allreduce);
  2. the inner optimizer updates only the local chunk (1/n state, 1/n
     update FLOPs);
  3. chunk updates are ``all_gather``-ed back (the other half of the
     allreduce) and applied to the replicated parameters.

Constraint: the inner optimizer must be *elementwise* (sgd/momentum/adam/
adamw/rmsprop...) — anything computing cross-parameter statistics
(global-norm clipping) would see only chunks. Compose such transforms
outside the wrapper.

Usage (inside the shard_map'd train step, like every in-jit collective):

    opt = zero_shard_optimizer(optax.adamw(1e-3), axis_name='data')
    state = opt.init(params)          # per-shard: holds 1/n of adam state
    updates, state = opt.update(grads, state, params)
    params = optax.apply_updates(params, updates)

``axis_name`` may also be a TUPLE of mesh axes: the state then shards
over their flattened product (ravelled index, product size) — the
layout the data-parallel wrapper's ``reduction_schedule='zero'``
(:mod:`chainermn_tpu.parallel.reduction_schedule`,
:class:`chainermn_tpu.optimizers.MultiNodeOptimizer`) builds on, where
the reduce-scatter, the 1/n update, and the allgather fuse into the
gradient-reduction hot path itself (arXiv:2004.13336).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

# Multi-axis group helpers: ONE owner of the flattened ravelled-index
# convention (collectives) — the 'zero' reduction schedule depends on
# the scatter chunk index and the state shard index agreeing, so no
# second copy of the axis-order rule may live here.
from chainermn_tpu.parallel.collectives import (
    _names_tuple as _names,
    axes_index as _group_index,
    axes_size as _group_size,
    two_level_shard_len as _shard_len,
)

PyTree = Any


def _chunk_rows(x: jax.Array, n: int) -> jax.Array:
    """Flatten ``x`` and pad so it splits into ``n`` equal rows [n, c].

    The row length comes from ``collectives.two_level_shard_len`` — the
    ONE owner of the ceil-pad rule, shared with the staged composition
    primitives (``staged_reduce_scatter``): the ZeRO path pairs grad
    chunks from the composed scatter with param chunks from here, and
    the pairing is only correct while both read the same rule."""
    flat = x.reshape(-1)
    c = _shard_len(flat.size, n)
    return jnp.pad(flat, (0, n * c - flat.size)).reshape(n, c)




def _unchunk(rows: jax.Array, shape, dtype) -> jax.Array:
    size = 1
    for s in shape:
        size *= s
    return rows.reshape(-1)[:size].reshape(shape).astype(dtype)


def zero_state_specs(
    inner: optax.GradientTransformation,
    params: PyTree,
    n: int,
    axis_name: str,
) -> PyTree:
    """PartitionSpec tree for the ZeRO-sharded state of ``inner`` — the
    shard_map ``in_specs``/``out_specs`` entry for the optimizer state.

    Chunked (array) leaves concatenate over ``axis_name``; scalar leaves
    (step counters, identical on every shard) stay replicated. Shapes come
    from ``eval_shape`` on abstract 1/n chunks, so nothing is materialised.
    """
    from jax.sharding import PartitionSpec as P

    chunks = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((-(-x.size // n),), x.dtype), params
    )
    template = jax.eval_shape(inner.init, chunks)
    return jax.tree.map(
        lambda l: P(axis_name) if getattr(l, "ndim", 0) >= 1 else P(),
        template,
    )


# ---------------------------------------------------------------------------
# ParallelPlan spec-provider surface (ISSUE 10): the plan composes ZeRO
# from these pieces instead of wrapping the optimizer at the call site —
# this module owes the compiled step exactly one reduce-scatter in and
# one all-gather out per float leaf, and publishes the stacked-state
# layout the plan's shard_map carries with an honest P(axis) spec.
# ---------------------------------------------------------------------------


def zero_plan_axis(axis_name: str = "zero") -> dict:
    """Spec-provider descriptor for :class:`~chainermn_tpu.parallel.plan.
    ParallelPlan`: the ``zero`` axis shards the OPTIMIZER STATE (params
    stay replicated over it — it is a data-parallel axis whose state is
    chunked), and owes the compiled step one reduce-scatter + one
    all-gather per parameter leaf."""
    return {
        "name": axis_name,
        "stacked": False,  # params do NOT stack a leading dim over it
        "state_stacked": True,  # opt state stacks [n, ...] over it
        "collectives": ("reduce-scatter", "all-gather"),
    }


def zero_stacked_init(inner: optax.GradientTransformation, leaves, n: int):
    """Initialise the plan's stacked ZeRO state over ``leaves`` (a list
    pytree of param leaves): every state leaf comes back stacked
    ``[n, ...]`` (scalar counters tiled), so one per-leaf ``P(axis)``
    spec shards the whole subtree — the same layout
    :class:`chainermn_tpu.optimizers.MultiNodeOptimizer`'s ``'zero'``
    schedule uses."""
    rows = [_chunk_rows(jnp.asarray(p), n) for p in leaves]
    return jax.vmap(inner.init)(rows)


def zero_grad_scatter(
    g: jax.Array, axis_name: str, *, extra_axes=(), total: int | None = None
) -> jax.Array:
    """This shard's MEAN gradient chunk: one ``psum_scatter`` over
    ``axis_name`` (half an allreduce's wire bytes) plus — when the plan
    carries more data-parallel axes — one ``psum`` of the 1/n chunk over
    ``extra_axes``. ``total`` is the full data-parallel degree the mean
    divides by (defaults to the product of the named axes). Call inside
    ``shard_map``."""
    n = lax.axis_size(axis_name)
    rows = _chunk_rows(g, n)
    part = lax.psum_scatter(rows, axis_name, scatter_dimension=0, tiled=False)
    if extra_axes:
        part = lax.psum(part, tuple(extra_axes))
    if total is None:
        total = n
        for a in extra_axes:
            total = total * lax.axis_size(a)
    return (part / total).astype(g.dtype)


def zero_param_chunk(p: jax.Array, axis_name: str) -> jax.Array:
    """This shard's 1/n chunk of a replicated parameter (the slice the
    sharded update owns). Call inside ``shard_map``."""
    n = lax.axis_size(axis_name)
    return lax.dynamic_index_in_dim(
        _chunk_rows(p, n), lax.axis_index(axis_name), keepdims=False
    )


def zero_gather_updates(u_chunk: jax.Array, like: jax.Array,
                        axis_name: str) -> jax.Array:
    """All-gather the per-shard update chunks back to ``like``'s full
    shape — the other half of the allreduce the scatter replaced. Call
    inside ``shard_map``."""
    rows = lax.all_gather(u_chunk, axis_name, axis=0, tiled=False)
    return _unchunk(rows, like.shape, like.dtype)


def zero_shard_optimizer(
    inner: optax.GradientTransformation,
    axis_name: str,
    *,
    compress_dtype=None,
) -> optax.GradientTransformation:
    """Wrap an elementwise optax transform with ZeRO-1 state sharding over
    ``axis_name``. Must be used inside that named-axis context (shard_map).

    ``compress_dtype`` casts gradients before the reduce-scatter (the
    bf16-compressed-allreduce feature, applied to the scatter instead).
    """

    names = _names(axis_name)

    def my_chunk(tree: PyTree) -> PyTree:
        idx = _group_index(names)
        n = _group_size(names)
        return jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(
                _chunk_rows(x, n), idx, keepdims=False
            ),
            tree,
        )

    def init_fn(params: PyTree):
        return inner.init(my_chunk(params))

    def _scatter(rows):
        # [n_total, c] -> this shard's [c] chunk-sum: one psum_scatter
        # per axis (rows viewed [n_a, n_b, ..., c]; each stage scatters
        # its leading axis) — a flattened multi-axis reduce-scatter.
        dims = tuple(lax.axis_size(a) for a in names)
        rows = rows.reshape(dims + rows.shape[1:])
        for a in names:
            rows = lax.psum_scatter(
                rows, a, scatter_dimension=0, tiled=False
            )
        return rows

    def update_fn(grads: PyTree, state, params: Optional[PyTree] = None):
        n = _group_size(names)

        def rs(g):
            rows = _chunk_rows(g, n)
            if compress_dtype is not None and jnp.issubdtype(
                g.dtype, jnp.floating
            ):
                return (_scatter(rows.astype(compress_dtype))
                        .astype(g.dtype) / n)
            return _scatter(rows) / n

        grad_chunks = jax.tree.map(rs, grads)
        param_chunks = my_chunk(params) if params is not None else None
        update_chunks, state = inner.update(grad_chunks, state, param_chunks)

        def ag(u, g):
            rows = u
            for a in reversed(names):
                rows = lax.all_gather(rows, a, axis=0, tiled=False)
            return _unchunk(rows, g.shape, g.dtype)

        updates = jax.tree.map(ag, update_chunks, grads)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)
