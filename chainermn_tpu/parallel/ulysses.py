"""Ulysses (DeepSpeed-style) sequence parallelism: all_to_all head↔sequence
reshard.

NEW capability relative to the reference (SURVEY.md section 5). Where ring
attention streams K/V around the ring, Ulysses *re-shards*: inputs arrive
sequence-sharded, one ``all_to_all`` turns them head-sharded with the full
sequence locally, plain (flash/blockwise) attention runs per-head, and a
second ``all_to_all`` restores sequence sharding. Two collectives total —
cheaper than the ring when heads >= axis size and the full sequence fits.

Constraint: ``num_heads`` must be divisible by the axis size (heads are the
resharding currency).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.ops.attention import blockwise_attention
from chainermn_tpu.ops.flash_attention import flash_attention


def check_ulysses_divisibility(q_heads: int, kv_heads: int, n: int,
                               *, axis_name: str = "seq") -> None:
    """Reject head counts Ulysses cannot reshard, naming BOTH numbers.

    Heads are the resharding currency: the two ``all_to_all``s split the
    head dim ``n`` ways, so ``q_heads % n`` and ``kv_heads % n`` must
    both be 0. Raised at ENTRY (``make_ulysses_attention``'s returned fn
    and the plan's ``seq_attn_impl`` resolver call this before any
    ``shard_map`` trace) so the caller sees the arithmetic, not a shape
    error from inside the collective (ISSUE 13 satellite — previously
    the check only fired mid-trace and had to be caught by the caller).
    """
    for name, h in (("q", int(q_heads)), ("kv", int(kv_heads))):
        if h % n != 0:
            raise ValueError(
                f"ulysses: {name} heads {h} not divisible by axis "
                f"{axis_name!r} size {n} — pad the head count, shrink "
                f"the seq axis, or use the ring provider (seq_attn_impl="
                f"'ring'), which has no divisibility constraint"
            )


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn: Optional[Callable] = None,
    impl: str = "flash",
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ulysses attention over local shards — call INSIDE ``shard_map``.

    Args:
      q/k/v: local sequence shards ``[B, T_local, H, D]``; global heads H
        must be divisible by the axis size. K/V may carry fewer heads
        (GQA/MQA) — they too must be divisible by the axis size.
      attn_fn: local attention ``fn(q, k, v, causal=..., scale=...)`` on
        ``[B, T, H_local, D]``; overrides ``impl`` when given.
      impl: ``'flash'`` — the Pallas kernel (fwd+bwd; the production path,
        same kernels as ring attention) — or ``'blockwise'`` (lax scan
        reference). ``interpret`` as in
        :func:`chainermn_tpu.parallel.ring_attention.ring_attention_local`.
      segment_ids: optional local ``[B, T_local]`` packed-segment slice;
        all-gathered (ids only — tiny) so the head-sharded full-sequence
        attention sees the whole mask. Requires ``impl='flash'`` or a
        segment-capable ``attn_fn``.
      window: causal sliding-window width, handed to the flash kernel
        (banded grids — heads are sharded here, so each device runs the
        full-sequence window band over its own heads). Requires
        ``causal=True`` and ``impl='flash'``.

    Returns:
      Local output shard ``[B, T_local, H, D]``.
    """
    n = lax.axis_size(axis_name)
    check_ulysses_divisibility(q.shape[2], k.shape[2], n,
                               axis_name=axis_name)
    if window is not None and (impl != "flash" or attn_fn is not None):
        raise ValueError(
            "window is implemented by the flash kernel — use impl='flash' "
            "without a custom attn_fn (or honour the window inside your "
            "attn_fn yourself)"
        )
    if attn_fn is None:
        if impl == "flash":
            def attn_fn(q, k, v, *, causal, scale, **kw):
                return flash_attention(
                    q, k, v, causal=causal, scale=scale, interpret=interpret,
                    window=window, **kw,
                )
        elif impl == "blockwise":
            if segment_ids is not None:
                raise ValueError(
                    "segment_ids requires impl='flash' (or a "
                    "segment-capable attn_fn)"
                )
            attn_fn = blockwise_attention
        else:
            raise ValueError(
                f"impl must be 'flash' or 'blockwise', got {impl!r}"
            )

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    kw = {}
    if segment_ids is not None:
        kw["segment_ids"] = lax.all_gather(
            segment_ids, axis_name, axis=1, tiled=True
        )
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attn_fn(qh, kh, vh, causal=causal, scale=scale, **kw)
    return heads_to_seq(out)


def make_ulysses_attention(
    mesh: Mesh,
    axis_name: str = "seq",
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn: Optional[Callable] = None,
    batch_axis: Optional[str] = None,
    impl: str = "flash",
    with_segments: bool = False,
    window: Optional[int] = None,
):
    """Jitted Ulysses attention over globally sequence-sharded BTHD arrays
    (counterpart of :func:`chainermn_tpu.parallel.make_ring_attention`).
    With ``with_segments`` the returned fn takes ``(q, k, v, segment_ids)``."""
    from jax import shard_map

    spec = P(batch_axis, axis_name, None, None)
    seg_spec = P(batch_axis, axis_name)
    interpret = mesh.devices.flat[0].platform != "tpu"
    n = mesh.shape[axis_name]

    def local(q, k, v, seg=None):
        return ulysses_attention_local(
            q, k, v, axis_name, causal=causal, scale=scale, attn_fn=attn_fn,
            impl=impl, segment_ids=seg, window=window, interpret=interpret,
        )

    in_specs = (spec, spec, spec) + ((seg_spec,) if with_segments else ())
    fn = shard_map(
        local, mesh=mesh, in_specs=in_specs, out_specs=spec,
        check_vma=False,
    )
    jitted = jax.jit(fn)

    def checked(q, k, v, *rest):
        # Divisibility rejected at ENTRY, with global head counts —
        # not from inside the shard_map trace.
        check_ulysses_divisibility(q.shape[2], k.shape[2], n,
                                   axis_name=axis_name)
        return jitted(q, k, v, *rest)

    return checked
